"""Fleet-level observability: merge N replicas' traces + metrics into
one report, and tripwire the stitching.

Per-replica observability (tools/obs_report.py) explains one process.
A fleet request crosses processes — a forwarded fold, a raw job routed
by feature key, a peer-cache fetch, a transport-death failover — and
with ISSUE 15's cross-process trace propagation every hop's record
shares ONE trace id plus a `parent_span_id` naming the exact sender
span it hangs under. This tool merges the fleet's evidence and answers
the fleet-level questions:

- the K slowest STITCHED traces as cross-replica waterfalls: the root
  record's spans, with each child replica's segment anchored at the
  parent's rpc (or peer_fetch) span — child offsets stay relative to
  their own process's monotonic clock and are re-based onto the
  parent's span start, so wall clocks are never compared across hosts
  (monotonic clocks don't agree between machines; the parent's rpc
  span brackets the child by construction);
- per-replica vs fleet tail latency (grouped by each record's
  `origin`);
- the SLO attainment table: `slo_*` gauges parsed out of each
  replica's Prometheus exposition (`GET /metrics` scrape files), plus
  fleet-merged per-bucket latency histograms (the fixed exponential
  buckets merge bucket-for-bucket across processes);
- the CONTROLLER section (ISSUE 16): `*decisions.jsonl` records from
  `FleetController` render as why-the-fleet-scaled — every non-hold
  action with its recorded reason, membership churn (joined / left /
  TTL-swept), rollout convergence verdicts, stale scrapes refused,
  warm submissions;
- `--check`: exit 1 on a BROKEN STITCH — a hop that armed stitching
  (an rpc span carrying a `span_id` that completed `outcome="ok"`, or
  a peer_fetch hit) with no child record continuing that span — on a
  failover span left open (an `rpc`/`forward` span auto-closed at
  finish instead of explicitly ended with an outcome: the ISSUE-15
  orphan bug), on an IDENTITY violation (an exposition whose
  `fleet_replica_identity` doesn't pin exactly one live
  (replica_id, model_tag, incarnation) series at 1, or one replica_id
  scraped under two different incarnations — the stale-scrape hazard
  a controller must never act on), and on every per-replica violation
  obs_report --check would flag (schema, orphan spans, STAGE_ORDER
  drift, prom parse).

Inputs are files or directories: directories are scanned recursively
for `*.jsonl` trace files and `*.prom` exposition files — point it at
a `ProcFleet` run dir (each replica's `<rid>/traces.jsonl`) and the
`--obs-fleet-out` scrape dir, or pass one pre-merged trace file.
`keys.jsonl` (scheduler key-frequency telemetry) and `*decisions.jsonl`
(controller decisions) are routed to their own parsers, never the
trace parser. `--scrape URL,...` additionally pulls live
`<url>/metrics` endpoints.

  python tools/obs_fleet.py /tmp/procfleet_run --check
  python tools/obs_fleet.py merged.jsonl --prom-dir scrapes/ --top 5
  python tools/obs_fleet.py run/ --scrape http://127.0.0.1:8701 --json
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from alphafold2_tpu.utils.profiling import percentile  # noqa: E402


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


obs_report = _load_obs_report()

# hop arming rules: (span name, outcome attr values) whose presence of
# a span_id attr promises a child record in a fleet-wide trace set.
# rpc "ok": the owner answered a terminal result, so its tracer (the
# aggregator's input is the whole fleet's trace dirs) emitted the
# continued record. transport_death/poll_exhausted/cancelled hops make
# no such promise — the owner may have died before finishing anything.
_STITCH_SPAN_OUTCOMES = {"rpc": ("ok",)}
# peer_fetch is an EVENT on the client side (the span wraps it one
# level up in cache.store); a "hit" proves the serving peer answered
_STITCH_EVENT_OUTCOMES = {"peer_fetch": ("hit",)}


# -- input gathering -----------------------------------------------------


def _classify_jsonl(name: str) -> str:
    """Not every fleet JSONL is a trace file: the controller's decision
    log (`*decisions.jsonl`) and the scheduler's key-frequency records
    (`keys.jsonl`) live in the same run dir and would otherwise be fed
    to the trace parser as schema violations."""
    if name == "keys.jsonl" or name.endswith(".keys.jsonl"):
        return "keys"
    if name.endswith("decisions.jsonl"):
        return "decisions"
    return "trace"


def gather_paths(paths: List[str]
                 ) -> Tuple[List[str], List[str], List[str], List[str]]:
    """(trace_jsonl_files, prom_files, decision_files, key_files) from
    a mix of files and dirs."""
    traces, proms, decisions, keys = [], [], [], []
    by_kind = {"trace": traces, "decisions": decisions, "keys": keys}
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    full = os.path.join(root, f)
                    if f.endswith(".jsonl"):
                        by_kind[_classify_jsonl(f)].append(full)
                    elif f.endswith(".prom"):
                        proms.append(full)
        elif p.endswith(".prom"):
            proms.append(p)
        else:
            by_kind[_classify_jsonl(os.path.basename(p))].append(p)
    return traces, proms, decisions, keys


def load_all_traces(files: List[str]) -> Tuple[List[dict], List[str]]:
    """Merged, de-duplicated records. Duplicates happen by design: a
    ProcFleet run dir holds each replica's own JSONL and the driver
    may also have merged them into one file — feeding both must not
    double-count a record."""
    records, problems, seen = [], [], set()
    for path in files:
        try:
            recs, errors = obs_report.load_traces(path)
        except OSError as exc:
            problems.append(f"{path}: unreadable ({exc})")
            continue
        problems += [f"{path}: {e}" for e in errors]
        for rec in recs:
            key = (rec.get("trace_id"), rec.get("origin", ""),
                   rec.get("request_id"), rec.get("start_unix_s"),
                   rec.get("duration_s"))
            if key in seen:
                continue
            seen.add(key)
            records.append(rec)
    return records, problems


def scrape_metrics(urls: List[str], timeout_s: float = 5.0
                   ) -> Tuple[Dict[str, str], List[str]]:
    """GET <url>/metrics for each url; {url: text}, problems."""
    from urllib import request as urlrequest

    out, problems = {}, []
    for url in urls:
        target = url.rstrip("/") + "/metrics"
        try:
            with urlrequest.urlopen(target, timeout=timeout_s) as resp:
                out[url] = resp.read().decode("utf-8")
        except Exception as exc:
            problems.append(f"scrape {target}: {exc!r}")
    return out, problems


# -- Prometheus text parsing ---------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str, List[Tuple[dict, float]]]:
    """{metric_name: [(labels, value), ...]} — enough structure to read
    gauges back and merge histogram `_bucket` series; not a full
    client. Unparseable values are skipped (the exposition is already
    format-validated by obs_report.check_prometheus_text)."""
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {k: v.replace(r"\"", '"').replace(r"\\", "\\")
                  for k, v in _LABEL_RE.findall(m.group("labels") or "")}
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


def slo_gauge_table(prom_by_source: Dict[str, str]) -> dict:
    """{objective: {source: {gauge_suffix: value}}} over every slo_*
    gauge in every exposition — the per-replica SLO attainment table."""
    table: dict = {}
    for source, text in prom_by_source.items():
        parsed = parse_prometheus(text)
        for name, samples in parsed.items():
            if not name.startswith("slo_"):
                continue
            for labels, value in samples:
                objective = labels.get("objective", "?")
                table.setdefault(objective, {}).setdefault(
                    source, {})[name[len("slo_"):]] = value
    return table


def merged_latency_histogram(prom_by_source: Dict[str, str]) -> dict:
    """Fleet-merged `serve_request_latency_seconds` buckets: the fixed
    exponential edges merge bucket-for-bucket across processes.
    {bucket_len: {"count": n, "buckets": {le: cum}}}."""
    merged: dict = {}
    for text in prom_by_source.values():
        parsed = parse_prometheus(text)
        for labels, value in parsed.get(
                "serve_request_latency_seconds_bucket", []):
            bucket_len = labels.get("bucket_len", "?")
            le = labels.get("le", "+Inf")
            slot = merged.setdefault(bucket_len,
                                     {"count": 0, "buckets": {}})
            slot["buckets"][le] = slot["buckets"].get(le, 0) + value
        for labels, value in parsed.get(
                "serve_request_latency_seconds_count", []):
            bucket_len = labels.get("bucket_len", "?")
            slot = merged.setdefault(bucket_len,
                                     {"count": 0, "buckets": {}})
            slot["count"] += value
    return merged


# -- controller decisions ------------------------------------------------


def load_decisions(files: List[str]) -> Tuple[List[dict], List[str]]:
    """Controller decision JSONL records (controlplane.FleetController
    `_log` output), merged in file order; torn lines are problems."""
    records, problems = [], []
    for path in files:
        try:
            with open(path) as fh:
                for lineno, line in enumerate(fh, 1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        problems.append(
                            f"{path}:{lineno}: torn decision record")
                        continue
                    if not isinstance(rec, dict) or "event" not in rec:
                        problems.append(
                            f"{path}:{lineno}: decision record without "
                            f"an event field")
                        continue
                    records.append(rec)
        except OSError as exc:
            problems.append(f"{path}: unreadable ({exc})")
    return records, problems


def controller_summary(decisions: List[dict]) -> dict:
    """Why the fleet scaled, from the decision log: reconcile count,
    every non-hold action with its recorded reason, membership churn,
    rollout verdicts, warming volume."""
    recs = [d for d in decisions if d.get("event") == "reconcile"]
    actions = []
    for d in recs:
        for act in d.get("actions", ()):
            actions.append({"reconcile": d.get("reconcile"),
                            "verb": act.get("verb"),
                            "replica": act.get("replica"),
                            "error": act.get("error"),
                            "reason": (d.get("decision") or {}
                                       ).get("reason", "")})
    replicas_over_time = [
        {"reconcile": d.get("reconcile"),
         "healthy": d.get("healthy"),
         "endpoints": len(d.get("endpoints", ()))}
        for d in recs]
    return {
        "reconciles": len(recs),
        "errors": sum(1 for d in decisions
                      if d.get("event") == "reconcile_error"),
        "actions": actions,
        "joined": sorted({r for d in recs for r in d.get("joined", ())}),
        "left": sorted({r for d in recs for r in d.get("left", ())}),
        "swept": sorted({r for d in recs for r in d.get("swept", ())}),
        "stale_scrapes": sum(int(d.get("stale_scrapes", 0))
                             for d in recs),
        "warm_submissions": sum(int(d.get("warm_submissions", 0))
                                for d in recs),
        "resizes": sum(len(d.get("resized", {})) for d in recs),
        "rollouts": [{"tag": d.get("tag"),
                      "converged": d.get("converged"),
                      "stragglers": d.get("stragglers")}
                     for d in decisions if d.get("event") == "rollout"],
        "replicas_over_time": replicas_over_time,
    }


# -- identity consistency ------------------------------------------------


def check_identity(prom_by_source: Dict[str, str]) -> List[str]:
    """The stale-scrape tripwire (ISSUE 16 satellite): each exposition
    that exports `fleet_replica_identity` must pin EXACTLY ONE
    (replica_id, model_tag, incarnation) series at value 1 — that's the
    contract controlplane.parse_identity relies on to refuse acting on
    a mismatched scrape. Across the merged set, one replica_id showing
    two different incarnations means the input mixes scrapes of two
    lives of the same replica — a controller fed this set could act on
    the dead incarnation's numbers."""
    problems = []
    active: Dict[str, Dict[str, str]] = {}   # replica_id -> {inc: src}
    for source, text in sorted(prom_by_source.items()):
        samples = parse_prometheus(text).get("fleet_replica_identity")
        if samples is None:
            continue           # pre-fleet exposition: nothing to pin
        ones = [labels for labels, value in samples if value == 1.0]
        if len(ones) != 1:
            problems.append(
                f"{source}: fleet_replica_identity has {len(ones)} "
                f"series at value 1 (want exactly 1) — the scrape "
                f"does not name a single live incarnation")
            continue
        labels = ones[0]
        rid = labels.get("replica_id", "?")
        inc = labels.get("incarnation", "?")
        prev = active.setdefault(rid, {})
        if inc not in prev and prev:
            others = ", ".join(
                f"{i} ({src})" for i, src in sorted(prev.items()))
            problems.append(
                f"{source}: replica_id {rid!r} incarnation {inc!r} "
                f"conflicts with {others} — the input mixes scrapes "
                f"from different lives of the same replica (stale "
                f"scrape hazard)")
        prev.setdefault(inc, os.path.basename(str(source)))
    return problems


# -- stitching -----------------------------------------------------------


def _armed_hops(rec: dict) -> List[dict]:
    """Every hop in `rec` that promised a child record: spans/events
    carrying a span_id whose outcome is in the arming table. Each hop:
    {span_id, kind, name, outcome, anchor_start_s}."""
    hops = []
    for span in rec.get("spans", ()):
        attrs = span.get("attrs") or {}
        sid = attrs.get("span_id")
        outcomes = _STITCH_SPAN_OUTCOMES.get(span.get("name"))
        if sid and outcomes and attrs.get("outcome") in outcomes:
            hops.append({"span_id": str(sid), "kind": "span",
                         "name": span.get("name"),
                         "outcome": attrs.get("outcome"),
                         "anchor_start_s": float(
                             span.get("start_s", 0.0))})
    for ev in rec.get("events", ()):
        attrs = ev.get("attrs") or {}
        sid = attrs.get("span_id")
        outcomes = _STITCH_EVENT_OUTCOMES.get(ev.get("name"))
        if sid and outcomes and attrs.get("outcome") in outcomes:
            hops.append({"span_id": str(sid), "kind": "event",
                         "name": ev.get("name"),
                         "outcome": attrs.get("outcome"),
                         "anchor_start_s": float(ev.get("at_s", 0.0))})
    return hops


def _anchor_for(rec: dict, span_id: str) -> float:
    """Offset (in `rec`'s own timeline) a child continuing `span_id`
    anchors at: the tagged span's start when present, else the tagged
    event's time, else 0 — never a cross-host wall-clock delta."""
    for span in rec.get("spans", ()):
        if (span.get("attrs") or {}).get("span_id") == span_id:
            return float(span.get("start_s", 0.0))
    for ev in rec.get("events", ()):
        if (ev.get("attrs") or {}).get("span_id") == span_id:
            return float(ev.get("at_s", 0.0))
    return 0.0


class StitchedTrace:
    """One trace id's records assembled into a parent→children tree.

    Hop edges are keyed by (sender origin, span id), not span id
    alone: each process's continued trace mints its own s0, s1, ...
    sequence, so a 3-hop trace (driver → r0 → r1) holds two distinct
    "s0" spans — the child record's `parent_origin` names whose s0 it
    continues."""

    def __init__(self, trace_id: str, records: List[dict]):
        self.trace_id = trace_id
        self.records = records
        by_parent: Dict[tuple, List[dict]] = {}
        hop_keys = set()
        for rec in records:
            origin = str(rec.get("origin", ""))
            for span in rec.get("spans", ()):
                sid = (span.get("attrs") or {}).get("span_id")
                if sid:
                    hop_keys.add((origin, str(sid)))
            for ev in rec.get("events", ()):
                sid = (ev.get("attrs") or {}).get("span_id")
                if sid:
                    hop_keys.add((origin, str(sid)))
        self.roots, self.unanchored = [], []
        for rec in records:
            parent = rec.get("parent_span_id")
            key = (str(rec.get("parent_origin", "")), str(parent))
            if not parent:
                self.roots.append(rec)
            elif key in hop_keys:
                by_parent.setdefault(key, []).append(rec)
            else:
                # child continuing a span nobody in the set recorded —
                # its sender's trace file is missing (or torn by a
                # kill -9 before the parent finished)
                self.unanchored.append(rec)
        self.children_of = by_parent

    @property
    def hops(self) -> int:
        return len(self.records)

    @property
    def origins(self) -> List[str]:
        return sorted({rec.get("origin", "?") for rec in self.records})

    @property
    def duration_s(self) -> float:
        if self.roots:
            return max(float(r.get("duration_s", 0.0))
                       for r in self.roots)
        return max((float(r.get("duration_s", 0.0))
                    for r in self.records), default=0.0)


def stitch(records: List[dict]) -> Dict[str, StitchedTrace]:
    by_trace: Dict[str, List[dict]] = {}
    for rec in records:
        by_trace.setdefault(str(rec.get("trace_id", "?")),
                            []).append(rec)
    return {tid: StitchedTrace(tid, recs)
            for tid, recs in by_trace.items()}


def check_stitches(stitched: Dict[str, StitchedTrace]) -> List[str]:
    """The fleet tripwire: every armed hop has its child; every
    rpc/forward span was explicitly closed (an auto_closed one is the
    dangling-failover-span bug the transports exist to prevent)."""
    problems = []
    for tid, st in stitched.items():
        child_parents = {(str(rec.get("parent_origin", "")),
                          str(rec.get("parent_span_id")))
                         for rec in st.records
                         if rec.get("parent_span_id")}
        for rec in st.records:
            origin = str(rec.get("origin", ""))
            where = (f"trace {tid} "
                     f"(origin {rec.get('origin', '?')}, "
                     f"request {rec.get('request_id', '?')})")
            for hop in _armed_hops(rec):
                if (origin, hop["span_id"]) not in child_parents:
                    problems.append(
                        f"{where}: BROKEN STITCH — {hop['name']} hop "
                        f"{hop['span_id']} completed "
                        f"outcome={hop['outcome']!r} but no record "
                        f"continues it (the receiver's segments don't "
                        f"share the trace)")
            for span in rec.get("spans", ()):
                attrs = span.get("attrs") or {}
                if span.get("name") in ("rpc", "forward") \
                        and attrs.get("auto_closed"):
                    problems.append(
                        f"{where}: {span['name']} span left open "
                        f"(auto-closed at finish — a dead-owner "
                        f"exchange must be explicitly ended with an "
                        f"outcome before failover re-submits)")
        # NOTE: unanchored children (a record continuing a span no
        # merged record contains) are deliberately NOT check failures:
        # a kill -9 tears exactly this way — the victim's in-flight
        # forward completes on the owner (child record emitted) while
        # the victim's own trace never reached finish(). The chaos the
        # fleet exists to survive must not fail its own tripwire; they
        # surface as warnings + a summary count instead.
    return problems


def unanchored_warnings(stitched: Dict[str, StitchedTrace]) -> List[str]:
    out = []
    for tid, st in stitched.items():
        for rec in st.unanchored:
            out.append(
                f"trace {tid}: record from "
                f"origin {rec.get('origin', '?')} continues span "
                f"{rec.get('parent_span_id')!r} that no merged record "
                f"contains (sender's trace torn — kill -9 / timeout — "
                f"or its file missing from the input set)")
    return out


# -- views ---------------------------------------------------------------


def per_origin_latency(records: List[dict]) -> dict:
    by_origin: Dict[str, List[float]] = {}
    alldurs: List[float] = []
    for rec in records:
        d = float(rec.get("duration_s", 0.0))
        by_origin.setdefault(rec.get("origin", "?"), []).append(d)
        alldurs.append(d)
    out = {origin: {"traces": len(durs),
                    "p50_s": percentile(durs, 50),
                    "p99_s": percentile(durs, 99)}
           for origin, durs in sorted(by_origin.items())}
    out["__fleet__"] = {"traces": len(alldurs),
                        "p50_s": percentile(alldurs, 50),
                        "p99_s": percentile(alldurs, 99)}
    return out


def render_stitched(st: StitchedTrace, indent: str = "") -> List[str]:
    """Cross-replica waterfall for one stitched trace: each record's
    spans at its own offsets; child records indented under the hop
    span they continue, their offsets re-based onto the parent's
    anchor (anchor + child offset) — a display convention, not a
    clock-sync claim."""
    lines = []

    def _render_record(rec, base_s, depth):
        pad = indent + "    " * depth
        origin = str(rec.get("origin", ""))
        head = (f"{pad}[{rec.get('origin', '?')}] "
                f"{rec.get('request_id', '?')} "
                f"{rec.get('status')}/{rec.get('source')} "
                f"dur={float(rec.get('duration_s', 0.0)):.4f}s")
        lines.append(head)
        for span in rec.get("spans", ()):
            t0 = base_s + float(span.get("start_s", 0.0))
            lines.append(
                f"{pad}  {t0:9.4f}s +{float(span.get('dur_s', 0.0)):.4f}s"
                f"  {span.get('name')}")
        sids = [str(sid) for sid in
                [(s.get("attrs") or {}).get("span_id")
                 for s in rec.get("spans", ())]
                + [(e.get("attrs") or {}).get("span_id")
                   for e in rec.get("events", ())]
                if sid]
        for sid in sids:
            for child in st.children_of.get((origin, sid), ()):
                _render_record(child,
                               base_s + _anchor_for(rec, sid),
                               depth + 1)

    lines.append(f"{indent}== trace {st.trace_id}: {st.hops} hop(s) "
                 f"across {st.origins}, {st.duration_s:.4f}s ==")
    for root in (st.roots or st.records[:1]):
        _render_record(root, 0.0, 0)
    return lines


def summarize(stitched: Dict[str, StitchedTrace],
              records: List[dict]) -> dict:
    multi = [st for st in stitched.values() if st.hops > 1]
    return {
        "records": len(records),
        "traces": len(stitched),
        "stitched_traces": len(multi),
        "max_hops": max((st.hops for st in stitched.values()),
                        default=0),
        "unanchored_records": sum(len(st.unanchored)
                                  for st in stitched.values()),
        "origins": sorted({rec.get("origin", "?") for rec in records}),
    }


# -- main ----------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+",
                    help="trace JSONL files, .prom files, or dirs "
                         "(scanned recursively; e.g. a ProcFleet "
                         "run dir)")
    ap.add_argument("--prom-dir", default="",
                    help="additional dir of .prom exposition files")
    ap.add_argument("--scrape", default="",
                    help="comma-separated replica base URLs to pull "
                         "live <url>/metrics from")
    ap.add_argument("--top", type=int, default=3,
                    help="slowest stitched traces to render")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on broken stitches, open failover "
                         "spans, or any per-replica obs violation")
    ap.add_argument("--json", action="store_true",
                    help="one JSON summary line instead of the human "
                         "report")
    ap.add_argument("--since", type=float, default=None, metavar="TS",
                    help="only consider controller decision records "
                         "with ts >= TS (unix seconds) — pairs with "
                         "the controller's decision-log retention so "
                         "a long-lived fleet's report reads one "
                         "window, not the whole history")
    args = ap.parse_args(argv)

    trace_files, prom_files, decision_files, _keys = gather_paths(
        args.paths)
    if args.prom_dir:
        _t, extra, _d, _k = gather_paths([args.prom_dir])
        prom_files += extra
    records, problems = load_all_traces(trace_files)
    if not records:
        problems.append(f"no trace records under {args.paths}")
    decisions, decision_problems = load_decisions(decision_files)
    problems += decision_problems
    if args.since is not None:
        decisions = [d for d in decisions
                     if float(d.get("ts", 0)) >= args.since]

    prom_by_source: Dict[str, str] = {}
    for path in prom_files:
        try:
            with open(path) as fh:
                prom_by_source[path] = fh.read()
        except OSError as exc:
            problems.append(f"{path}: unreadable ({exc})")
    if args.scrape:
        scraped, scrape_problems = scrape_metrics(
            [u for u in args.scrape.split(",") if u])
        prom_by_source.update(scraped)
        problems += scrape_problems

    # the per-replica rules still apply to the merged set: schema,
    # orphan spans, STAGE_ORDER drift, and each exposition must parse
    problems += obs_report.check_traces(records)
    problems += obs_report.check_stage_order(records)
    for source, text in prom_by_source.items():
        problems += [f"{source}: {p}"
                     for p in obs_report.check_prometheus_text(text)]
    problems += check_identity(prom_by_source)

    stitched = stitch(records)
    stitch_problems = check_stitches(stitched)
    problems += stitch_problems
    warnings = unanchored_warnings(stitched)

    summary = summarize(stitched, records)
    latency = per_origin_latency(records)
    slo_table = slo_gauge_table(prom_by_source)
    merged_hist = merged_latency_histogram(prom_by_source)
    slowest = sorted((st for st in stitched.values() if st.hops > 1),
                     key=lambda st: -st.duration_s)[:args.top]

    ctrl = controller_summary(decisions) if decisions else None

    if args.json:
        out = dict(summary)
        out["latency_by_origin"] = latency
        out["slo"] = slo_table
        out["merged_latency_buckets"] = merged_hist
        out["broken_stitches"] = len(stitch_problems)
        if ctrl is not None:
            out["controller"] = ctrl
        out["warnings"] = warnings[:20]
        out["problems"] = problems[:20]
        print(json.dumps(out))
    else:
        print(f"== fleet: {summary['records']} records, "
              f"{summary['traces']} traces "
              f"({summary['stitched_traces']} stitched, max "
              f"{summary['max_hops']} hops) from origins "
              f"{summary['origins']} ==")
        print("\n-- latency by origin --")
        for origin, s in latency.items():
            print(f"  {origin:>12}  {s['traces']:>6} traces  "
                  f"p50 {s['p50_s']:.4f}s  p99 {s['p99_s']:.4f}s")
        if slo_table:
            print("\n-- SLO attainment (slo_* gauges per source) --")
            for objective, by_source in sorted(slo_table.items()):
                for source, gauges in sorted(by_source.items()):
                    rendered = "  ".join(
                        f"{k}={v:.3f}" for k, v in sorted(gauges.items()))
                    print(f"  {objective:>12}  "
                          f"{os.path.basename(str(source)):>16}  "
                          f"{rendered}")
        if merged_hist:
            print("\n-- fleet-merged latency buckets (requests) --")
            for bucket_len, slot in sorted(merged_hist.items()):
                print(f"  bucket {bucket_len}: "
                      f"{int(slot['count'])} served")
        if ctrl is not None:
            print(f"\n-- controller: {ctrl['reconciles']} reconciles, "
                  f"{len(ctrl['actions'])} actions, "
                  f"{ctrl['stale_scrapes']} stale scrapes refused, "
                  f"{ctrl['warm_submissions']} warm submissions, "
                  f"{ctrl['resizes']} pool resizes --")
            for act in ctrl["actions"][:20]:
                what = act["replica"] or act["error"] or "?"
                print(f"  reconcile {act['reconcile']}: "
                      f"{act['verb']} {what}  ({act['reason']})")
            for ro in ctrl["rollouts"]:
                print(f"  rollout tag={ro['tag']} "
                      f"converged={ro['converged']} "
                      f"stragglers={ro['stragglers']}")
            if ctrl["joined"] or ctrl["left"] or ctrl["swept"]:
                print(f"  membership: joined={ctrl['joined']} "
                      f"left={ctrl['left']} swept={ctrl['swept']}")
        print(f"\n-- top {args.top} slowest stitched traces --")
        if not slowest:
            print("(no multi-hop traces)")
        for st in slowest:
            print("\n".join(render_stitched(st)))
        if warnings:
            print(f"\n-- {len(warnings)} warnings (not check "
                  f"failures) --")
            for w in warnings[:20]:
                print(f"  {w}")
        if problems:
            print(f"\n-- {len(problems)} problems --")
            for p in problems[:20]:
                print(f"  {p}")

    if args.check and problems:
        print(f"OBS FLEET CHECK FAIL: {len(problems)} violations "
              f"({problems[0]})", file=sys.stderr)
        return 1
    if args.check:
        print(f"OBS FLEET CHECK OK: {summary['records']} records, "
              f"{summary['stitched_traces']} stitched traces, "
              f"0 broken stitches, all rpc/forward spans closed",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
