"""Minimal stub modules so the reference package imports in this container
(its heavy native deps — BioPython, sidechainnet, mp_nerf, pytorch3d,
invariant-point-attention — are not installed). Only what the reference's
module-level imports touch; enough to run the trunk benchmark."""
import sys, types
import torch

AA3 = {"A":"ALA","R":"ARG","N":"ASN","D":"ASP","C":"CYS","Q":"GLN","E":"GLU",
       "G":"GLY","H":"HIS","I":"ILE","L":"LEU","K":"LYS","M":"MET","F":"PHE",
       "P":"PRO","S":"SER","T":"THR","W":"TRP","Y":"TYR","V":"VAL"}
SC_ATOMS = {"ALA":["CB"],"ARG":["CB","CG","CD","NE","CZ","NH1","NH2"],
 "ASN":["CB","CG","OD1","ND2"],"ASP":["CB","CG","OD1","OD2"],
 "CYS":["CB","SG"],"GLN":["CB","CG","CD","OE1","NE2"],
 "GLU":["CB","CG","CD","OE1","OE2"],"GLY":[],
 "HIS":["CB","CG","ND1","CD2","CE1","NE2"],"ILE":["CB","CG1","CG2","CD1"],
 "LEU":["CB","CG","CD1","CD2"],"LYS":["CB","CG","CD","CE","NZ"],
 "MET":["CB","CG","SD","CE"],"PHE":["CB","CG","CD1","CD2","CE1","CE2","CZ"],
 "PRO":["CB","CG","CD"],"SER":["CB","OG"],"THR":["CB","OG1","CG2"],
 "TRP":["CB","CG","CD1","CD2","NE1","CE2","CE3","CZ2","CZ3","CH2"],
 "TYR":["CB","CG","CD1","CD2","CE1","CE2","CZ","OH"],
 "VAL":["CB","CG1","CG2"]}

def _mod(name):
    m = types.ModuleType(name); sys.modules[name] = m; return m

# Bio
bio = _mod("Bio"); bio.SeqIO = _mod("Bio.SeqIO")

# sidechainnet
scn = _mod("sidechainnet")
sequ = _mod("sidechainnet.utils"); _mod("sidechainnet.utils.sequence")
class ProteinVocabulary: pass
sys.modules["sidechainnet.utils.sequence"].ProteinVocabulary = ProteinVocabulary
sys.modules["sidechainnet.utils.sequence"].ONE_TO_THREE_LETTER_MAP = AA3
_mod("sidechainnet.utils.measure").GLOBAL_PAD_CHAR = 0
bi = _mod("sidechainnet.structure.build_info")
bi.NUM_COORDS_PER_RES = 14
bi.BB_BUILD_INFO = {"BONDLENS": {"n-ca": 1.442, "ca-c": 1.498, "c-n": 1.379, "c-o": 1.229}}
bi.SC_BUILD_INFO = {k: {"atom-names": v} for k, v in SC_ATOMS.items()}
_mod("sidechainnet.structure")
_mod("sidechainnet.structure.StructureBuilder")._get_residue_build_iter = lambda *a, **k: iter(())

# mp_nerf
mp = _mod("mp_nerf"); mp.proteins = _mod("mp_nerf.proteins")
_mod("mp_nerf.kb_proteins"); _mod("mp_nerf.utils")

# pytorch3d quaternion ops (pure torch)
p3d = _mod("pytorch3d"); tr = _mod("pytorch3d.transforms")
def quaternion_multiply(a, b):
    aw, ax, ay, az = a.unbind(-1); bw, bx, by, bz = b.unbind(-1)
    return torch.stack([aw*bw-ax*bx-ay*by-az*bz, aw*bx+ax*bw+ay*bz-az*by,
                        aw*by-ax*bz+ay*bw+az*bx, aw*bz+ax*by-ay*bx+az*bw], -1)
def quaternion_to_matrix(q):
    q = q / q.norm(dim=-1, keepdim=True)
    w, x, y, z = q.unbind(-1)
    return torch.stack([
        torch.stack([1-2*(y*y+z*z), 2*(x*y-z*w), 2*(x*z+y*w)], -1),
        torch.stack([2*(x*y+z*w), 1-2*(x*x+z*z), 2*(y*z-x*w)], -1),
        torch.stack([2*(x*z-y*w), 2*(y*z+x*w), 1-2*(x*x+y*y)], -1)], -2)
tr.quaternion_multiply = quaternion_multiply
tr.quaternion_to_matrix = quaternion_to_matrix
p3d.transforms = tr

# invariant_point_attention — not exercised by the trunk bench
ipa = _mod("invariant_point_attention")
class IPABlock(torch.nn.Module):
    def __init__(self, *a, **k):
        super().__init__()
        self.attn = types.SimpleNamespace(to_out=torch.nn.Linear(1, 1))
ipa.IPABlock = IPABlock
