"""Multi-config benchmark suite over the BASELINE.json configs.

`bench.py` covers the north-star metric (config-1-shaped train step at
256res). This tool fills the rest of the BASELINE table: one JSON line
per config with train-step ms and, where the config folds structures,
folds/hour/chip (inference with recycling).

Configs (BASELINE.md "Benchmark configs to measure"):
  1 distogram-only dim256/depth2 trunk, 128-res
  2 trRosetta-mode: predict_angles trunk with anglegram CE targets
    (the ESM seq-embed preprocessing is host-side and not timed here)
  3 EGNN structure module end-to-end, 64-res, backbone coords
  4 SE3-style refiner, refinement_iters=4, reversible trunk
  5 flagship: depth-48 trunk, 384-res, 3x recycling, pair-sharded mesh
  fold: folds/hour/chip at 256-res with 3 recycles (predict_coords IPA)

Usage:
  python tools/bench_suite.py [--configs 1,2,3,4,fold] [--iters 5]
                              [--tiny]   # smoke sizes for CPU checks

Runs on whatever platform jax selects (the real chip under the driver);
falls back to CPU with the same hardening as bench.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import (_enable_compile_cache, force_cpu_fallback,  # noqa: E402
                             jax_backends_initialized, tiny_op_probe)

if not jax_backends_initialized() and \
        os.environ.get("BENCH_NO_FALLBACK") != "1" and not tiny_op_probe():
    # same CPU recipe as bench.py's _cpu_env: f32 activations + AMX Dense
    # + the SHARED flag constant (one owner — a drifted copy here would
    # silently benchmark a different compiler configuration). All still
    # take effect after this point: XLA_FLAGS at backend init,
    # AF2_CPU_AMX/BENCH_DTYPE at trace time.
    from bench import _CPU_XLA_FLAGS
    os.environ.setdefault("BENCH_DTYPE", "float32")
    os.environ.setdefault("AF2_CPU_AMX", "1")
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " +
                               _CPU_XLA_FLAGS).strip()
    force_cpu_fallback("bench_suite: default platform unreachable")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

_enable_compile_cache()

# BENCH_DTYPE override, mirroring bench.py: the production dtype is bf16
# (TPU MXU); the CPU fallback recipe runs f32 + AMX (see bench.py's
# _CPU_XLA_FLAGS comment) — bf16 on XLA:CPU is emulated in f32 with
# rounding converts and the AMX router is f32-only.
_DTYPE = jnp.dtype(os.environ.get("BENCH_DTYPE", "bfloat16"))

from alphafold2_tpu import Alphafold2  # noqa: E402
from alphafold2_tpu.data.synthetic import synthetic_batch  # noqa: E402
from alphafold2_tpu.predict import fold  # noqa: E402
from alphafold2_tpu.train import TrainState, adam, make_train_step  # noqa: E402


def _train_step_ms(model, batch, iters, warmup=1):
    params = model.init(
        {"params": jax.random.PRNGKey(1), "mlm": jax.random.PRNGKey(2)},
        batch["seq"], msa=batch["msa"], mask=batch["mask"],
        msa_mask=batch["msa_mask"], train=True)
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=adam(3e-4), rng=jax.random.PRNGKey(3))
    step = jax.jit(make_train_step(model), donate_argnums=(0,))
    # device_get, not block_until_ready: under the axon tunnel the
    # latter was observed returning before device completion (r05)
    for _ in range(warmup):
        state, metrics = step(state, batch)
    float(jax.device_get(metrics["loss"]))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    float(jax.device_get(metrics["loss"]))
    return (time.perf_counter() - t0) / iters * 1e3


def config_1(tiny, iters):
    l = 32 if tiny else 128
    model = Alphafold2(dim=64 if tiny else 256, depth=2, heads=8,
                       dim_head=64, dtype=_DTYPE)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=l,
                            msa_depth=5, with_coords=True)
    return {"config": "1_distogram_128res",
            "train_step_ms": round(_train_step_ms(model, batch, iters), 2)}


def config_2(tiny, iters):
    l = 32 if tiny else 128
    dim = 64 if tiny else 256
    model = Alphafold2(dim=dim, depth=2, heads=8, dim_head=64,
                       predict_angles=True, dtype=_DTYPE)
    # with_angles: theta/phi/omega bucket targets so the anglegram CE
    # loss (and its backward) is actually part of the timed step
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=l,
                            msa_depth=5, with_coords=True,
                            with_angles=True)
    return {"config": "2_trrosetta_angles",
            "train_step_ms": round(_train_step_ms(model, batch, iters), 2)}


def config_3(tiny, iters):
    l = 16 if tiny else 64
    model = Alphafold2(dim=32 if tiny else 128, depth=2, heads=8,
                       dim_head=64, predict_coords=True,
                       structure_module_type="egnn",
                       structure_module_depth=2, dtype=_DTYPE)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=l,
                            msa_depth=5, with_coords=True)
    return {"config": "3_egnn_end2end_64res",
            "train_step_ms": round(_train_step_ms(model, batch, iters), 2)}


def config_4(tiny, iters):
    l = 16 if tiny else 64
    model = Alphafold2(dim=32 if tiny else 128, depth=2, heads=8,
                       dim_head=64, predict_coords=True,
                       structure_module_type="se3",
                       structure_module_depth=2,
                       structure_module_refinement_iters=4,
                       reversible=True, dtype=_DTYPE)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=l,
                            msa_depth=5, with_coords=True)
    return {"config": "4_se3_refine_reversible",
            "train_step_ms": round(_train_step_ms(model, batch, iters), 2)}


def config_fold(tiny, iters):
    l = 32 if tiny else 256
    model = Alphafold2(dim=64 if tiny else 256, depth=2, heads=8,
                       dim_head=64, predict_coords=True,
                       structure_module_depth=2, dtype=_DTYPE)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=l,
                            msa_depth=5, with_coords=False)
    params = model.init(jax.random.PRNGKey(1), batch["seq"],
                        msa=batch["msa"], mask=batch["mask"],
                        msa_mask=batch["msa_mask"])

    import functools
    run = jax.jit(functools.partial(fold, model,
                                    num_recycles=3))
    res = run(params, batch["seq"], msa=batch["msa"], mask=batch["mask"],
              msa_mask=batch["msa_mask"])
    jax.device_get(res.coords)
    t0 = time.perf_counter()
    for _ in range(iters):
        res = run(params, batch["seq"], msa=batch["msa"],
                  mask=batch["mask"], msa_mask=batch["msa_mask"])
    jax.device_get(res.coords)
    sec = (time.perf_counter() - t0) / iters
    return {"config": f"fold_{l}res_3recycles",
            "fold_seconds": round(sec, 4),
            "folds_per_hour_per_chip": round(3600.0 / sec, 1)}


def config_5(tiny, iters):
    """BASELINE config 5 — the flagship: depth-48 Evoformer, 384-res,
    3x recycling, pair representation sharded over the mesh's (i, j)
    axes when the platform offers >1 device (the v4-32 row of
    BASELINE.md, scaled to whatever is attached).

    Emits train-step time, AOT peak-memory analysis of the compiled
    step (pairs with tools/memory_probe.py's depth sweep), and the
    3-recycle fold time. On a 1-core CPU fallback the full-size step
    would run for hours, so timing is skipped there with a stated
    reason — the memory analysis (compile-only) still lands.
    """
    import contextlib

    from alphafold2_tpu.parallel import make_mesh, use_mesh

    l = 32 if tiny else 384
    depth = 4 if tiny else 48
    dim = 64 if tiny else 256
    model = Alphafold2(dim=dim, depth=depth, heads=8, dim_head=64,
                       predict_coords=True, structure_module_depth=2,
                       dtype=_DTYPE)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=l,
                            msa_depth=5, with_coords=True)

    ndev = len(jax.devices())
    mesh = None
    if ndev >= 4 and ndev % 2 == 0:
        mesh = make_mesh(1, 2, ndev // 2)   # (i=2, j=ndev/2) pair grid
    elif ndev == 2:
        mesh = make_mesh(1, 2, 1)
    ctx = use_mesh(mesh) if mesh is not None else contextlib.nullcontext()

    entry = {"config": f"5_flagship_depth{depth}_{l}res",
             "mesh": None if mesh is None else
             {k: int(v) for k, v in mesh.shape.items()}}
    with ctx:
        params = model.init(
            {"params": jax.random.PRNGKey(1), "mlm": jax.random.PRNGKey(2)},
            batch["seq"], msa=batch["msa"], mask=batch["mask"],
            msa_mask=batch["msa_mask"], train=True)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=adam(3e-4), rng=jax.random.PRNGKey(3))
        step = jax.jit(make_train_step(model), donate_argnums=(0,))
        compiled = step.lower(state, batch).compile()
        mem = compiled.memory_analysis()
        if mem is not None:
            for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                      "output_size_in_bytes"):
                v = getattr(mem, k, None)
                if v is not None:
                    entry[k.replace("_in_bytes", "_gb")] = round(
                        v / 2**30, 3)

        is_cpu = jax.default_backend() == "cpu"
        if tiny or not is_cpu:
            # time with the ALREADY-compiled step/state — a second init +
            # re-jit of the largest model in the suite would double its
            # dominant cost
            st = state
            for _ in range(1):
                st, metrics = step(st, batch)
            float(jax.device_get(metrics["loss"]))
            t0 = time.perf_counter()
            for _ in range(iters):
                st, metrics = step(st, batch)
            float(jax.device_get(metrics["loss"]))
            entry["train_step_ms"] = round(
                (time.perf_counter() - t0) / iters * 1e3, 2)

            import functools
            run = jax.jit(functools.partial(fold, model, num_recycles=3))
            # st.params, not params: the donated train step above consumed
            # the original param buffers
            fparams = st.params
            res = run(fparams, batch["seq"], msa=batch["msa"],
                      mask=batch["mask"], msa_mask=batch["msa_mask"])
            jax.device_get(res.coords if hasattr(res, "coords")
                           else res.distogram)
            t0 = time.perf_counter()
            for _ in range(max(1, iters // 2)):
                res = run(fparams, batch["seq"], msa=batch["msa"],
                          mask=batch["mask"], msa_mask=batch["msa_mask"])
            jax.device_get(res.coords if hasattr(res, "coords")
                           else res.distogram)
            entry["fold_3recycle_seconds"] = round(
                (time.perf_counter() - t0) / max(1, iters // 2), 3)
        else:
            entry["train_step_ms"] = None
            entry["skipped"] = ("full-size depth-48/384res step timing "
                                "skipped on the 1-core CPU fallback "
                                "(estimated hours/step); memory analysis "
                                "above is the compile-only artifact")
    return entry


CONFIGS = {"1": config_1, "2": config_2, "3": config_3, "4": config_4,
           "5": config_5, "fold": config_fold}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="1,2,3,4,5,fold")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--tiny", action="store_true")
    args = ap.parse_args()
    for key in args.configs.split(","):
        res = CONFIGS[key](args.tiny, args.iters)
        res["platform"] = jax.default_backend()
        print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
