#!/bin/sh
# Chunked full-suite runner: one pytest process per test file.
#
# Why: a monolithic 285-test process trips an XLA:CPU compiler segfault
# on the pipeline train-step compile after ~150 prior compilations
# (r05, jax 0.9; crash is in-process-state dependent — every file is
# green standalone). conftest.py also clears jax caches between modules,
# which mitigates the monolithic run; this runner is the isolation-
# guaranteed form. The persistent per-platform compile cache keeps the
# chunked wall time close to the monolithic one.
#
# Usage: sh tools/run_suite.sh [extra pytest args]
set -u
cd "$(dirname "$0")/.."
PY="${PYTHON:-/opt/venv/bin/python}"
[ -x "$PY" ] || PY=python
fail=0
for f in tests/test_*.py; do
  echo "== $f"
  env -u PYTHONPATH "$PY" -m pytest "$f" -q --no-header "$@" || fail=1
done
exit $fail
