"""Block-sparse kernel microbenchmark: Pallas block-skipping attention
(ops/block_sparse.py) vs the XLA dense+mask path, matched shapes/pattern.

SURVEY §7.7 keep-or-kill rule: a kernel must beat the XLA baseline on
hardware to be kept. This prints one JSON line per config:

  {"n": N, "block": B, "live_frac": f, "dense_ms": X, "sparse_ms": Y,
   "speedup": X/Y, "platform": ...}

Run on the TPU (`python tools/bench_blocksparse.py` from /root/repo with
the ambient axon platform). On CPU the Mosaic path cannot lower —
the script emits a labeled skip line instead of timing interpret mode
(which benchmarks nothing real).

Shapes mirror the Evoformer axial-attention layout after head folding
(B = batch*heads, N = crop length, D = head dim). Block sparsity pays
off at long N (ring/long-context regime): at N=1024, window=1,
num_global=1 the live fraction is ~0.3; at N=2048 ~0.16.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DONE = threading.Event()


def _watchdog(seconds: float):
    def waiter():
        if not _DONE.wait(seconds):
            print(json.dumps({"error": f"bench_blocksparse timed out "
                              f"after {seconds:.0f}s"}), flush=True)
            os._exit(2)
    threading.Thread(target=waiter, daemon=True).start()


def main():
    _watchdog(float(os.environ.get("BENCH_TIMEOUT_S", 900)))
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _enable_compile_cache, is_tpu_platform
    _enable_compile_cache()

    platform = jax.default_backend()
    on_tpu = is_tpu_platform(platform)
    if not on_tpu:
        print(json.dumps({
            "skipped": True, "platform": platform,
            "reason": "Mosaic lowering needs a TPU; interpret-mode timing "
                      "is not evidence (exactness is covered by "
                      "tests/test_ops.py)"}), flush=True)
        _DONE.set()
        return

    from alphafold2_tpu.model.attention_variants import (
        block_sparse_block_pattern)
    from alphafold2_tpu.ops.attention import MASK_VALUE
    from alphafold2_tpu.ops.block_sparse import block_sparse_attention

    B, D = int(os.environ.get("BSB_BATCH", 8)), 64
    block = int(os.environ.get("BSB_BLOCK", 128))
    iters = int(os.environ.get("BSB_ITERS", 20))

    for n in (512, 1024, 2048):
        nb = n // block
        pattern = block_sparse_block_pattern(nb, num_global=1, window=1)
        live_frac = float(pattern.mean())
        rng = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(rng, 3)
        q = jax.random.normal(kq, (B, n, D), jnp.bfloat16)
        k = jax.random.normal(kk, (B, n, D), jnp.bfloat16)
        v = jax.random.normal(kv, (B, n, D), jnp.bfloat16)

        import numpy as np
        tok = np.repeat(np.repeat(pattern, block, 0), block, 1)
        bias = jnp.where(jnp.asarray(tok), 0.0, MASK_VALUE)[None]
        bias = jnp.broadcast_to(bias, (B, n, n)).astype(jnp.float32)

        @jax.jit
        def dense(q, k, v, bias):
            logits = jnp.einsum("bnd,bmd->bnm", q.astype(jnp.float32),
                                k.astype(jnp.float32)) * (D ** -0.5)
            attn = jax.nn.softmax(logits + bias, axis=-1)
            return jnp.einsum("bnm,bmd->bnd", attn,
                              v.astype(jnp.float32)).astype(q.dtype)

        # pattern is STATIC (host-side plan); close it into the jitted fn
        # rather than passing it as a (traced) argument
        sparse = jax.jit(functools.partial(
            block_sparse_attention, pattern=pattern, block=block))

        def timeit(fn, *args):
            # Measurement discipline (r05, both lessons tunnel-taught):
            # (a) block_until_ready can return before device completion
            #     under axon — close the window with a device_get of a
            #     scalar reduction instead (a transfer cannot complete
            #     before the compute it depends on);
            # (b) per-call dispatch costs ~3.5 ms through the tunnel and
            #     swamps ms-scale kernels — run the whole window as ONE
            #     dispatch: a lax.scan of `iters` chained applications
            #     (output feeds back as q, serializing on-device).
            @jax.jit
            def window(q0, rest):
                def body(q, _):
                    return fn(q, *rest), None
                out, _ = jax.lax.scan(body, q0, None, length=iters)
                return jnp.sum(out.astype(jnp.float32))

            float(jax.device_get(window(args[0], args[1:])))  # warm
            t0 = time.perf_counter()
            s = window(args[0], args[1:])
            float(jax.device_get(s))
            return (time.perf_counter() - t0) / iters * 1e3

        dense_ms = timeit(dense, q, k, v, bias)
        sparse_ms = timeit(sparse, q, k, v)
        print(json.dumps({
            "n": n, "block": block, "batch": B, "dim_head": D,
            "live_frac": round(live_frac, 3),
            "dense_ms": round(dense_ms, 3),
            "sparse_ms": round(sparse_ms, 3),
            "speedup": round(dense_ms / sparse_ms, 3),
            "platform": platform}), flush=True)
    _DONE.set()


if __name__ == "__main__":
    main()
