"""Block-sparse kernel microbenchmark: Pallas block-skipping attention
(ops/block_sparse.py) vs the XLA dense+mask path, matched shapes/pattern.

SURVEY §7.7 keep-or-kill rule: a kernel must beat the XLA baseline on
hardware to be kept. This prints one JSON line per config:

  {"n": N, "block": B, "live_frac": f, "dense_ms": X, "sparse_ms": Y,
   "speedup": X/Y, "platform": ...}

Run on the TPU (`python tools/bench_blocksparse.py` from /root/repo with
the ambient axon platform). On CPU the Mosaic path cannot lower —
the script emits a labeled skip line instead of timing interpret mode
(which benchmarks nothing real).

Two pattern sources:

- default: the static banded+global pattern at each bucket edge
  (window=1, num_global=1 — the serving KernelPolicy's first-pass
  mask). Block sparsity pays off at long N: at N=1024 the live
  fraction is ~0.53, at N=2048 ~0.29.
- `--from-contacts FILE.npz` (ISSUE 12): replay SAVED pair activations
  — a `distogram` (b, n, n, buckets) logits array (save one from
  `predict.fold_init(...).distogram`) or a precomputed `contacts`
  (n, n) probability map — through the same
  `ops.block_sparse.contact_block_pattern` planner the serving
  scheduler uses, and bench the MEASURED live fraction per bucket
  edge. `--append tools/tpu_blocksparse.json` appends the results
  (tagged "source": "contacts") so the auto kernel policy's
  sparse-live-frac threshold is backed by live fractions real targets
  produce instead of guessed from the banded geometry.
  `--emit-synthetic FILE.npz` writes a plausible synthetic
  pair-activation file (banded backbone + off-diagonal domain
  contacts) for trying the flow without a TPU fold.

Shapes mirror the Evoformer axial-attention layout after head folding
(B = batch*heads, N = crop length, D = head dim).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DONE = threading.Event()


def _watchdog(seconds: float):
    def waiter():
        if not _DONE.wait(seconds):
            print(json.dumps({"error": f"bench_blocksparse timed out "
                              f"after {seconds:.0f}s"}), flush=True)
            os._exit(2)
    threading.Thread(target=waiter, daemon=True).start()


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--buckets", default="512,1024,2048",
                    help="comma-separated bucket edges (N) to bench")
    ap.add_argument("--block", type=int,
                    default=int(os.environ.get("BSB_BLOCK", 128)))
    ap.add_argument("--batch", type=int,
                    default=int(os.environ.get("BSB_BATCH", 8)))
    ap.add_argument("--iters", type=int,
                    default=int(os.environ.get("BSB_ITERS", 20)))
    ap.add_argument("--from-contacts", default="",
                    help="npz with 'distogram' (b,n,n,buckets) logits "
                         "or 'contacts' (n,n) probabilities: plan the "
                         "per-bucket pattern from it instead of the "
                         "static banded mask")
    ap.add_argument("--contact-cutoff", type=float, default=8.0,
                    help="contact distance (A) for P(d < cutoff)")
    ap.add_argument("--contact-threshold", type=float, default=0.5,
                    help="block live when max cell P(contact) >= this")
    ap.add_argument("--append", default="",
                    help="append result lines to this JSON array file "
                         "(e.g. tools/tpu_blocksparse.json)")
    ap.add_argument("--emit-synthetic", default="",
                    help="write a synthetic pair-activation npz here "
                         "and exit (demo/test input for "
                         "--from-contacts)")
    ap.add_argument("--emit-n", type=int, default=2048,
                    help="sequence length of --emit-synthetic")
    return ap.parse_args(argv)


def _synthetic_contacts(n: int, seed: int = 0):
    """A plausible (n, n) contact-probability map: strong short-range
    band (backbone neighbors), a few off-diagonal domain-contact
    patches, weak background."""
    import numpy as np

    rng = np.random.default_rng(seed)
    i = np.arange(n)
    d = np.abs(i[:, None] - i[None, :])
    probs = np.exp(-d / 12.0)                      # banded backbone
    for _ in range(max(3, n // 256)):              # domain contacts
        a, b = sorted(rng.integers(0, n, 2))
        w = int(rng.integers(16, 64))
        probs[a:a + w, b:b + w] = np.maximum(
            probs[a:a + w, b:b + w], rng.uniform(0.6, 0.95))
    probs = np.maximum(probs, probs.T)
    return np.clip(probs + rng.uniform(0, 0.05, (n, n)), 0.0, 1.0)


def _load_contacts(args):
    """(n, n) contact probabilities from the --from-contacts npz."""
    import numpy as np

    from alphafold2_tpu.ops.block_sparse import \
        contact_probs_from_distogram

    with np.load(args.from_contacts) as z:
        if "contacts" in z:
            return np.asarray(z["contacts"], np.float32)
        if "distogram" in z:
            return contact_probs_from_distogram(
                z["distogram"], cutoff=args.contact_cutoff)
    raise SystemExit(f"{args.from_contacts}: neither 'contacts' nor "
                     "'distogram' array found")


def _fit_contacts(contacts, n: int):
    """Crop (or wrap-tile) the saved map to bucket edge n — the replay
    benches every configured edge from one saved target."""
    import numpy as np

    m = contacts.shape[0]
    if m >= n:
        return contacts[:n, :n]
    reps = -(-n // m)
    return np.tile(contacts, (reps, reps))[:n, :n]


def _pattern_for(args, n: int, contacts):
    from alphafold2_tpu.model.attention_variants import \
        block_sparse_block_pattern
    from alphafold2_tpu.ops.block_sparse import contact_block_pattern

    if contacts is None:
        return block_sparse_block_pattern(n // args.block, num_global=1,
                                          window=1), "static"
    return contact_block_pattern(
        _fit_contacts(contacts, n), args.block,
        threshold=args.contact_threshold), "contacts"


def _append_json(path: str, lines):
    """Append result dicts to a JSON array file (created if absent)."""
    existing = []
    if os.path.exists(path):
        with open(path) as fh:
            existing = json.load(fh)
    existing.extend(lines)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(existing, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)


def main(argv=None):
    args = parse_args(argv)
    if args.emit_synthetic:
        import numpy as np
        np.savez_compressed(args.emit_synthetic,
                            contacts=_synthetic_contacts(args.emit_n))
        print(json.dumps({"emitted": args.emit_synthetic,
                          "n": args.emit_n}), flush=True)
        return

    _watchdog(float(os.environ.get("BENCH_TIMEOUT_S", 900)))
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _enable_compile_cache, is_tpu_platform
    _enable_compile_cache()

    platform = jax.default_backend()
    on_tpu = is_tpu_platform(platform)
    contacts = _load_contacts(args) if args.from_contacts else None
    buckets = [int(x) for x in args.buckets.split(",") if x]

    if not on_tpu:
        # no timing off-TPU (interpret mode benchmarks nothing real),
        # but the --from-contacts replay still reports the MEASURED
        # live fraction per bucket edge — the number the auto policy's
        # threshold is calibrated against
        lines = []
        for n in buckets:
            pattern, source = _pattern_for(args, n, contacts)
            lines.append({
                "skipped": True, "platform": platform, "n": n,
                "block": args.block, "source": source,
                "live_frac": round(float(pattern.mean()), 3),
                "reason": "Mosaic lowering needs a TPU; interpret-mode "
                          "timing is not evidence (exactness is "
                          "covered by tests/test_ops.py)"})
            print(json.dumps(lines[-1]), flush=True)
        if args.append and contacts is not None:
            _append_json(args.append, lines)
        _DONE.set()
        return

    from alphafold2_tpu.ops.attention import MASK_VALUE
    from alphafold2_tpu.ops.block_sparse import block_sparse_attention

    B, D = args.batch, 64
    block, iters = args.block, args.iters

    lines = []
    for n in buckets:
        pattern, source = _pattern_for(args, n, contacts)
        live_frac = float(pattern.mean())
        rng = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(rng, 3)
        q = jax.random.normal(kq, (B, n, D), jnp.bfloat16)
        k = jax.random.normal(kk, (B, n, D), jnp.bfloat16)
        v = jax.random.normal(kv, (B, n, D), jnp.bfloat16)

        import numpy as np
        tok = np.repeat(np.repeat(pattern, block, 0), block, 1)
        bias = jnp.where(jnp.asarray(tok), 0.0, MASK_VALUE)[None]
        bias = jnp.broadcast_to(bias, (B, n, n)).astype(jnp.float32)

        @jax.jit
        def dense(q, k, v, bias):
            logits = jnp.einsum("bnd,bmd->bnm", q.astype(jnp.float32),
                                k.astype(jnp.float32)) * (D ** -0.5)
            attn = jax.nn.softmax(logits + bias, axis=-1)
            return jnp.einsum("bnm,bmd->bnd", attn,
                              v.astype(jnp.float32)).astype(q.dtype)

        # pattern is STATIC (host-side plan); close it into the jitted fn
        # rather than passing it as a (traced) argument
        sparse = jax.jit(functools.partial(
            block_sparse_attention, pattern=pattern, block=block))

        def timeit(fn, *args_):
            # Measurement discipline (r05, both lessons tunnel-taught):
            # (a) block_until_ready can return before device completion
            #     under axon — close the window with a device_get of a
            #     scalar reduction instead (a transfer cannot complete
            #     before the compute it depends on);
            # (b) per-call dispatch costs ~3.5 ms through the tunnel and
            #     swamps ms-scale kernels — run the whole window as ONE
            #     dispatch: a lax.scan of `iters` chained applications
            #     (output feeds back as q, serializing on-device).
            @jax.jit
            def window(q0, rest):
                def body(q, _):
                    return fn(q, *rest), None
                out, _ = jax.lax.scan(body, q0, None, length=iters)
                return jnp.sum(out.astype(jnp.float32))

            float(jax.device_get(window(args_[0], args_[1:])))  # warm
            t0 = time.perf_counter()
            s = window(args_[0], args_[1:])
            float(jax.device_get(s))
            return (time.perf_counter() - t0) / iters * 1e3

        dense_ms = timeit(dense, q, k, v, bias)
        sparse_ms = timeit(sparse, q, k, v)
        lines.append({
            "n": n, "block": block, "batch": B, "dim_head": D,
            "source": source,
            "live_frac": round(live_frac, 3),
            "dense_ms": round(dense_ms, 3),
            "sparse_ms": round(sparse_ms, 3),
            "speedup": round(dense_ms / sparse_ms, 3),
            "platform": platform})
        print(json.dumps(lines[-1]), flush=True)
    if args.append:
        _append_json(args.append, lines)
    _DONE.set()


if __name__ == "__main__":
    main()
