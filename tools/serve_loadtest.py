"""Offline load-test driver for `alphafold2_tpu.serve`.

Closed-loop harness: `--concurrency` submitter threads each submit a
synthetic request, wait for its result, and repeat — either for a fixed
`--requests` count or until `--duration-s` of wall clock. Warmup
(per-bucket compiles) is timed separately and excluded from throughput,
so the reported folds/hour is steady-state serving, comparable to
STATUS.md's raw `predict.fold` numbers — the delta between the two is
the scheduling + padding overhead this subsystem is supposed to keep
small.

Prints ONE JSON line:
  {"folds_per_hour": N, "padding_waste": F, "shed": 0, ...}

`--smoke` (tools/serve_smoke.sh) exits 1 on ANY shed / timeout / error /
rejected request at trivial load — the serving regression tripwire.

Runs on CPU by default (__graft_entry__.force_cpu_fallback); pass
--platform ambient to target the real chip.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=64,
                    help="total requests (ignored when --duration-s > 0)")
    ap.add_argument("--duration-s", type=float, default=0.0,
                    help="run this many seconds instead of a fixed count")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed-loop submitter threads")
    ap.add_argument("--lengths", default="24,48,96",
                    help="comma-separated request lengths (cycled)")
    ap.add_argument("--buckets", default="",
                    help="comma-separated bucket edges; default: "
                         "powers-of-two covering --lengths")
    ap.add_argument("--msa-depth", type=int, default=3)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-wait-ms", type=float, default=25.0)
    ap.add_argument("--num-recycles", type=int, default=0)
    ap.add_argument("--deadline-s", type=float, default=0.0,
                    help="per-request deadline; 0 = none")
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--depth", type=int, default=1)
    ap.add_argument("--metrics-path", default="/tmp/serve_loadtest.jsonl")
    ap.add_argument("--platform", default="cpu",
                    choices=("cpu", "ambient"))
    ap.add_argument("--smoke", action="store_true",
                    help="exit 1 on any shed/timeout/error/rejection")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    import __graft_entry__
    if args.platform == "cpu":
        __graft_entry__.force_cpu_fallback()

    import jax
    import jax.numpy as jnp

    from alphafold2_tpu import Alphafold2, serve
    from alphafold2_tpu.data.synthetic import synthetic_requests
    from alphafold2_tpu.utils.profiling import StepTimer

    lengths = tuple(int(x) for x in args.lengths.split(",") if x)
    if args.buckets:
        policy = serve.BucketPolicy(
            int(x) for x in args.buckets.split(",") if x)
    else:
        policy = serve.BucketPolicy.powers_of_two(
            min(lengths), max(max(lengths), min(lengths)))

    model = Alphafold2(dim=args.dim, depth=args.depth, heads=2,
                       dim_head=16, predict_coords=True,
                       structure_module_depth=1)
    n0 = policy.edges[0]
    seq = jnp.zeros((1, n0), jnp.int32)
    init_kwargs = dict(mask=jnp.ones((1, n0), bool))
    if args.msa_depth > 0:
        init_kwargs["msa"] = jnp.zeros((1, args.msa_depth, n0), jnp.int32)
        init_kwargs["msa_mask"] = jnp.ones((1, args.msa_depth, n0), bool)
    params = model.init(jax.random.PRNGKey(0), seq, **init_kwargs)

    executor = serve.FoldExecutor(model, params,
                                  max_entries=policy.num_buckets)
    metrics = serve.ServeMetrics(args.metrics_path)
    config = serve.SchedulerConfig(
        max_batch_size=args.max_batch, max_wait_ms=args.max_wait_ms,
        num_recycles=args.num_recycles, msa_depth=args.msa_depth)
    scheduler = serve.Scheduler(executor, policy, config, metrics)

    warmup_timer = StepTimer()
    with warmup_timer.measure():
        compiles = scheduler.warmup()
    scheduler.start()

    deadline_s = args.deadline_s or None
    pool = synthetic_requests(
        jax.random.PRNGKey(1), num=max(args.requests, 64),
        lengths=lengths, msa_depth=args.msa_depth, deadline_s=deadline_s)
    failures = []
    lock = threading.Lock()
    counter = [0]

    def run_submitter(stop_at, budget):
        import numpy as np
        while True:
            with lock:
                i = counter[0]
                if (stop_at and time.monotonic() >= stop_at) or \
                        (budget and i >= budget):
                    return
                counter[0] = i + 1
            req_proto = pool[i % len(pool)]
            req = serve.FoldRequest(seq=req_proto.seq, msa=req_proto.msa,
                                    deadline_s=deadline_s)
            try:
                resp = scheduler.submit(req).result(timeout=600)
            except Exception as exc:
                with lock:
                    failures.append(repr(exc))
                return  # a broken loop would spin; one strike ends it
            if not resp.ok:
                with lock:
                    failures.append(f"{resp.status}: {resp.error}")
            elif resp.coords.shape != (req.length, 3) or \
                    not np.isfinite(resp.coords).all():
                with lock:
                    failures.append(
                        f"bad coords {resp.coords.shape} for n={req.length}")

    t0 = time.monotonic()
    stop_at = t0 + args.duration_s if args.duration_s > 0 else 0.0
    budget = 0 if args.duration_s > 0 else args.requests
    threads = [threading.Thread(target=run_submitter,
                                args=(stop_at, budget), daemon=True)
               for _ in range(max(args.concurrency, 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    serving_wall = time.monotonic() - t0
    scheduler.stop()

    snap = scheduler.serve_stats()
    report = {
        "metric": "serve_loadtest",
        "platform": args.platform,
        "folds_per_hour": round(snap["served"] / serving_wall * 3600.0, 1),
        "serving_wall_s": round(serving_wall, 3),
        "warmup_s": round(warmup_timer.mean * warmup_timer.count, 3),
        "compiles": compiles,
        "bucket_edges": snap["bucket_edges"],
        "padding_waste": round(snap["padding_waste"], 4),
        "served": snap["served"],
        "shed": snap["shed"],
        "errors": snap["errors"],
        "rejected": snap["rejected"],
        "batches": snap["batches"],
        "latency_by_bucket": snap["latency_by_bucket"],
        "executor": {k: snap["executor"][k]
                     for k in ("hits", "misses", "evictions")},
        "metrics_path": args.metrics_path,
        "failures": failures[:8],
    }
    metrics.close()
    print(json.dumps(report))

    if args.smoke:
        bad = snap["shed"] + snap["errors"] + snap["rejected"] \
            + len(failures)
        if bad or snap["served"] == 0:
            print(f"SMOKE FAIL: {bad} bad outcomes, "
                  f"{snap['served']} served", file=sys.stderr)
            return 1
        print(f"SMOKE OK: {snap['served']} folds, 0 shed/errors",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
