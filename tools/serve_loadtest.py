"""Offline load-test driver for `alphafold2_tpu.serve`.

Closed-loop harness: `--concurrency` submitter threads each submit a
synthetic request, wait for its result, and repeat — either for a fixed
`--requests` count or until `--duration-s` of wall clock. Warmup
(per-bucket compiles) is timed separately and excluded from throughput,
so the reported folds/hour is steady-state serving, comparable to
STATUS.md's raw `predict.fold` numbers — the delta between the two is
the scheduling + padding overhead this subsystem is supposed to keep
small.

Prints ONE JSON line:
  {"folds_per_hour": N, "padding_waste": F, "shed": 0, ...}

`--dup-rate F` makes fraction F of submissions repeats of earlier
sequences with a Zipf-ish popularity skew (rank r re-requested with
weight 1/(r+1) — the head-heavy shape of real serving traffic per
ParaFold's workload analysis). `--cache {auto,on,off}` controls the
content-addressed result cache + in-flight coalescing (auto = on iff
dup-rate > 0); the report then carries the cache section (hit ratio,
coalesced count) and `executor_calls_avoided` — requests that never
occupied the accelerator — next to folds/hour and padding waste.

`--replicas N` (with N > 1) runs the workload against an in-process
FLEET (`alphafold2_tpu.fleet.InProcessFleet`): N full serving stacks —
each with its own executor, cache, and localhost peer-cache server —
split the traffic round-robin (the dumb-load-balancer model).
`--fleet {auto,on,off}` controls the fleet wiring itself (consistent-
hash routing + peer cache tier; auto = on iff replicas > 1); `off` is
the two-independent-replicas baseline the fleet run is measured
against. `--rollout-at F` bumps the fleet-wide model tag after
fraction F of the request budget — the report's `rollout` section
carries `stale_tag_hits`, which must be 0 (the epoch bump's whole
contract). The fleet report aggregates served/batches/hit-ratio
fleet-wide plus forwards, peer hits, and leader promotions.

`--trace-path F` enables request-scoped tracing (`obs.Tracer`): one
JSONL record per completed request covering submit -> terminal with
per-stage spans (submit/queue/batch_form/compile/fold/writeback),
rendered by `tools/obs_report.py`; `--prom-path F` dumps the process
metrics registry as Prometheus text exposition on exit. Together they
are the observability phase of tools/serve_smoke.sh.

`--chaos` arms a seeded fault-injection plan (`serve.FaultPlan`) after
warmup: each executor batch fails transiently with probability
`--chaos-exec-rate`, `--chaos-poison` poison requests are mixed into
the schedule (mode "raise" fails any batch containing one — the
bisection path; mode "nan" corrupts its output rows — the validation
path), and optional latency spikes / corrupt cache bytes / peer
transport failures exercise the watchdog, quarantine, and markdown
tiers. Chaos implies `--retry on` (a `serve.RetryPolicy` on the
scheduler) unless `--retry off` explicitly measures the unhardened
baseline. The report carries a "chaos" section (injections actually
fired) plus poisoned/degraded/retried counts and per-poison attempt
counts; with `--smoke` the run FAILS unless every ticket reaches a
terminal state, every innocent request resolves ok, exactly the
requested number of poison requests is quarantined, and each poison
was cornered within the log2(max_batch)+1 bisection bound.

`--smoke` (tools/serve_smoke.sh) exits 1 on ANY shed / timeout / error /
rejected request at trivial load — the serving regression tripwire. With
a duplicated workload (`--dup-rate` > 0, cache on) it additionally fails
when the cache never hits or any coalesced ticket fails to resolve.

Runs on CPU by default (__graft_entry__.force_cpu_fallback); pass
--platform ambient to target the real chip.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=64,
                    help="total requests (ignored when --duration-s > 0)")
    ap.add_argument("--duration-s", type=float, default=0.0,
                    help="run this many seconds instead of a fixed count")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed-loop submitter threads")
    ap.add_argument("--lengths", default="24,48,96",
                    help="comma-separated request lengths (cycled)")
    ap.add_argument("--buckets", default="",
                    help="comma-separated bucket edges; default: "
                         "powers-of-two covering --lengths")
    ap.add_argument("--msa-depth", type=int, default=3)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-wait-ms", type=float, default=25.0)
    ap.add_argument("--num-recycles", type=int, default=0)
    ap.add_argument("--deadline-s", type=float, default=0.0,
                    help="per-request deadline; 0 = none")
    ap.add_argument("--dup-rate", type=float, default=0.0,
                    help="fraction of submissions repeating an earlier "
                         "sequence (Zipf-ish popularity skew)")
    ap.add_argument("--cache", default="auto",
                    choices=("auto", "on", "off"),
                    help="result cache + coalescing; auto = on iff "
                         "--dup-rate > 0")
    ap.add_argument("--cache-dir", default="",
                    help="optional on-disk tier for the result cache "
                         "(per-replica subdirs in fleet mode)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="in-process serving replicas; > 1 runs the "
                         "fleet harness with round-robin traffic split")
    ap.add_argument("--procs", type=int, default=0,
                    help="MULTI-PROCESS fleet: spawn this many real "
                         "replica processes (fleet.procfleet) and "
                         "drive them over HTTP with driver-side "
                         "failover; enables --proc-* chaos verbs")
    ap.add_argument("--proc-run-dir", default="",
                    help="procfleet run dir (state/cache/logs/traces "
                         "per replica); default: a fresh /tmp dir")
    ap.add_argument("--proc-kill-at", type=float, default=0.0,
                    help="kill -9 one replica after this fraction of "
                         "the request budget, then restart it "
                         "(0 = never)")
    ap.add_argument("--proc-partition-at", type=float, default=0.0,
                    help="partition one replica (both planes 503) "
                         "after this fraction of the budget")
    ap.add_argument("--proc-partition-s", type=float, default=2.0,
                    help="induced partition duration")
    ap.add_argument("--proc-drain-at", type=float, default=0.0,
                    help="rolling drain-restart (SIGTERM -> exit 0 -> "
                         "respawn) one replica after this fraction")
    ap.add_argument("--preempt-at", type=float, default=0.0,
                    help="spot-preempt one replica (ISSUE 20: notice "
                         "file, grace-budgeted drain + orphan "
                         "manifest, then kill -9) after this fraction "
                         "of the budget; arms "
                         "ProcFleet(preemption=True)")
    ap.add_argument("--preempt-grace-s", type=float, default=5.0,
                    help="grace window between the preemption notice "
                         "and the hard kill")
    ap.add_argument("--controller", action="store_true",
                    help="CONTROL PLANE (ISSUE 16, --procs only): arm "
                         "FleetController on the ProcFleet — the "
                         "reconcile loop owns membership, autoscaling, "
                         "rollout convergence, pool resizing, and "
                         "warming; the driver fires NO operator verbs "
                         "(a killed replica is NOT restarted by the "
                         "driver — the controller restores quorum)")
    ap.add_argument("--scale-min", type=int, default=0,
                    help="controller ScalingPolicy.min_replicas "
                         "(0 = the --procs boot count)")
    ap.add_argument("--scale-max", type=int, default=0,
                    help="controller ScalingPolicy.max_replicas "
                         "(0 = boot count + 2)")
    ap.add_argument("--traffic-wave", default="",
                    help="'F0:F1:MULT' — while the request counter is "
                         "inside [F0, F1) of the budget, run MULT x "
                         "--concurrency EXTRA submitter threads (their "
                         "requests are on top of the budget): the "
                         "traffic spike the controller must absorb by "
                         "scaling up")
    ap.add_argument("--fleet", default="auto",
                    choices=("auto", "on", "off"),
                    help="wire replicas into one fleet (consistent-hash "
                         "routing + peer cache); auto = on iff "
                         "--replicas > 1, off = independent-replicas "
                         "baseline")
    ap.add_argument("--rollout-at", type=float, default=0.0,
                    help="bump the fleet-wide model tag after this "
                         "fraction of the request budget (0 = never); "
                         "fleet mode only")
    ap.add_argument("--mesh-policy", default="",
                    help="multi-chip serving (serve.MeshPolicy): 'auto' "
                         "derives per-bucket slices from the analytic "
                         "HBM model (--mesh-hbm-gb), or an explicit "
                         "'BUCKET=CHIPS,...' map e.g. '32=1,64=4'; "
                         "empty = single-chip (today's behavior). Run "
                         "under XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=8 to exercise sharding on CPU")
    ap.add_argument("--mesh-hbm-gb", type=float, default=16.0,
                    help="per-device HBM budget the 'auto' mesh policy "
                         "and the too-large admission guard price "
                         "against")
    ap.add_argument("--recycle-sched", action="store_true",
                    help="iteration-level scheduling "
                         "(serve.RecyclePolicy): the scheduler owns "
                         "the recycle loop — early-exit converged "
                         "folds, preempt between recycles for "
                         "deadline traffic. With --deadline-s, only "
                         "the SHORTEST request length carries the "
                         "deadline (the tight traffic class); the "
                         "report then splits p50/p99 by class and "
                         "counts recycles saved")
    ap.add_argument("--converge-tol", type=float, default=0.0,
                    help="per-element convergence threshold for "
                         "early exit (0 = off: full recycles, "
                         "numerics identical to the opaque fold)")
    ap.add_argument("--converge-percentile", type=float, default=0.0,
                    help="CALIBRATE --converge-tol from the measured "
                         "per-element recycle-1 delta distribution of "
                         "the synthetic pool at this percentile "
                         "(0 = off). Injects SKEWED convergence: ~P%% "
                         "of elements early-exit at recycle 1, the "
                         "rest run longer — the freed-rows workload "
                         "the continuous batcher exists for. "
                         "Deterministic (same seeds -> same tol), so "
                         "a --continuous run and its early-exit-only "
                         "baseline see the identical threshold")
    ap.add_argument("--continuous", action="store_true",
                    help="continuous batching (ISSUE 11, implies "
                         "--recycle-sched): admit pending requests "
                         "into freed batch rows BETWEEN recycles via "
                         "the row-masked init program instead of "
                         "padding until the batch's last survivor "
                         "finishes; the report adds rows_occupied_"
                         "fraction / row_admissions / rows_dead_steps")
    ap.add_argument("--cross-bucket", action="store_true",
                    help="cross-bucket continuous batching (ISSUE 13, "
                         "implies --continuous): a freed row whose own "
                         "bucket's queue is dry admits a pending "
                         "request from a SHORTER bucket at the host "
                         "shape — priced per admit (padded step cost "
                         "x loop extension vs projected native-bucket "
                         "queue delay, deadline urgency tiebreak). The "
                         "report adds cross_bucket_admissions / "
                         "cross_bucket_refusals / "
                         "padding_waste_admitted / admit_pad_fraction")
    ap.add_argument("--cross-bucket-max-pad-frac", type=float,
                    default=0.75,
                    help="hard guard: refuse a cross-bucket candidate "
                         "whose pad fraction at the host edge "
                         "(1 - length/host_edge) exceeds this")
    ap.add_argument("--eager-form", action="store_true",
                    help="admission-aware batch formation (ISSUE 13, "
                         "implies --continuous): form an under-filled "
                         "batch immediately instead of waiting out "
                         "max_wait, counting on mid-loop row admission "
                         "to top it up")
    ap.add_argument("--min-recycles", type=int, default=0,
                    help="recycles every element must run before "
                         "early exit may fire")
    ap.add_argument("--stream", action="store_true",
                    help="publish per-recycle progressive results to "
                         "each ticket; the report counts updates")
    ap.add_argument("--no-preempt", action="store_true",
                    help="disable between-recycle preemption "
                         "(isolates the early-exit effect)")
    ap.add_argument("--kernel-policy", default="",
                    help="per-bucket attention-kernel routing "
                         "(ISSUE 12, serve.KernelPolicy.parse): "
                         "'dense' | 'blocksparse' | 'auto' | "
                         "'64=dense,512=blocksparse'. auto routes a "
                         "bucket sparse when its static banded mask's "
                         "live fraction <= --sparse-live-frac. Empty "
                         "(default) = feature off, byte-identical "
                         "serving. The report adds a 'kernel' section "
                         "(per-kernel folds/hour, mask live-fraction "
                         "histogram, interpret-mode numerics check)")
    ap.add_argument("--sparse-live-frac", type=float, default=0.5,
                    help="auto-policy threshold: route a bucket onto "
                         "the block-sparse kernel when its static "
                         "banded+global pattern's live fraction is <= "
                         "this (tpu_blocksparse.json: ~parity at 0.53 "
                         "live, 1.15x at 0.29)")
    ap.add_argument("--sparse-block", type=int, default=128,
                    help="sparse pattern block size (128 = TPU lane "
                         "width; small CPU smokes use 8/16)")
    ap.add_argument("--sparse-window", type=int, default=1,
                    help="banded-mask half-width in blocks")
    ap.add_argument("--sparse-global", type=int, default=1,
                    help="global blocks of the static mask")
    ap.add_argument("--kernel-backend", default="auto",
                    help="auto (Pallas on TPU, masked-dense on CPU) | "
                         "pallas (force; interpret off-TPU) | masked")
    ap.add_argument("--kernel-contact", action="store_true",
                    help="contact-prior masks (needs --recycle-sched): "
                         "re-plan each batch's block mask from its own "
                         "recycle-1 distogram, re-lowering the step "
                         "executable for the remaining recycles")
    ap.add_argument("--feature-latency-ms", type=float, default=0.0,
                    help="FEATURE-PIPELINE mode (ISSUE 10): synthetic "
                         "featurize latency per execution, standing in "
                         "for real MSA-search cost. > 0 switches to "
                         "the raw-submission driver: requests enter as "
                         "AA strings + raw MSA and featurize "
                         "replica-side")
    ap.add_argument("--feature-pool", type=int, default=0,
                    help="featurize worker threads (serve.FeaturePool "
                         "+ feature cache + coalescing). 0 = the "
                         "SERIALIZED baseline: featurize inline on the "
                         "submit path, no feature cache — exactly what "
                         "callers paid before the pipeline split")
    ap.add_argument("--feature-dup-rate", type=float, default=0.0,
                    help="fraction of raw submissions repeating an "
                         "earlier raw sequence (Zipf skew), "
                         "exercising the feature cache + featurize "
                         "coalescing independently of fold dedup")
    ap.add_argument("--cascade", action="store_true",
                    help="SPECULATIVE CASCADE (ISSUE 19, "
                         "serve.CascadePolicy): fold every request on a "
                         "half-size draft model first (0 recycles, its "
                         "own model_tag) and accept/escalate on a "
                         "confidence gate; the report adds a 'cascade' "
                         "section (accept rate, flagship_folds, "
                         "accelerator-seconds per accepted fold) and "
                         "latency_by_tier p50/p99. Single-scheduler "
                         "mode only")
    ap.add_argument("--draft-accept-rate", type=float, default=0.6,
                    help="scripted confidence gate: deterministic "
                         "fraction of draft folds accepted. The tiny "
                         "random-param draft's own confidence is "
                         "arbitrary, so the loadtest scripts the gate "
                         "decision to exercise BOTH cascade paths at a "
                         "known mix (serve_smoke.sh phase 17 compares "
                         "flagship executions against a no-cascade "
                         "baseline). Negative = use the real "
                         "serve.ConfidenceGate over the draft's own "
                         "pLDDT")
    ap.add_argument("--express-rate", type=float, default=0.0,
                    help="fraction of submissions sent as qos='express' "
                         "at the SHORTEST --lengths entry: the "
                         "interactive express lane with its own metric "
                         "class (serve_express_requests_total / "
                         "serve_express_latency_seconds, minted "
                         "lazily); the report adds latency_by_lane "
                         "p50/p99. The MSA-BYPASS express featurizer "
                         "is the raw-path seam — serve.FeaturePool("
                         "express=StubEmbedder()) — exercised by "
                         "tests/test_cascade.py, not this driver")
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--depth", type=int, default=1)
    ap.add_argument("--metrics-path", default="/tmp/serve_loadtest.jsonl")
    ap.add_argument("--trace-path", default="",
                    help="enable request tracing (obs.Tracer) and append "
                         "one JSONL record per completed trace here; "
                         "render with tools/obs_report.py")
    ap.add_argument("--trace-slow-k", type=int, default=8,
                    help="slowest traces retained in serve_stats()")
    ap.add_argument("--prom-path", default="",
                    help="dump the process metrics registry as "
                         "Prometheus text exposition here on exit")
    ap.add_argument("--slo", default="",
                    help="SLO objectives (ISSUE 15), the "
                         "obs.slo.SLOPolicy.parse spec: "
                         "'CLASS=P99_MS,...' where CLASS is a bucket "
                         "edge or 'all' and the value is the p99 "
                         "latency target in ms (or 'auto' — "
                         "driver-calibrated from the run's own "
                         "pre-chaos latencies, --procs mode only). "
                         "With --procs, each replica also runs an "
                         "SLOEngine (serve_stats()['slo'] + slo_* "
                         "gauges on GET /metrics) and the driver "
                         "reports windowed burn rates, kill window "
                         "included")
    ap.add_argument("--slo-window-s", type=float, default=5.0,
                    help="error-budget window for the SLO engine and "
                         "the driver's burn-rate windows")
    ap.add_argument("--obs-fleet-out", default="",
                    help="directory to collect fleet observability "
                         "artifacts into (--procs mode): one "
                         "<rid>.prom scrape of each replica's "
                         "GET /metrics plus the driver's windowed "
                         "SLO series (slo_driver.json) — the input "
                         "set tools/obs_fleet.py aggregates")
    ap.add_argument("--platform", default="cpu",
                    choices=("cpu", "ambient"))
    ap.add_argument("--smoke", action="store_true",
                    help="exit 1 on any shed/timeout/error/rejection")
    ap.add_argument("--retry", default="auto",
                    choices=("auto", "on", "off"),
                    help="scheduler RetryPolicy (failure-domain "
                         "hardening); auto = on iff --chaos")
    ap.add_argument("--retry-max-attempts", type=int, default=4)
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="step-loop carry checkpointing (ISSUE 14, "
                         "needs --recycle-sched): snapshot the carry "
                         "+ per-row ages every N recycles (and at "
                         "admission gaps) so a transient mid-loop "
                         "failure resumes survivors at their "
                         "checkpointed ages instead of requeueing to "
                         "recycle 0; the report adds "
                         "checkpoint_resumes / recycles_lost. 0 = off "
                         "(the PR-5 requeue-from-zero recovery)")
    ap.add_argument("--row-isolation", action="store_true",
                    help="per-row poison isolation in the step loop "
                         "(ISSUE 14): a per-step non-finite scan and "
                         "row-attributed deterministic failures "
                         "retire ONLY the offending row while batch "
                         "mates keep folding (bisection stays the "
                         "fallback); the report adds "
                         "row_poison_isolations")
    ap.add_argument("--watchdog-s", type=float, default=0.0,
                    help="per-batch executor watchdog deadline; 0 = off")
    ap.add_argument("--breaker-threshold", type=int, default=0,
                    help="consecutive batch failures that open the "
                         "degraded-mode circuit breaker; 0 = off")
    ap.add_argument("--chaos", action="store_true",
                    help="arm seeded fault injection (serve.FaultPlan) "
                         "after warmup")
    ap.add_argument("--chaos-seed", type=int, default=7)
    ap.add_argument("--chaos-exec-rate", type=float, default=0.10,
                    help="P(injected transient executor failure) per "
                         "batch execution")
    ap.add_argument("--chaos-latency-rate", type=float, default=0.0)
    ap.add_argument("--chaos-latency-s", type=float, default=0.05)
    ap.add_argument("--chaos-poison", type=int, default=1,
                    help="poison requests mixed into the schedule")
    ap.add_argument("--chaos-poison-mode", default="raise",
                    choices=("raise", "nan"))
    ap.add_argument("--chaos-corrupt-rate", type=float, default=0.0,
                    help="P(corrupted disk-cache bytes) per read")
    ap.add_argument("--chaos-peer-rate", type=float, default=0.0,
                    help="P(injected peer transport failure) per fetch "
                         "(fleet mode)")
    ap.add_argument("--chaos-step-at", default="",
                    help="mid-loop step faults (ISSUE 14): "
                         "'RECYCLE=RATE[,RECYCLE=RATE]' — each step "
                         "execution at that recycle index fails "
                         "transiently with that probability (e.g. "
                         "'1=0.25'), hitting the recycle loop exactly "
                         "where checkpoint resume recovers")
    ap.add_argument("--chaos-featurize-rate", type=float, default=0.0,
                    help="P(injected featurize failure) per featurize "
                         "execution (feature-pipeline mode); errors "
                         "must fan out to coalesced waiters")
    return ap.parse_args(argv)


def _parse_step_fail_at(spec: str) -> dict:
    """'1=0.25,2=0.1' -> {1: 0.25, 2: 0.1} (the FaultPlan step_fail_at
    form); empty -> {}. A typo'd schedule must fail loudly at boot
    (same contract as MeshPolicy.parse), naming the flag and the form."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        recycle, _, rate = part.partition("=")
        try:
            out[int(recycle)] = float(rate)
        except ValueError:
            raise ValueError(
                f"--chaos-step-at: malformed entry {part!r} — expected "
                f"RECYCLE=RATE[,RECYCLE=RATE...], e.g. 1=0.25,2=0.1")
    return out


def _build_resilience(args):
    """(FaultPlan or None, RetryPolicy or None) from the chaos flags."""
    from alphafold2_tpu import serve

    plan = None
    if args.chaos:
        plan = serve.FaultPlan(
            seed=args.chaos_seed,
            exec_error_rate=args.chaos_exec_rate,
            exec_latency_rate=args.chaos_latency_rate,
            exec_latency_s=args.chaos_latency_s,
            peer_error_rate=args.chaos_peer_rate,
            corrupt_rate=args.chaos_corrupt_rate,
            step_fail_at=_parse_step_fail_at(
                getattr(args, "chaos_step_at", "")),
            featurize_error_rate=getattr(args, "chaos_featurize_rate",
                                         0.0))
    retry = None
    if args.retry == "on" or (args.retry == "auto" and args.chaos):
        retry = serve.RetryPolicy(
            max_attempts=args.retry_max_attempts,
            backoff_base_s=0.02, backoff_max_s=0.5,
            seed=args.chaos_seed,
            watchdog_s=args.watchdog_s or None,
            breaker_threshold=args.breaker_threshold,
            checkpoint_every=getattr(args, "checkpoint_every", 0),
            row_isolation=getattr(args, "row_isolation", False))
    return plan, retry


def _build_mesh_policy(args, model, params, policy, jax,
                       devices=None):
    """serve.MeshPolicy (or None) from --mesh-policy, via the shared
    `MeshPolicy.parse` every --mesh-policy surface uses (this CLI,
    ProcFleet configs, replica_main). 'auto' derives per-bucket slices
    analytically; 'BUCKET=CHIPS,...' pins them. Shapes wider than the
    device pool clamp cleanly, so the same invocation works on
    1-device and 8-device hosts. `devices` restricts the policy to a
    subset pool (per-replica pinning in fleet mode)."""
    from alphafold2_tpu.serve import MeshPolicy

    return MeshPolicy.parse(
        args.mesh_policy, model=model, params=params, buckets=policy,
        max_batch=args.max_batch, msa_depth=args.msa_depth,
        hbm_gb=args.mesh_hbm_gb, devices=devices,
        # auto-sized slices must price what will actually run: the
        # step loop's carried Recyclables under --recycle-sched, plus
        # the row-admission seam under --continuous
        carry_recyclables=bool(getattr(args, "recycle_sched", False)
                               or getattr(args, "continuous", False)),
        continuous=bool(getattr(args, "continuous", False)))


def _build_recycle_policy(args):
    """serve.RecyclePolicy (or None) from --recycle-sched /
    --continuous (which implies it)."""
    if not (args.recycle_sched or getattr(args, "continuous", False)):
        return None
    from alphafold2_tpu.serve import RecyclePolicy

    return RecyclePolicy(converge_tol=args.converge_tol,
                         min_recycles=args.min_recycles,
                         preempt=not args.no_preempt,
                         stream=args.stream,
                         continuous=getattr(args, "continuous", False),
                         cross_bucket=getattr(args, "cross_bucket",
                                              False),
                         cross_bucket_max_pad_frac=getattr(
                             args, "cross_bucket_max_pad_frac", 0.75),
                         eager_form=getattr(args, "eager_form", False))


def _build_kernel_policy(args, policy):
    """serve.KernelPolicy (or None) from --kernel-policy, via the
    shared `KernelPolicy.parse` surface."""
    from alphafold2_tpu.serve import KernelPolicy

    return KernelPolicy.parse(
        args.kernel_policy, policy.edges, block=args.sparse_block,
        sparse_live_frac=args.sparse_live_frac,
        backend=args.kernel_backend, window=args.sparse_window,
        num_global=args.sparse_global,
        contact_priors=args.kernel_contact)


def _kernel_numerics_check(kernel_policy, policy, dim_head=16,
                           batch=4) -> dict:
    """Interpret-mode numerics check for every sparse-routed bucket:
    the block-skipping kernel vs the dense+mask reference on the EXACT
    pattern being served (random q/k/v at the serving length). Cheap on
    CPU (one tiny interpret compile per sparse bucket) and honest —
    the pattern, block size, and length are the production ones, so a
    planning/kernel regression fails the smoke here even when the
    serving path runs the masked-dense fallback."""
    import jax.numpy as jnp
    import numpy as np

    from alphafold2_tpu.ops.attention import (MASK_VALUE,
                                              attention_reference)
    from alphafold2_tpu.ops.block_sparse import (block_sparse_attention,
                                                 on_tpu_backend)

    out = {}
    for edge in policy.edges:
        spec = kernel_policy.spec_for(edge)
        if spec is None:
            continue
        rng = np.random.default_rng(edge)
        q, k, v = (jnp.asarray(rng.normal(size=(batch, edge, dim_head)),
                               jnp.float32) for _ in range(3))
        # on_tpu_backend (not == "tpu"): the tunneled chip reports
        # 'axon', and the check must exercise the COMPILED Mosaic
        # kernel there, not the interpreter
        sparse = block_sparse_attention(
            q, k, v, spec.pattern_array(), block=spec.block,
            interpret=not on_tpu_backend())
        bias = jnp.where(jnp.asarray(spec.token_mask()), 0.0,
                         MASK_VALUE)[None]
        ref = attention_reference(
            q * dim_head ** -0.5, k, v,
            bias=jnp.broadcast_to(bias, (batch, edge, edge)))
        out[str(edge)] = float(
            np.abs(np.asarray(sparse) - np.asarray(ref)).max())
    return out


def _calibrate_converge_tol(args, executor, policy, pool):
    """--converge-percentile: measure the SERVING pool's own
    recycle-1 deltas at the serving signature (the same init+step
    executables the scheduler will run — they stay warm in the
    executor's LRU) and return the P-th percentile as the converge
    tol. Elements whose delta sits below it early-exit at recycle 1;
    the rest outlive them — exactly the skewed per-element convergence
    that frees rows mid-loop. Calibrating on the pool the run will
    actually submit (not a disjoint sample: delta distributions shift
    between pools by more than their spread on small models) keeps the
    split honest, and it is seed-deterministic, so a --continuous run
    and its early-exit-only baseline gate on one identical
    threshold."""
    import numpy as np

    from alphafold2_tpu.serve.recycle import element_deltas
    from alphafold2_tpu.utils.profiling import percentile

    protos = pool[:max(16, 2 * args.max_batch)]
    by_bucket = {}
    for p in protos:
        by_bucket.setdefault(
            policy.bucket_for(int(p.seq.shape[0])), []).append(p)
    deltas = []
    for bucket, group in sorted(by_bucket.items()):
        for i in range(0, len(group), args.max_batch):
            chunk = group[i:i + args.max_batch]
            batch, _ = policy.assemble(chunk, bucket, args.max_batch,
                                       msa_depth=args.msa_depth)
            st0 = executor.run_init(batch)
            st1 = executor.run_step(batch, st0, 1)
            deltas.extend(element_deltas(
                np.asarray(st0.coords), np.asarray(st0.confidence),
                np.asarray(st1.coords), np.asarray(st1.confidence),
                [int(r.seq.shape[0]) for r in chunk]))
    return float(percentile(deltas, args.converge_percentile))


def _poison_pool(args, jax):
    """Dedicated poison prototypes, disjoint from the normal pool by
    construction (their own PRNG key)."""
    from alphafold2_tpu.data.synthetic import synthetic_requests

    if not (args.chaos and args.chaos_poison > 0):
        return []
    lengths = tuple(int(x) for x in args.lengths.split(",") if x)
    return synthetic_requests(
        jax.random.PRNGKey(999), num=args.chaos_poison,
        lengths=lengths, msa_depth=args.msa_depth)


def _schedule_poison(schedule, n_poison):
    """Replace n_poison slots with sentinel indices -(p+1), spread
    through the middle of the schedule so each poison meets a warm,
    concurrent system. Slots are kept DISTINCT (clamping at the tail
    walks down to the nearest free slot) so a short schedule never
    silently drops a poison; when the schedule is shorter than
    n_poison the leftover poisons are unplaceable and the chaos smoke
    check reports the shortfall."""
    if not n_poison or not schedule:
        return schedule
    schedule = list(schedule)
    step = max(1, len(schedule) // (n_poison + 1))
    used = set()
    for p in range(n_poison):
        slot = min((p + 1) * step, len(schedule) - 1)
        while slot in used and slot > 0:
            slot -= 1
        if slot in used:
            break                     # more poisons than slots
        used.add(slot)
        schedule[slot] = -(p + 1)
    return schedule


def _zipf_schedule(args, pool_len: int):
    """Submission schedule over prototype indices: with --dup-rate, a
    submission repeats an ALREADY-USED prototype with probability
    dup_rate, picking it Zipf-ishly (first-seen rank r with weight
    1/(r+1)) — duplicates are exact (same seq AND msa), so they are
    cache/coalesce candidates. dup_rate=0 degenerates to the old
    round-robin over unique prototypes."""
    import numpy as np

    sched_rng = np.random.default_rng(2)
    schedule_len = args.requests if args.duration_s <= 0 else 4096
    schedule, used = [], []
    fresh_i = 0

    def zipf_pick():
        w = 1.0 / (np.arange(len(used)) + 1.0)
        return used[int(sched_rng.choice(len(used), p=w / w.sum()))]

    for _ in range(max(schedule_len, 1)):
        if used and sched_rng.random() < args.dup_rate:
            j = zipf_pick()
        elif fresh_i < pool_len:
            j = fresh_i
            fresh_i += 1
            used.append(j)
        elif args.dup_rate > 0:
            # unique budget exhausted on a duplicate-heavy run: an
            # explicit Zipf repeat, keeping `used` duplicate-free so the
            # 1/(rank+1) weights stay meaningful
            j = zipf_pick()
        else:
            # dup_rate=0: plain round-robin over the pool, exactly the
            # pre-cache behavior (no popularity skew in baselines)
            j = fresh_i % pool_len
            fresh_i += 1
        schedule.append(j)
    return schedule


def _build_tiny_model(args, jax, jnp, policy):
    """The loadtest's synthetic serving model + params (shared by the
    single-scheduler and fleet paths)."""
    from alphafold2_tpu import Alphafold2

    model = Alphafold2(dim=args.dim, depth=args.depth, heads=2,
                       dim_head=16, predict_coords=True,
                       structure_module_depth=1)
    n0 = policy.edges[0]
    seq = jnp.zeros((1, n0), jnp.int32)
    init_kwargs = dict(mask=jnp.ones((1, n0), bool))
    if args.msa_depth > 0:
        init_kwargs["msa"] = jnp.zeros((1, args.msa_depth, n0), jnp.int32)
        init_kwargs["msa_mask"] = jnp.ones((1, args.msa_depth, n0), bool)
    params = model.init(jax.random.PRNGKey(0), seq, **init_kwargs)
    return model, params


class _ScriptedGate:
    """Deterministic stand-in for serve.ConfidenceGate (--cascade).

    A dim-16 random-param draft emits arbitrary confidence, so
    thresholding it would pin the loadtest's accept fraction to 0 or 1
    by luck. This gate ignores the score and accepts a Bresenham-spread
    `rate` fraction of decisions instead — both cascade paths run at a
    known mix, and the aggregate accept_rate in serve_stats() converges
    on `rate` regardless of submitter interleaving. Exposes the two
    attributes serve_stats()'s cascade section reads off a gate."""

    def __init__(self, rate: float):
        self.accept_plddt = 0.0       # read by serve_stats(); scripted
        self.max_entropy = None
        self.rate = max(0.0, min(1.0, rate))
        self._acc = 0.0
        self._lock = threading.Lock()

    def accepts(self, score) -> bool:
        with self._lock:
            self._acc += self.rate
            if self._acc >= 1.0 - 1e-9:
                self._acc -= 1.0
                return True
            return False


class _TimedExecutor:
    """Wall-clock accounting of executor work, the report's
    accelerator-seconds proxy (the unit survives the move from this
    CPU smoke to a real accelerator). Only the execution verbs are
    timed — warmup/compile passes through untimed so the cascade's
    per-accepted-fold cost reads serving work alone."""

    def __init__(self, inner):
        self._inner = inner
        self.seconds = 0.0
        self._lock = threading.Lock()

    def _timed(self, fn, *a, **kw):
        t0 = time.monotonic()
        try:
            return fn(*a, **kw)
        finally:
            with self._lock:
                self.seconds += time.monotonic() - t0

    def run(self, *a, **kw):
        return self._timed(self._inner.run, *a, **kw)

    def run_init(self, *a, **kw):
        return self._timed(self._inner.run_init, *a, **kw)

    def run_step(self, *a, **kw):
        return self._timed(self._inner.run_step, *a, **kw)

    def run_init_rows(self, *a, **kw):
        return self._timed(self._inner.run_init_rows, *a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.slo and not args.procs:
        # an objective that silently monitors nothing is the exact
        # failure SLOPolicy.parse's fail-loudly contract exists to
        # prevent — the driver-side SLO harness is --procs-only today
        print("--slo requires --procs (the SLO harness drives the "
              "multi-process fleet; in-process modes attach an "
              "SLOEngine via serve.Scheduler(slo=) directly)",
              file=sys.stderr)
        return 2
    if args.controller and not args.procs:
        print("--controller requires --procs (the control plane "
              "actuates ProcFleet's spawn/SIGTERM verbs)",
              file=sys.stderr)
        return 2
    if (args.cascade or args.express_rate > 0) and \
            (args.procs or args.replicas > 1
             or args.feature_latency_ms > 0 or args.feature_pool > 0):
        print("--cascade/--express-rate drive the single-scheduler "
              "mode (the fleet/feature/procs drivers exercise the "
              "cascade through ProcFleet(cascade=) and "
              "tests/test_cascade.py)", file=sys.stderr)
        return 2
    if args.cross_bucket or args.eager_form:
        args.continuous = True       # both ride the continuous batcher
    if args.continuous:
        args.recycle_sched = True    # continuous batching IS step mode
    import __graft_entry__
    if args.platform == "cpu":
        __graft_entry__.force_cpu_fallback()
    if args.procs > 0:
        return _run_procs(args)
    if args.replicas > 1:
        return _run_fleet(args)
    if args.feature_latency_ms > 0 or args.feature_pool > 0:
        return _run_features(args)

    import jax
    import jax.numpy as jnp

    from alphafold2_tpu import serve
    from alphafold2_tpu.data.synthetic import synthetic_requests
    from alphafold2_tpu.utils.profiling import StepTimer

    lengths = tuple(int(x) for x in args.lengths.split(",") if x)
    if args.buckets:
        policy = serve.BucketPolicy(
            int(x) for x in args.buckets.split(",") if x)
    else:
        policy = serve.BucketPolicy.powers_of_two(
            min(lengths), max(max(lengths), min(lengths)))

    model, params = _build_tiny_model(args, jax, jnp, policy)

    deadline_s = args.deadline_s or None
    # duration-mode cache runs need unique headroom: a 64-prototype pool
    # under a 4096-entry schedule would force-duplicate almost every
    # submission regardless of --dup-rate. The report's
    # unique_requests/requests ratio is the effective duplicate rate.
    pool_n = max(args.requests, 64)
    if args.duration_s > 0 and (args.cache == "on" or args.dup_rate > 0):
        pool_n = max(pool_n, 1024)
    pool = synthetic_requests(
        jax.random.PRNGKey(1), num=pool_n,
        lengths=lengths, msa_depth=args.msa_depth, deadline_s=deadline_s)

    plan, retry = _build_resilience(args)
    mesh_policy = _build_mesh_policy(args, model, params, policy, jax)
    # mesh serving mints one executable per (bucket, slice identity):
    # size the LRU so concurrent slices don't thrash each other out
    # (the scheduler doubles it for the step-mode init+step pair,
    # triples under --continuous for the init_rows admission program)
    max_entries = policy.num_buckets * (
        len(jax.devices()) if mesh_policy is not None else 1)
    executor = serve.FoldExecutor(model, params,
                                  max_entries=max_entries,
                                  faults=plan,
                                  model_tag="serve_loadtest")
    calibrated_tol = None
    if args.recycle_sched and args.converge_percentile > 0:
        # measure BEFORE the policy is built; the executables compiled
        # here are the serving ones, so warmup below hits them warm
        args.converge_tol = calibrated_tol = _calibrate_converge_tol(
            args, executor, policy, pool)
    recycle_policy = _build_recycle_policy(args)
    kernel_policy = _build_kernel_policy(args, policy)
    metrics = serve.ServeMetrics(args.metrics_path)
    config = serve.SchedulerConfig(
        max_batch_size=args.max_batch, max_wait_ms=args.max_wait_ms,
        num_recycles=args.num_recycles, msa_depth=args.msa_depth)
    cache_on = args.cache == "on" or (args.cache == "auto"
                                      and args.dup_rate > 0)
    cache = None
    if cache_on:
        cache = serve.FoldCache(disk_dir=args.cache_dir or None,
                                faults=plan)
    tracer = None
    if args.trace_path:
        from alphafold2_tpu import obs
        tracer = obs.Tracer(jsonl_path=args.trace_path,
                            slow_k=args.trace_slow_k)
    cascade_policy = None
    draft_sched = None
    draft_exec = None
    if args.cascade:
        from alphafold2_tpu import Alphafold2
        executor = _TimedExecutor(executor)
        # the draft tier: half the trunk, zero recycles, its own
        # model_tag — the speculative cascade's whole premise is that
        # this config is materially cheaper per fold than the flagship
        draft_model = Alphafold2(dim=max(args.dim // 2, 16),
                                 depth=max(args.depth // 2, 1),
                                 heads=2, dim_head=16,
                                 predict_coords=True,
                                 structure_module_depth=1)
        n0 = policy.edges[0]
        init_kwargs = dict(mask=jnp.ones((1, n0), bool))
        if args.msa_depth > 0:
            init_kwargs["msa"] = jnp.zeros((1, args.msa_depth, n0),
                                           jnp.int32)
            init_kwargs["msa_mask"] = jnp.ones((1, args.msa_depth, n0),
                                               bool)
        draft_params = draft_model.init(
            jax.random.PRNGKey(2), jnp.zeros((1, n0), jnp.int32),
            **init_kwargs)
        draft_exec = _TimedExecutor(serve.FoldExecutor(
            draft_model, draft_params, max_entries=policy.num_buckets,
            model_tag="serve_loadtest#draft"))
        draft_sched = serve.build_draft_scheduler(
            draft_exec, policy,
            config=serve.SchedulerConfig(
                max_batch_size=args.max_batch,
                max_wait_ms=args.max_wait_ms,
                num_recycles=0, msa_depth=args.msa_depth,
                confidence_summary=True),
            model_tag="serve_loadtest#draft", cache=cache)
        gate = (_ScriptedGate(args.draft_accept_rate)
                if args.draft_accept_rate >= 0
                else serve.ConfidenceGate())
        cascade_policy = serve.CascadePolicy(draft=draft_sched,
                                             gate=gate)
    scheduler = serve.Scheduler(executor, policy, config, metrics,
                                cache=cache, model_tag="serve_loadtest",
                                tracer=tracer, retry=retry,
                                mesh_policy=mesh_policy,
                                recycle_policy=recycle_policy,
                                kernel_policy=kernel_policy,
                                cascade=cascade_policy)

    warmup_timer = StepTimer()
    with warmup_timer.measure():
        compiles = scheduler.warmup()
        if draft_sched is not None:
            compiles += draft_sched.warmup()
    scheduler.start()

    import numpy as np

    poisons = _poison_pool(args, jax)
    if plan is not None:
        for p in poisons:
            plan.add_poison(np.asarray(p.seq),
                            mode=args.chaos_poison_mode)
        plan.arm()        # warmup/compiles ran clean; the window starts

    schedule = _schedule_poison(_zipf_schedule(args, len(pool)),
                                len(poisons))

    failures = []
    statuses = {}
    poison_results = []
    lock = threading.Lock()
    counter = [0]
    # --recycle-sched traffic classes: the shortest length is the
    # TIGHT class (it alone carries --deadline-s and exercises
    # preemption), everything else is bulk; per-class client-side
    # latencies feed the report's p50/p99 split
    short_len = min(lengths)
    class_latencies = {"tight": [], "bulk": []}
    # cascade tier + express lane client-side latency splits (ISSUE 19)
    tier_latencies = {"draft": [], "flagship": []}
    lane_latencies = {"express": [], "online": []}
    short_pool = [p for p in pool
                  if int(p.seq.shape[0]) == short_len] or list(pool)
    progress_updates = [0]

    def run_submitter(stop_at, budget):
        while True:
            with lock:
                i = counter[0]
                if (stop_at and time.monotonic() >= stop_at) or \
                        (budget and i >= budget):
                    return
                counter[0] = i + 1
            idx = schedule[i % len(schedule)]
            is_poison = idx < 0
            req_proto = poisons[-idx - 1] if is_poison else pool[idx]
            # express lane (ISSUE 19): a deterministic well-spread
            # subset of submissions rides qos="express" on SHORT
            # prototypes — the interactive class whose p99 the lane's
            # own metric class (and phase 17's gate) watches
            is_express = (args.express_rate > 0 and not is_poison
                          and ((i * 2654435761) % 1000) / 1000.0
                          < args.express_rate)
            if is_express:
                req_proto = short_pool[idx % len(short_pool)]
            req_len = int(req_proto.seq.shape[0])
            req_deadline = deadline_s
            klass = "bulk"
            if args.recycle_sched and deadline_s:
                klass = "tight" if req_len <= short_len else "bulk"
                req_deadline = deadline_s if klass == "tight" else None
            req = serve.FoldRequest(seq=req_proto.seq, msa=req_proto.msa,
                                    deadline_s=req_deadline,
                                    qos=("express" if is_express
                                         else "online"))
            t_submit = time.monotonic()
            try:
                # FoldTicket.result(timeout=) is the caller-side hang
                # fence: a wedged ticket fails THIS run loudly instead
                # of blocking the harness forever
                ticket = scheduler.submit(req)
                if args.stream:
                    def _on_progress(_p):
                        with lock:
                            progress_updates[0] += 1
                    ticket.add_progress_callback(_on_progress)
                resp = ticket.result(timeout=600)
            except Exception as exc:
                with lock:
                    failures.append(repr(exc))
                return  # a broken loop would spin; one strike ends it
            with lock:
                statuses[resp.status] = statuses.get(resp.status, 0) + 1
                if not is_poison and resp.ok:
                    lat = time.monotonic() - t_submit
                    class_latencies[klass].append(lat)
                    if args.cascade:
                        tier_latencies["draft" if resp.tier == "draft"
                                       else "flagship"].append(lat)
                    if args.express_rate > 0:
                        lane_latencies["express" if is_express
                                       else "online"].append(lat)
            if is_poison:
                # a poison request is EXPECTED to terminate "poisoned";
                # the chaos smoke judges these separately
                with lock:
                    poison_results.append(
                        {"request_id": resp.request_id,
                         "poison": -idx - 1,
                         "status": resp.status,
                         "attempts": resp.attempts})
                continue
            if not resp.ok:
                with lock:
                    failures.append(f"{resp.status}: {resp.error}")
            elif resp.coords.shape != (req.length, 3) or \
                    not np.isfinite(resp.coords).all():
                with lock:
                    failures.append(
                        f"bad coords {resp.coords.shape} for n={req.length}")

    t0 = time.monotonic()
    stop_at = t0 + args.duration_s if args.duration_s > 0 else 0.0
    budget = 0 if args.duration_s > 0 else args.requests
    threads = [threading.Thread(target=run_submitter,
                                args=(stop_at, budget), daemon=True)
               for _ in range(max(args.concurrency, 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    serving_wall = time.monotonic() - t0
    scheduler.stop()

    snap = scheduler.serve_stats()
    total = counter[0]
    cache_snap = snap["cache"]
    avoided = cache_snap["hits"] + cache_snap["coalesced"]
    report = {
        "metric": "serve_loadtest",
        "platform": args.platform,
        "folds_per_hour": round(snap["served"] / serving_wall * 3600.0, 1),
        "requests_per_hour": round(total / serving_wall * 3600.0, 1),
        "serving_wall_s": round(serving_wall, 3),
        "warmup_s": round(warmup_timer.mean * warmup_timer.count, 3),
        "compiles": compiles,
        "bucket_edges": snap["bucket_edges"],
        "padding_waste": round(snap["padding_waste"], 4),
        "requests": total,
        "unique_requests": len({schedule[i % len(schedule)]
                                for i in range(total)}),
        "dup_rate": args.dup_rate,
        "served": snap["served"],
        "shed": snap["shed"],
        "errors": snap["errors"],
        "rejected": snap["rejected"],
        "degraded": snap["degraded"],
        "poisoned": snap["poisoned"],
        "retried": snap["retried"],
        "statuses": statuses,
        "batches": snap["batches"],
        "cache_enabled": cache_on,
        "cache_hit_ratio": round(cache_snap["hit_ratio"], 4),
        "coalesced": cache_snap["coalesced"],
        "executor_calls_avoided": avoided,
        "latency_by_bucket": snap["latency_by_bucket"],
        "executor": {k: snap["executor"][k]
                     for k in ("hits", "misses", "evictions")},
        "metrics_path": args.metrics_path,
        "failures": failures[:8],
    }
    if tracer is not None:
        tracer.close()
        slowest = snap["traces"]
        report["trace_path"] = args.trace_path
        report["traces_completed"] = tracer.completed
        report["slowest_trace_s"] = (slowest[0]["duration_s"]
                                     if slowest else 0.0)
    if mesh_policy is not None:
        report["devices"] = len(jax.devices())
        report["mesh"] = snap.get("mesh")
        report["too_large"] = snap.get("too_large", 0)
    if kernel_policy is not None:
        ksnap = snap["kernel"]
        # per-kernel folds/hour over the same serving wall clock the
        # headline number uses, plus a mask live-fraction histogram
        # weighted by executed batches
        per_kernel_fph = {}
        for key, v in ksnap["folds"].items():
            kind = key.split(":")[0]
            per_kernel_fph[kind] = per_kernel_fph.get(kind, 0) \
                + v["served"]
        per_kernel_fph = {
            k: round(v / serving_wall * 3600.0, 1)
            for k, v in per_kernel_fph.items()}
        hist = {}
        for key, v in ksnap["folds"].items():
            kind, _, bucket = key.partition(":")
            b = ksnap["buckets"].get(bucket, {})
            frac = 1.0 if b.get("live_frac") is None else b["live_frac"]
            lo = int(frac * 10) / 10.0
            bin_label = f"{lo:.1f}-{min(lo + 0.1, 1.0):.1f}"
            hist[bin_label] = hist.get(bin_label, 0) + v["batches"]
        report["kernel"] = dict(
            ksnap,
            folds_per_hour_by_kernel=per_kernel_fph,
            live_frac_hist=dict(sorted(hist.items())),
            numerics_max_diff=_kernel_numerics_check(kernel_policy,
                                                     policy))
    if args.cascade:
        from alphafold2_tpu.utils.profiling import percentile
        casc = dict(snap["cascade"])
        # flagship EXECUTIONS, the number serve_smoke.sh phase 17
        # gates against a no-cascade baseline: every served fold that
        # was not an accepted draft folded on the flagship (exact with
        # dedup off; store hits are counted separately either way)
        casc["flagship_folds"] = snap["served"] - casc["draft_accepted"]
        casc["scripted_gate"] = args.draft_accept_rate >= 0
        total_s = executor.seconds + draft_exec.seconds
        casc["accel_seconds"] = {
            "draft": round(draft_exec.seconds, 3),
            "flagship": round(executor.seconds, 3),
            "total": round(total_s, 3)}
        # the cascade's efficiency headline: total accelerator work
        # per fold the draft tier fully paid for
        casc["accel_seconds_per_accepted"] = (
            round(total_s / casc["draft_accepted"], 4)
            if casc["draft_accepted"] else None)
        report["cascade"] = casc
        report["latency_by_tier"] = {
            k: {"count": len(v),
                "p50_s": round(percentile(v, 50), 4),
                "p99_s": round(percentile(v, 99), 4)}
            for k, v in tier_latencies.items() if v}
    if args.express_rate > 0:
        from alphafold2_tpu.utils.profiling import percentile
        report["express"] = snap.get("express", {})
        report["latency_by_lane"] = {
            k: {"count": len(v),
                "p50_s": round(percentile(v, 50), 4),
                "p99_s": round(percentile(v, 99), 4)}
            for k, v in lane_latencies.items() if v}
    # executor step-executions: the apples-to-apples cost unit across
    # the opaque and step-scheduled paths (an opaque fold IS
    # 1 + num_recycles fused steps) — serve_smoke.sh phase 8 compares
    # this between a baseline and a --recycle-sched run
    if recycle_policy is not None:
        rec = snap["recycle"]
        report["executor_steps"] = snap["batches"] \
            + rec["recycles_executed"]
        report["recycle"] = rec
        report["recycles_saved"] = rec["recycles_skipped"]
        # continuous-batching occupancy (identical keys with
        # --continuous off, so the smoke's baseline comparison reads
        # the same stat from both runs)
        report["rows_occupied_fraction"] = round(
            rec["rows_occupied_fraction"], 4)
        report["row_admissions"] = rec["row_admissions"]
        report["rows_dead_steps"] = rec["rows_dead_steps"]
        report["continuous"] = bool(args.continuous)
        # cross-bucket trade observability (ISSUE 13): identical keys
        # with --cross-bucket off, so the smoke's same-bucket-only
        # baseline comparison reads the same stats from both runs
        report["cross_bucket"] = bool(args.cross_bucket)
        report["cross_bucket_admissions"] = rec["cross_bucket_admissions"]
        report["cross_bucket_refusals"] = rec["cross_bucket_refusals"]
        report["padding_waste_admitted"] = round(
            snap["padding_waste_admitted"], 4)
        report["admit_pad_fraction"] = snap["admit_pad_fraction"]
        if calibrated_tol is not None:
            report["converge_tol_calibrated"] = calibrated_tol
        from alphafold2_tpu.utils.profiling import percentile
        report["latency_by_class"] = {
            k: {"count": len(v),
                "p50_s": round(percentile(v, 50), 4),
                "p99_s": round(percentile(v, 99), 4)}
            for k, v in class_latencies.items() if v}
        if args.stream:
            report["progress_updates"] = progress_updates[0]
    else:
        report["executor_steps"] = snap["batches"] \
            * (1 + args.num_recycles)
    if args.prom_path:
        from alphafold2_tpu import obs
        obs.write_prometheus(args.prom_path)
        report["prom_path"] = args.prom_path
    if cache_on:
        report["cache_store"] = {
            k: cache_snap["store"][k]
            for k in ("hits", "misses", "disk_hits", "disk_errors",
                      "evictions", "bytes_resident", "entries_resident")}
    if retry is not None:
        report["resilience"] = snap["resilience"]
        # step-loop fault-domain headline numbers (ISSUE 14; zero when
        # the knobs are off, so smoke comparisons read one key set)
        res = snap["resilience"]
        report["checkpoint_resumes"] = res.get("checkpoint_resumes", 0)
        report["recycles_lost"] = res.get("recycles_lost", 0)
        report["row_poison_isolations"] = res.get(
            "row_poison_isolations", 0)
    if plan is not None:
        report["chaos"] = dict(plan.snapshot(),
                               poison_mode=args.chaos_poison_mode,
                               poison_results=poison_results)
    metrics.close()
    print(json.dumps(report))

    if args.smoke and args.chaos:
        return _check_chaos_smoke(args, snap, failures, poison_results,
                                  retry is not None, plan=plan)
    if args.smoke:
        bad = snap["shed"] + snap["errors"] + snap["rejected"] \
            + len(failures)
        if bad or snap["served"] == 0:
            print(f"SMOKE FAIL: {bad} bad outcomes, "
                  f"{snap['served']} served", file=sys.stderr)
            return 1
        if cache_on and args.dup_rate > 0 and cache_snap["hits"] == 0:
            # a duplicated workload that never hits the store means the
            # cache subsystem is broken (every ticket still resolved:
            # coalesced-only would show up here as hits == 0)
            print(f"SMOKE FAIL: dup-rate {args.dup_rate} workload with "
                  f"0 cache hits ({cache_snap['coalesced']} coalesced)",
                  file=sys.stderr)
            return 1
        if mesh_policy is not None:
            multi = [b for b in policy.edges
                     if mesh_policy.chips_for(b) > 1]
            n_dev = len(jax.devices())
            if multi and n_dev > 1:
                mesh_folds = (snap.get("mesh") or {}).get("folds", {})
                sharded = sum(v["batches"]
                              for k, v in mesh_folds.items()
                              if k != "1x1")
                if sharded == 0:
                    print(f"SMOKE FAIL: mesh policy maps buckets "
                          f"{multi} to >1 chip but no sharded batch "
                          f"executed (folds {mesh_folds})",
                          file=sys.stderr)
                    return 1
            elif mesh_policy.clamped:
                # small-pool host: the policy clamped the wide slices —
                # multi-chip assertions are vacuous, skip them cleanly
                print(f"SMOKE NOTE: mesh slices {mesh_policy.clamped} "
                      f"clamped to the {n_dev}-device pool; "
                      "sharded-execution assertions skipped",
                      file=sys.stderr)
        if kernel_policy is not None:
            sparse_routed = [e for e in policy.edges
                             if kernel_policy.kernel_for(e)
                             == "blocksparse"]
            if sparse_routed:
                sparse_served = sum(
                    v["served"] for k, v in
                    snap["kernel"]["folds"].items()
                    if k.startswith("blocksparse"))
                sparse_keys = [k for k in snap["executor"]["keys"]
                               if len(k) >= 8 and k[7] != "dense"]
                if sparse_served == 0 or not sparse_keys:
                    # a policy that routes buckets sparse but never
                    # executes a sparse-keyed executable is dead weight
                    print(f"SMOKE FAIL: kernel policy routes buckets "
                          f"{sparse_routed} blocksparse but sparse "
                          f"executables never served (folds "
                          f"{snap['kernel']['folds']}, keys "
                          f"{snap['executor']['keys']})",
                          file=sys.stderr)
                    return 1
                bad_num = {b: d for b, d in
                           report["kernel"]["numerics_max_diff"].items()
                           if d > 1e-3}
                if bad_num:
                    print(f"SMOKE FAIL: block-sparse kernel numerics "
                          f"diverge from the dense+mask reference: "
                          f"{bad_num}", file=sys.stderr)
                    return 1
        if recycle_policy is not None and args.converge_tol > 0:
            rec = snap["recycle"]
            if rec["recycles_skipped"] == 0 and rec["retired_early"] == 0:
                # a convergence-injected workload that never early-exits
                # means the step scheduler is dead weight — fail loudly
                print(f"SMOKE FAIL: --recycle-sched with converge-tol "
                      f"{args.converge_tol} never early-exited "
                      f"(recycle stats {rec})", file=sys.stderr)
                return 1
            if args.continuous and rec["row_admissions"] == 0:
                # a skewed-convergence workload under load that never
                # refills a freed row means the continuous batcher is
                # dead weight — fail loudly
                print(f"SMOKE FAIL: --continuous with converge-tol "
                      f"{args.converge_tol} never admitted a row "
                      f"(recycle stats {rec})", file=sys.stderr)
                return 1
        if args.cascade:
            casc = snap["cascade"]
            if casc["cross_tier_hits"]:
                # the tripwire phase 17 pins to 0: equal draft and
                # flagship cache keys mean a keying regression that
                # could serve draft structures to flagship callers
                print(f"SMOKE FAIL: {casc['cross_tier_hits']} "
                      f"cross-tier cache key hits — tier keying "
                      f"regressed", file=sys.stderr)
                return 1
            if 0.0 < args.draft_accept_rate < 1.0 and (
                    casc["draft_accepted"] == 0
                    or casc["escalated"] == 0):
                print(f"SMOKE FAIL: cascade with accept-rate "
                      f"{args.draft_accept_rate} never exercised both "
                      f"paths (cascade stats {casc})", file=sys.stderr)
                return 1
        if args.express_rate > 0 and \
                snap.get("express", {}).get("served", 0) == 0:
            print(f"SMOKE FAIL: --express-rate {args.express_rate} "
                  f"but no express request served (express stats "
                  f"{snap.get('express')})", file=sys.stderr)
            return 1
        if recycle_policy is not None and args.cross_bucket \
                and snap["recycle"]["cross_bucket_admissions"] == 0:
            # a mixed-bucket workload that never admitted across
            # buckets means the cross-bucket batcher is dead weight —
            # fail loudly (independent of convergence injection: freed
            # rows also come from under-filled formation)
            print(f"SMOKE FAIL: --cross-bucket never admitted "
                  f"across buckets (recycle stats {snap['recycle']})",
                  file=sys.stderr)
            return 1
        extra = (f", {cache_snap['hits']} cache hits, "
                 f"{cache_snap['coalesced']} coalesced"
                 if cache_on else "")
        if mesh_policy is not None:
            extra += f", mesh folds {(snap.get('mesh') or {}).get('folds')}"
        if kernel_policy is not None:
            extra += (f", kernel folds "
                      f"{(snap.get('kernel') or {}).get('folds')}")
        if args.cascade:
            extra += (f", cascade "
                      f"{snap['cascade']['draft_accepted']} accepted / "
                      f"{snap['cascade']['escalated']} escalated")
        if args.express_rate > 0:
            extra += (f", express "
                      f"{snap.get('express', {}).get('served', 0)} "
                      f"served")
        if recycle_policy is not None:
            extra += (f", {report['executor_steps']} executor steps "
                      f"({snap['recycle']['recycles_skipped']} recycles "
                      f"skipped, {snap['recycle']['preemptions']} "
                      f"preemptions)")
            if args.continuous:
                extra += (f", rows occupied "
                          f"{report['rows_occupied_fraction']} "
                          f"({report['row_admissions']} row admissions)")
            if args.cross_bucket:
                extra += (f", {report['cross_bucket_admissions']} "
                          f"cross-bucket admits "
                          f"({report['cross_bucket_refusals']} refused, "
                          f"waste admitted "
                          f"{report['padding_waste_admitted']})")
        print(f"SMOKE OK: {snap['served']} folds, 0 shed/errors{extra}",
              file=sys.stderr)
    return 0


def _check_chaos_smoke(args, snap, failures, poison_results,
                       retry_on: bool, plan=None) -> int:
    """Chaos tripwire (serve_smoke.sh phase 5): under seeded faults the
    hardened scheduler must leave ZERO collateral damage — every ticket
    terminal, every innocent request ok, each poison request quarantined
    within the bisection bound, and nothing hung. With step-loop carry
    checkpointing on (ISSUE 14, --checkpoint-every), recovery cost is
    additionally bounded: measured recycles_lost must stay within
    checkpoint_every x the transient failures actually injected (the
    requeue-from-zero baseline loses ~num_recycles x survivors
    instead)."""
    import math

    problems = []
    if failures:
        # includes caller-side FoldTicket.result timeouts == hung
        # tickets, and any innocent non-ok terminal state
        problems.append(f"{len(failures)} innocent failures "
                        f"(first: {failures[0]})")
    innocent_bad = snap["shed"] + snap["errors"] + snap["rejected"]
    if innocent_bad:
        problems.append(f"{innocent_bad} shed/error/rejected outcomes "
                        "among innocent requests")
    if snap["served"] == 0:
        problems.append("0 served")
    if args.duration_s <= 0 and len(poison_results) != args.chaos_poison:
        problems.append(f"{len(poison_results)} poison submissions, "
                        f"expected {args.chaos_poison}")
    if args.chaos_poison and not poison_results:
        # duration mode can cycle the schedule without ever reaching a
        # poison slot — that run proved nothing, fail it loudly
        problems.append("no poison requests were submitted")
    # the quarantine is KEYED: N submissions of one poison (duration
    # mode cycles the schedule; duplicates fail fast) still hold
    # exactly one key, so compare against distinct poisons submitted
    distinct = len({pr["poison"] for pr in poison_results})
    if retry_on:
        quarantined = snap["resilience"]["quarantine"]["quarantined"]
        if quarantined != distinct:
            problems.append(f"{quarantined} quarantined keys, expected "
                            f"exactly {distinct} (distinct poisons "
                            "submitted)")
        # the log2 bound models BISECTION executions only, which is
        # exact for raise-mode poisons (their batches always fail
        # deterministically before the transient draw); a nan-mode
        # poison's batch can fail transiently and be re-enqueued any
        # number of times before validation ever sees its output, so
        # attempts legitimately exceeds the bisection bound there
        bound = int(math.log2(max(args.max_batch, 1))) + 1
        for pr in poison_results:
            if pr["status"] != "poisoned":
                problems.append(f"poison {pr['request_id']} resolved "
                                f"{pr['status']!r}, not 'poisoned'")
            elif args.chaos_poison_mode == "raise" \
                    and pr["attempts"] > bound:
                problems.append(
                    f"poison {pr['request_id']} took {pr['attempts']} "
                    f"batch executions > log2(max_batch)+1 = {bound}")
    if retry_on and getattr(args, "checkpoint_every", 0):
        # bounded recovery (ISSUE 14): each transient mid-loop failure
        # may cost at most checkpoint_every recycles of progress; the
        # injected-fault counts are the failure census
        res = snap["resilience"]
        injected = (plan.snapshot()["injected"] if plan is not None
                    else {})
        n_fail = (injected.get("exec_error", 0)
                  + injected.get("step_fail", 0)
                  + res.get("watchdog_fires", 0))
        bound = args.checkpoint_every * max(1, n_fail)
        if res.get("recycles_lost", 0) > bound:
            problems.append(
                f"recycles_lost {res.get('recycles_lost')} > "
                f"checkpoint_every x failures = {bound} "
                f"({n_fail} injected/watchdog failures)")
    if problems:
        print("SMOKE FAIL (chaos): " + "; ".join(problems),
              file=sys.stderr)
        return 1
    inj = snap.get("resilience", {})
    extra = ""
    if retry_on and (getattr(args, "checkpoint_every", 0)
                     or getattr(args, "row_isolation", False)):
        extra = (f", {inj.get('checkpoint_resumes', 0)} checkpoint "
                 f"resumes ({inj.get('recycles_lost', 0)} recycles "
                 f"lost), {inj.get('row_poison_isolations', 0)} row "
                 f"poison isolations")
    print(f"SMOKE OK (chaos): {snap['served']} folds under injected "
          f"faults, {snap['retried']} retries, "
          f"{inj.get('bisections', 0)} bisections, "
          f"{snap['poisoned']} poisoned, 0 innocent casualties"
          f"{extra}", file=sys.stderr)
    return 0


def _run_features(args) -> int:
    """--feature-latency-ms / --feature-pool: the two-stage feature
    pipeline vs the serialized featurize-in-submit baseline (ISSUE 10).

    Requests enter RAW (AA strings + raw MSA rows) in two open-loop
    waves — submit a wave without waiting per-request, then wait it
    out, then the next (wave 2's duplicates of wave-1 keys exercise
    the feature CACHE; in-wave duplicates exercise featurize
    COALESCING). `--feature-pool 0` is the baseline: each submitter
    thread pays the synthetic featurize latency inline before
    submitting, exactly the pre-pipeline cost model. `--feature-pool
    N` runs a serve.FeaturePool of N workers + FeatureCache, so
    featurization overlaps the executor and scales independently of
    the submit path (ParaFold's separately-scaled pools).

    One JSON line (`"metric": "serve_loadtest_features"`): folds/hour,
    executor idle fraction (1 - exec_busy/wall — the number the
    pipeline exists to drive down), featurize p50/p99, feature cache
    hit ratio, featurize executions vs unique keys. With --smoke:
    FAILS on any non-ok outcome, on any duplicate featurize execution
    for a coalesced/cached key (executions must equal unique keys
    featurized), and — with duplicate traffic — on a dead feature
    cache (hit ratio 0)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from alphafold2_tpu import serve
    from alphafold2_tpu.cache import FeatureCache, feature_key
    from alphafold2_tpu.data.featurize import detokenize
    from alphafold2_tpu.data.synthetic import synthetic_requests
    from alphafold2_tpu.utils.profiling import StepTimer

    lengths = tuple(int(x) for x in args.lengths.split(",") if x)
    if args.buckets:
        policy = serve.BucketPolicy(
            int(x) for x in args.buckets.split(",") if x)
    else:
        policy = serve.BucketPolicy.powers_of_two(
            min(lengths), max(max(lengths), min(lengths)))
    model, params = _build_tiny_model(args, jax, jnp, policy)

    latency_s = args.feature_latency_ms / 1000.0
    pipelined = args.feature_pool > 0
    # featurize chaos (ISSUE 14): --chaos threads the plan into the
    # pool, so --chaos-featurize-rate exercises the CPU stage's error
    # fan-out / deadline paths over a real workload
    plan, retry = _build_resilience(args)
    pool_obj = None
    if pipelined:
        pool_obj = serve.FeaturePool(
            workers=args.feature_pool,
            cache=FeatureCache(),
            latency_s=latency_s,
            faults=plan)
    tracer = None
    if args.trace_path:
        from alphafold2_tpu import obs
        tracer = obs.Tracer(jsonl_path=args.trace_path,
                            slow_k=args.trace_slow_k)
    executor = serve.FoldExecutor(model, params,
                                  max_entries=policy.num_buckets,
                                  model_tag="serve_loadtest")
    metrics = serve.ServeMetrics(args.metrics_path)
    config = serve.SchedulerConfig(
        max_batch_size=args.max_batch, max_wait_ms=args.max_wait_ms,
        num_recycles=args.num_recycles, msa_depth=args.msa_depth)
    scheduler = serve.Scheduler(executor, policy, config, metrics,
                                model_tag="serve_loadtest",
                                tracer=tracer, feature_pool=pool_obj,
                                retry=retry)

    warmup_timer = StepTimer()
    with warmup_timer.measure():
        compiles = scheduler.warmup()
    scheduler.start()
    if plan is not None:
        plan.arm()

    # raw prototypes: detokenize back to AA strings (tokenize is an
    # exact inverse over the synthetic token range), so the run
    # exercises the real string -> tokens path
    proto_pool = synthetic_requests(
        jax.random.PRNGKey(1), num=max(args.requests, 64),
        lengths=lengths, msa_depth=args.msa_depth)
    raw_pool = []
    for p in proto_pool:
        msa_rows = (None if p.msa is None
                    else [detokenize(row) for row in np.asarray(p.msa)])
        raw_pool.append((detokenize(np.asarray(p.seq)), msa_rows))

    import copy
    sched_args = copy.copy(args)
    sched_args.dup_rate = args.feature_dup_rate
    sched_args.duration_s = 0.0
    schedule = _zipf_schedule(sched_args, len(raw_pool))

    failures = []
    statuses = {}
    lock = threading.Lock()
    fold_digest = serve.featurizer_config_digest()
    unique_keys = {feature_key(raw_pool[j][0], raw_pool[j][1],
                               config_digest=fold_digest)
                   for j in set(schedule)}

    def submit_one(i):
        seq_str, msa_rows = raw_pool[schedule[i]]
        raw = serve.RawFoldRequest(seq=seq_str, msa=msa_rows)
        if not pipelined and latency_s > 0:
            time.sleep(latency_s)    # serialized featurize-in-submit
        return raw, scheduler.submit_raw(raw)

    def run_wave(indices):
        tickets = []
        t_lock = threading.Lock()
        it = iter(indices)

        def worker():
            while True:
                with t_lock:
                    i = next(it, None)
                if i is None:
                    return
                try:
                    raw, ticket = submit_one(i)
                except Exception as exc:
                    with lock:
                        failures.append(repr(exc))
                    continue
                with t_lock:
                    tickets.append((raw, ticket))

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(max(args.concurrency, 1))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for raw, ticket in tickets:
            try:
                resp = ticket.result(timeout=600)
            except Exception as exc:
                with lock:
                    failures.append(repr(exc))
                continue
            with lock:
                statuses[resp.status] = statuses.get(resp.status, 0) + 1
            if not resp.ok:
                if plan is not None and resp.error \
                        and "injected featurize" in resp.error:
                    # chaos-injected featurize failure: the expected
                    # outcome under --chaos-featurize-rate (counted in
                    # statuses + the chaos section), not a harness bug
                    continue
                with lock:
                    failures.append(f"{resp.status}: {resp.error}")
            elif resp.coords.shape != (raw.length, 3) or \
                    not np.isfinite(resp.coords).all():
                with lock:
                    failures.append(
                        f"bad coords {resp.coords.shape} for "
                        f"n={raw.length}")

    t0 = time.monotonic()
    half = max(1, args.requests // 2)
    run_wave(range(half))
    run_wave(range(half, args.requests))
    serving_wall = time.monotonic() - t0
    if pool_obj is not None:
        pool_obj.stop()
    scheduler.stop()

    snap = scheduler.serve_stats()
    busy = snap.get("exec_busy_s", 0.0)
    idle_fraction = max(0.0, 1.0 - busy / serving_wall) \
        if serving_wall > 0 else 0.0
    feat = snap.get("featurize")
    report = {
        "metric": "serve_loadtest_features",
        "platform": args.platform,
        "mode": "pipelined" if pipelined else "serialized",
        "feature_latency_ms": args.feature_latency_ms,
        "feature_pool": args.feature_pool,
        "feature_dup_rate": args.feature_dup_rate,
        "requests": args.requests,
        "unique_raw_keys": len(unique_keys),
        "served": snap["served"],
        "batches": snap["batches"],
        "folds_per_hour": round(
            snap["served"] / serving_wall * 3600.0, 1)
        if serving_wall else 0.0,
        "serving_wall_s": round(serving_wall, 3),
        "warmup_s": round(warmup_timer.mean * warmup_timer.count, 3),
        "compiles": compiles,
        "executor_busy_s": round(busy, 3),
        "executor_idle_fraction": round(idle_fraction, 4),
        "statuses": statuses,
        "shed": snap["shed"],
        "errors": snap["errors"],
        "rejected": snap["rejected"],
        "failures": failures[:8],
    }
    if plan is not None:
        report["chaos"] = plan.snapshot()
    if feat is not None:
        cache_snap = feat.get("cache", {})
        report["featurize"] = {
            "executions": feat["executions"],
            "submissions": feat["submissions"],
            "coalesced": feat["coalesced"],
            "cache_hits": feat["cache_hits"],
            "errors": feat["errors"],
            "p50_s": round(feat["featurize_p50_s"], 4),
            "p99_s": round(feat["featurize_p99_s"], 4),
            "hit_ratio": round(cache_snap.get("hit_ratio", 0.0), 4),
        }
    if tracer is not None:
        tracer.close()
        report["trace_path"] = args.trace_path
        report["traces_completed"] = tracer.completed
    if args.prom_path:
        from alphafold2_tpu import obs
        obs.write_prometheus(args.prom_path)
        report["prom_path"] = args.prom_path
    metrics.close()
    print(json.dumps(report))

    if not args.smoke:
        return 0
    problems = []
    bad = snap["shed"] + snap["errors"] + snap["rejected"] + len(failures)
    if bad or snap["served"] == 0:
        problems.append(f"{bad} bad outcomes, {snap['served']} served")
    if pipelined and feat is not None and plan is None:
        # zero duplicate featurize work: every unique key featurizes
        # exactly once — duplicates either coalesced in flight or hit
        # the cache, never re-executed (not checkable under chaos:
        # injected featurize failures legitimately end a key's attempt)
        if feat["executions"] != len(unique_keys):
            problems.append(
                f"{feat['executions']} featurize executions != "
                f"{len(unique_keys)} unique raw keys (duplicate "
                f"featurize work)")
        if args.feature_dup_rate > 0 and feat["cache_hits"] == 0:
            problems.append("duplicate raw traffic with 0 feature "
                            "cache hits")
    if problems:
        print("SMOKE FAIL (features): " + "; ".join(problems),
              file=sys.stderr)
        return 1
    extra = ""
    if feat is not None:
        extra = (f", {feat['executions']} featurize execs / "
                 f"{feat['cache_hits']} hits / {feat['coalesced']} "
                 f"coalesced")
    print(f"SMOKE OK (features/{report['mode']}): {snap['served']} "
          f"folds, idle fraction {idle_fraction:.3f}{extra}",
          file=sys.stderr)
    return 0


def _run_fleet(args) -> int:
    """--replicas > 1: drive an in-process fleet (or its independent-
    replicas baseline with --fleet off) and report fleet-wide numbers.
    One JSON line, `"metric": "serve_loadtest_fleet"`."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from alphafold2_tpu import fleet, obs, serve
    from alphafold2_tpu.data.synthetic import synthetic_requests
    from alphafold2_tpu.utils.profiling import StepTimer

    lengths = tuple(int(x) for x in args.lengths.split(",") if x)
    if args.buckets:
        policy = serve.BucketPolicy(
            int(x) for x in args.buckets.split(",") if x)
    else:
        policy = serve.BucketPolicy.powers_of_two(
            min(lengths), max(max(lengths), min(lengths)))
    model, params = _build_tiny_model(args, jax, jnp, policy)

    fleet_on = args.fleet != "off"
    model_tag = "serve_loadtest@v1"
    deadline_s = args.deadline_s or None
    plan, retry = _build_resilience(args)
    config = serve.SchedulerConfig(
        max_batch_size=args.max_batch, max_wait_ms=args.max_wait_ms,
        num_recycles=args.num_recycles, msa_depth=args.msa_depth)
    tracer = None
    if args.trace_path:
        tracer = obs.Tracer(jsonl_path=args.trace_path,
                            slow_k=args.trace_slow_k)
    cache_kwargs = {}
    if args.cache_dir:
        cache_kwargs["disk_dir"] = args.cache_dir
    # --mesh-policy in fleet mode: each in-process replica pins its own
    # contiguous chunk of the shared device pool (separate hosts own
    # their chips outright in production), so concurrent replicas never
    # fight over a chip
    mesh_policy_factory = None
    if args.mesh_policy:
        devices = jax.devices()
        chunk = max(1, len(devices) // args.replicas)

        def mesh_policy_factory(i):
            sub = devices[i * chunk:(i + 1) * chunk] or devices[-chunk:]
            return _build_mesh_policy(args, model, params, policy, jax,
                                      devices=sub)

    fl = fleet.InProcessFleet(
        lambda: serve.FoldExecutor(model, params,
                                   max_entries=policy.num_buckets,
                                   faults=plan),
        policy, config, n_replicas=args.replicas, model_tag=model_tag,
        cache_kwargs=cache_kwargs, fleet=fleet_on, tracer=tracer,
        metrics_factory=lambda i: serve.ServeMetrics(
            f"{args.metrics_path}.r{i}"),
        retry=retry, faults=plan,
        mesh_policy_factory=mesh_policy_factory,
        recycle_policy=_build_recycle_policy(args))

    warmup_timer = StepTimer()
    with warmup_timer.measure():
        compiles = fl.warmup()
    fl.start()

    poisons = _poison_pool(args, jax)
    if plan is not None:
        for p in poisons:
            plan.add_poison(np.asarray(p.seq),
                            mode=args.chaos_poison_mode)
        plan.arm()

    pool_n = max(args.requests, 64)
    if args.duration_s > 0 and (args.cache == "on" or args.dup_rate > 0):
        pool_n = max(pool_n, 1024)
    pool = synthetic_requests(
        jax.random.PRNGKey(1), num=pool_n, lengths=lengths,
        msa_depth=args.msa_depth, deadline_s=deadline_s)
    schedule = _schedule_poison(_zipf_schedule(args, len(pool)),
                                len(poisons))

    # mid-run weight rollout: request index >= bump_at keys under the
    # new tag (count mode only; the shared counter makes exactly one
    # submitter perform the bump)
    bump_at = 0
    if args.rollout_at > 0 and args.duration_s <= 0:
        bump_at = max(1, int(args.requests * args.rollout_at))
    rolled_tag = model_tag + "+rolled"

    failures = []
    poison_results = []
    lock = threading.Lock()
    counter = [0]

    def run_submitter(stop_at, budget):
        while True:
            with lock:
                i = counter[0]
                if (stop_at and time.monotonic() >= stop_at) or \
                        (budget and i >= budget):
                    return
                counter[0] = i + 1
            if bump_at and i == bump_at:
                fl.bump_model_tag(rolled_tag)
            idx = schedule[i % len(schedule)]
            is_poison = idx < 0
            req_proto = poisons[-idx - 1] if is_poison else pool[idx]
            req = serve.FoldRequest(seq=req_proto.seq, msa=req_proto.msa,
                                    deadline_s=deadline_s)
            try:
                # round-robin by index: the dumb-load-balancer split the
                # router is supposed to beat
                resp = fl.submit(req, replica=i % args.replicas) \
                    .result(timeout=600)
            except Exception as exc:
                with lock:
                    failures.append(repr(exc))
                return
            if is_poison:
                with lock:
                    poison_results.append(
                        {"request_id": resp.request_id,
                         "status": resp.status,
                         "attempts": resp.attempts})
                continue
            if not resp.ok:
                with lock:
                    failures.append(f"{resp.status}: {resp.error}")
            elif resp.coords.shape != (req.length, 3) or \
                    not np.isfinite(resp.coords).all():
                with lock:
                    failures.append(
                        f"bad coords {resp.coords.shape} for "
                        f"n={req.length}")

    t0 = time.monotonic()
    stop_at = t0 + args.duration_s if args.duration_s > 0 else 0.0
    budget = 0 if args.duration_s > 0 else args.requests
    threads = [threading.Thread(target=run_submitter,
                                args=(stop_at, budget), daemon=True)
               for _ in range(max(args.concurrency, 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    serving_wall = time.monotonic() - t0

    # the rollout tripwire must EXERCISE the rejection path, not just
    # count a by-construction-zero: probe the live peer servers with a
    # straggler client still pinned to the PRE-bump tag, asking for a
    # key that was folded (and cached on its owner) before the bump —
    # the fleet must refuse (409), never return a value
    stale_probe = None
    if bump_at and fleet_on:
        from alphafold2_tpu.cache import fold_key
        from alphafold2_tpu.obs.registry import MetricsRegistry

        proto = pool[schedule[0]]          # Zipf rank-0: folded pre-bump
        old_key = fold_key(
            np.asarray(proto.seq),
            None if proto.msa is None else np.asarray(proto.msa),
            msa_depth=args.msa_depth, num_recycles=args.num_recycles,
            model_tag=model_tag)
        probe_reg = MetricsRegistry()
        straggler = fleet.PeerCacheClient(
            fl.registry, "old-tag-probe",
            rollout=fleet.RolloutState(model_tag, registry=probe_reg),
            metrics=probe_reg)
        returned = straggler.get(old_key)
        fetch = probe_reg.snapshot().get("fleet_peer_fetch_total",
                                         {"samples": []})
        refusals = sum(
            s["value"] for s in fetch["samples"]
            if s["labels"].get("outcome") == "stale_tag")
        stale_probe = {"returned_value": returned is not None,
                       "refusals_409": int(refusals)}

    fl.stop()

    st = fl.stats()
    agg = st["aggregate"]
    total = counter[0]
    hit_ratio = ((agg["cache_hits"] + agg["coalesced"]) / total
                 if total else 0.0)
    stale_tag_hits = sum(
        r.cache.peer.stale_tag_hits
        for r in fl.replicas
        if r.cache is not None and getattr(r.cache, "peer", None)
        is not None and hasattr(r.cache.peer, "stale_tag_hits"))
    peer_recoveries = sum(
        r.cache.peer.recoveries
        for r in fl.replicas
        if r.cache is not None and getattr(r.cache, "peer", None)
        is not None and hasattr(r.cache.peer, "recoveries"))
    forwards = 0
    fwd_metric = obs.get_registry().snapshot().get("fleet_forwards_total")
    if fwd_metric:
        forwards = int(sum(s["value"] for s in fwd_metric["samples"]))
    bad = sum(st["replicas"][r]["shed"] + st["replicas"][r]["errors"]
              + st["replicas"][r]["rejected"] for r in st["replicas"])

    report = {
        "metric": "serve_loadtest_fleet",
        "platform": args.platform,
        "replicas": args.replicas,
        "fleet_enabled": fleet_on,
        "requests": total,
        "unique_requests": len({schedule[i % len(schedule)]
                                for i in range(total)}),
        "dup_rate": args.dup_rate,
        "served": agg["served"],
        "batches": agg["batches"],
        "hit_ratio": round(hit_ratio, 4),
        "cache_hits": agg["cache_hits"],
        "coalesced": agg["coalesced"],
        "peer_hits": agg["peer_hits"],
        "forwards": forwards,
        "leader_promotions": agg["leader_promotions"],
        "peer_recoveries": peer_recoveries,
        "bad_outcomes": bad,
        "serving_wall_s": round(serving_wall, 3),
        "warmup_s": round(warmup_timer.mean * warmup_timer.count, 3),
        "compiles": compiles,
        "rollout": (None if not bump_at else {
            "at_request": bump_at,
            "old_tag": model_tag, "new_tag": rolled_tag,
            "model_epoch": st["fleet"]["model_epoch"],
            "stale_tag_hits": stale_tag_hits,
            "stale_probe": stale_probe}),
        "per_replica": {
            rid: {k: snap[k] for k in ("served", "batches", "shed",
                                       "errors", "rejected",
                                       "degraded", "poisoned",
                                       "retried")}
            for rid, snap in st["replicas"].items()},
        "failures": failures[:8],
    }
    if plan is not None:
        report["chaos"] = dict(plan.snapshot(),
                               poison_mode=args.chaos_poison_mode,
                               poison_results=poison_results)
    if tracer is not None:
        tracer.close()
        report["trace_path"] = args.trace_path
        report["traces_completed"] = tracer.completed
    if args.prom_path:
        obs.write_prometheus(args.prom_path)
        report["prom_path"] = args.prom_path
    print(json.dumps(report))

    if args.smoke:
        if bad or failures or agg["served"] == 0:
            print(f"SMOKE FAIL (fleet): {bad} bad outcomes, "
                  f"{len(failures)} failures, {agg['served']} served",
                  file=sys.stderr)
            return 1
        bad_poison = [p for p in poison_results
                      if p["status"] != "poisoned"]
        if bad_poison:
            print(f"SMOKE FAIL (fleet): poison requests not "
                  f"quarantined: {bad_poison}", file=sys.stderr)
            return 1
        if args.dup_rate > 0 and \
                agg["cache_hits"] + agg["coalesced"] == 0:
            print("SMOKE FAIL (fleet): duplicated workload with 0 "
                  "fleet-wide hits/coalesces", file=sys.stderr)
            return 1
        if stale_tag_hits:
            print(f"SMOKE FAIL (fleet): {stale_tag_hits} stale-tag "
                  "cache hits after the epoch bump", file=sys.stderr)
            return 1
        if stale_probe is not None and (stale_probe["returned_value"]
                                        or not stale_probe["refusals_409"]):
            print(f"SMOKE FAIL (fleet): old-tag probe not refused "
                  f"({stale_probe})", file=sys.stderr)
            return 1
        print(f"SMOKE OK (fleet): {agg['served']} folds across "
              f"{args.replicas} replicas, hit_ratio {hit_ratio:.3f}, "
              f"{forwards} forwards, 0 stale-tag hits",
              file=sys.stderr)
    return 0


def _driver_slo_report(args, samples, chaos_t, kill_t,
                       recovery_from=None):
    """Windowed SLO evaluation over the DRIVER's own observations
    (--procs mode): per-request completion times + latencies sliced
    into half-overlapping windows of --slo-window-s, each evaluated
    with obs.slo's one budget-math implementation. `auto` latency
    targets calibrate from the run's own healthy requests (completed
    before the first chaos event): 1.25 x healthy p99 + 0.3 s — above
    the healthy tail by construction, below the failover penalty the
    driver's backoff guarantees — so the kill window burns budget
    against the run's own baseline, not a machine-speed guess."""
    import dataclasses as _dc

    from alphafold2_tpu.obs.slo import SLOPolicy, evaluate_class
    from alphafold2_tpu.utils.profiling import percentile as _pct

    policy = SLOPolicy.parse(args.slo, window_s=args.slo_window_s)
    first_chaos = min(chaos_t.values()) if chaos_t else None
    healthy = [s for s in samples
               if first_chaos is None or s["t"] < first_chaos]
    healthy = healthy or samples
    classes = []
    for c in policy.classes:
        if c.target_s is None:
            lats = [s["lat"] for s in healthy
                    if s["ok"] and c.covers(s["bucket"])]
            lats = lats or [s["lat"] for s in healthy if s["ok"]] \
                or [0.0]
            c = _dc.replace(
                c, target_s=max(0.25, 1.25 * _pct(lats, 99) + 0.3))
        classes.append(c)
    t_end = max((s["t"] for s in samples), default=0.0)
    w = policy.window_s
    hop = max(w / 2.0, 0.25)
    windows = []
    t0 = 0.0
    while t0 <= t_end:
        in_w = [s for s in samples if t0 <= s["t"] < t0 + w]
        per_class = {}
        for c in classes:
            sel = [s for s in in_w if c.covers(s["bucket"])]
            ok = [s for s in sel if s["ok"]]
            good = sum(1 for s in ok if s["lat"] <= c.target_s)
            bad = sum(1 for s in sel if not s["ok"])
            res = evaluate_class(c, good, len(ok), bad, len(sel))
            per_class[c.name] = {
                "requests": len(sel),
                "latency_burn": res["latency"]["burn_rate"],
                "attainment": res["latency"]["attainment"],
                "availability_burn":
                    res.get("availability", {}).get("burn_rate", 0.0),
            }
        windows.append({"t0": round(t0, 3), "t1": round(t0 + w, 3),
                        "classes": per_class})
        t0 += hop

    def _burn(win):
        return max((c["latency_burn"] for c in win["classes"].values()),
                   default=0.0)

    max_burn = max((_burn(win) for win in windows), default=0.0)
    kill_burn = None
    if kill_t is not None:
        kill_burn = max(
            (_burn(win) for win in windows
             if win["t1"] > kill_t and win["t0"] < kill_t + 15.0),
            default=0.0)
    # the post-convergence recovery probe (controller mode), evaluated
    # as ONE window per class: traffic served by the healed fleet
    recovery = None
    if recovery_from is not None:
        rs = [s for s in samples if s["t"] >= recovery_from]
        per_class = {}
        for c in classes:
            sel = [s for s in rs if c.covers(s["bucket"])]
            ok = [s for s in sel if s["ok"]]
            good = sum(1 for s in ok if s["lat"] <= c.target_s)
            bad = sum(1 for s in sel if not s["ok"])
            res = evaluate_class(c, good, len(ok), bad, len(sel))
            per_class[c.name] = {
                "requests": len(sel),
                "latency_burn": res["latency"]["burn_rate"],
                "attainment": res["latency"]["attainment"],
            }
        recovery = {
            "from_t": round(recovery_from, 3),
            "samples": len(rs),
            "burn": max((v["latency_burn"]
                         for v in per_class.values()), default=0.0),
            "classes": per_class,
            "latencies_s": [round(s["lat"], 3) for s in rs],
        }
    return {
        "spec": args.slo,
        "window_s": w,
        "classes": {c.name: {"target_s": round(c.target_s, 4),
                             "percentile": c.percentile,
                             "buckets": list(c.buckets)}
                    for c in classes},
        "samples": len(samples),
        "windows": windows,
        "max_burn_rate": max_burn,
        "kill_t": None if kill_t is None else round(kill_t, 3),
        "kill_window_burn": kill_burn,
        "recovery": recovery,
    }


def _run_procs(args) -> int:
    """--procs N: drive a REAL multi-process fleet (fleet.procfleet)
    over HTTP with driver-side failover, inducing the --proc-* chaos
    schedule mid-run: one kill -9 + restart, one network partition,
    one rolling drain-restart, one spot preemption (--preempt-at:
    notice -> grace-budgeted drain -> kill -9, orphans adopted by the
    controller), plus an optional fleet-wide rollout.
    One JSON line, `"metric": "serve_loadtest_procs"`. With --smoke:
    FAILS unless every request (chaos notwithstanding) reached an ok
    terminal state, zero requests were lost, the drained replica
    exited 0, restarted replicas rejoined at the rolled tag, zero
    stale-tag hits, and the merged traces carry rpc (and, when a drain
    ran, drain) spans for obs_report."""
    import tempfile

    from alphafold2_tpu import serve
    from alphafold2_tpu.fleet.procfleet import ProcFleet

    n = args.procs
    lengths = tuple(int(x) for x in args.lengths.split(",") if x)
    if args.buckets:
        buckets = tuple(int(x) for x in args.buckets.split(",") if x)
    else:
        buckets = tuple(serve.BucketPolicy.powers_of_two(
            min(lengths), max(max(lengths), min(lengths))).edges)
    run_dir = args.proc_run_dir or tempfile.mkdtemp(
        prefix="procfleet_")
    model_tag = "procfleet@v1"
    rolled_tag = model_tag + "+rolled"

    fleet = ProcFleet(
        n, run_dir, model_tag=model_tag, buckets=buckets,
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        num_recycles=args.num_recycles,
        model={"dim": args.dim, "depth": args.depth,
               "msa_depth": args.msa_depth},
        mesh_policy=args.mesh_policy,
        mesh_hbm_gb=args.mesh_hbm_gb,
        recycle=(None if not args.recycle_sched else dict(
            converge_tol=args.converge_tol,
            min_recycles=args.min_recycles,
            preempt=not args.no_preempt,
            stream=args.stream,
            continuous=args.continuous,
            cross_bucket=args.cross_bucket,
            cross_bucket_max_pad_frac=args.cross_bucket_max_pad_frac,
            eager_form=args.eager_form)),
        slo=args.slo, slo_window_s=args.slo_window_s,
        key_log=bool(args.controller),
        preemption=bool(args.preempt_at),
        controller=(None if not args.controller else dict(
            {"min_replicas": args.scale_min} if args.scale_min else {},
            **({"max_replicas": args.scale_max}
               if args.scale_max else {}),
            interval_s=0.5, heartbeat_timeout_s=4.0,
            cooldown_s=6.0, warm=True)))
    print(f"procfleet: starting {n} replica processes under {run_dir}"
          + (" + controller" if args.controller else ""),
          file=sys.stderr)
    try:
        return _drive_procs(args, fleet, run_dir, model_tag,
                            rolled_tag)
    finally:
        # children only exit on SIGTERM: any driver exception (or a
        # partial start) must not orphan N warm replica processes
        fleet.stop()


def _drive_procs(args, fleet, run_dir, model_tag, rolled_tag) -> int:
    import jax
    import numpy as np

    from alphafold2_tpu import obs, serve
    from alphafold2_tpu.data.synthetic import synthetic_requests
    from alphafold2_tpu.fleet.procfleet import FleetClient
    from alphafold2_tpu.obs.trace import NULL_TRACE

    n = args.procs
    lengths = tuple(int(x) for x in args.lengths.split(",") if x)
    deadline_s = args.deadline_s or None
    controller_on = bool(args.controller)
    wave = None
    if args.traffic_wave:
        try:
            f0, f1, mult = args.traffic_wave.split(":")
            wave = (float(f0), float(f1), int(mult))
            if not (0.0 <= wave[0] < wave[1] <= 1.0) or wave[2] < 1:
                raise ValueError(args.traffic_wave)
        except ValueError:
            print(f"serve_loadtest: bad --traffic-wave "
                  f"{args.traffic_wave!r} (want F0:F1:MULT, "
                  f"0 <= F0 < F1 <= 1, MULT >= 1)", file=sys.stderr)
            return 2
    fleet.start()

    tracer = None
    driver_trace_path = ""
    if args.trace_path:
        driver_trace_path = args.trace_path + ".driver"
        # fresh file: the merge at the end rewrites args.trace_path
        try:
            os.remove(driver_trace_path)
        except OSError:
            pass
        # origin-tagged (ISSUE 15): the driver's records merge into
        # the fleet set and its submits carry trace contexts the
        # replicas' continued traces stitch under
        tracer = obs.Tracer(jsonl_path=driver_trace_path,
                            slow_k=args.trace_slow_k, origin="driver")
    client_retry = None
    if args.slo:
        # a deliberately heavy failover backoff: a request that hits
        # the killed replica pays >= backoff_base_s on top of its
        # refold, putting it decisively past the auto-calibrated
        # latency target — the kill window's burn rate is then a
        # guaranteed signal, not a timing coin-flip
        client_retry = serve.RetryPolicy(
            max_attempts=4, backoff_base_s=0.75, backoff_max_s=1.5)
    client = FleetClient(
        [h.frontdoor_url for h in fleet.replicas],
        retry=client_retry,
        result_timeout_s=180.0)
    if args.buckets:
        bucket_edges = tuple(int(x) for x in args.buckets.split(",")
                             if x)
    else:
        bucket_edges = tuple(serve.BucketPolicy.powers_of_two(
            min(lengths), max(max(lengths), min(lengths))).edges)
    bucketer = serve.BucketPolicy(bucket_edges)

    pool = synthetic_requests(
        jax.random.PRNGKey(1), num=max(args.requests, 64),
        lengths=lengths, msa_depth=args.msa_depth,
        deadline_s=deadline_s)
    schedule = _zipf_schedule(args, len(pool))
    budget = args.requests

    # one-shot chaos triggers, pinned to request indices; victims are
    # distinct replicas so the three faults never stack on one process
    # (requires n >= 3 to exercise all three; with fewer they share).
    # max(1, ...): a small budget must still fire a requested fault —
    # int() truncating to 0 would silently mean "never"
    def _trigger(fraction):
        return max(1, int(budget * fraction)) if fraction else 0

    kill_at = _trigger(args.proc_kill_at)
    part_at = _trigger(args.proc_partition_at)
    drain_at = _trigger(args.proc_drain_at)
    preempt_at = _trigger(args.preempt_at)
    bump_at = _trigger(args.rollout_at)
    kill_victim = n - 1
    part_victim = 1 % n
    drain_victim = 0
    # the preempt victim dodges the kill victim when both are armed
    # (a preempted-then-killed process would test neither verb)
    preempt_victim = max(0, n - 1 - (1 if kill_at else 0))
    events = []
    events_lock = threading.Lock()
    fired = set()
    failures = []
    statuses = {}
    lock = threading.Lock()
    counter = [0]
    burst_box = {"tickets": [], "transport": None}
    drain_rc = [None]
    rolled = {"tag": None}    # set once the fleet-wide rollout fired
    # driver-side SLO evidence (ISSUE 15): per-request completion time
    # (relative to serving start) + latency + native bucket + outcome,
    # and when each chaos verb actually fired — the offline windowed
    # burn-rate evaluation slices these
    run_t0 = [0.0]
    slo_samples = []
    chaos_t = {}

    def _note(event, **kw):
        with events_lock:
            events.append(dict({"event": event}, **kw))

    def _fire(name, i, fn):
        with events_lock:
            if name in fired:
                return
            fired.add(name)
        chaos_t.setdefault(name,
                           time.monotonic() - run_t0[0])
        fn(i)

    def _reannounce(index):
        """Control-plane duty on rejoin: a replica that was down when
        the rollout fired never heard the bump — re-announce the
        current tag (idempotent for replicas that already rolled or
        rejoined from a post-bump persisted epoch)."""
        if rolled["tag"]:
            resp = fleet._admin_post(index, "/admin/rollout",
                                     {"tag": rolled["tag"]})
            _note("reannounced", replica=index, resp=resp)

    restart_threads = []

    def _do_kill(i):
        _note("kill", at_request=i, replica=kill_victim)
        rc = fleet.kill(kill_victim)
        _note("killed", rc=rc)
        if controller_on:
            # NO operator restart: the controller's reconcile loop
            # must notice the missing endpoint and restore quorum by
            # spawning a replacement — that's the thing under test
            return

        def _restart():
            fleet.restart(kill_victim)
            _reannounce(kill_victim)
            _note("restarted", replica=kill_victim,
                  healthz=fleet.healthz(kill_victim))

        t = threading.Thread(target=_restart, daemon=True)
        restart_threads.append(t)
        t.start()

    def _do_partition(i):
        _note("partition", at_request=i, replica=part_victim,
              duration_s=args.proc_partition_s)
        fleet.partition(part_victim, args.proc_partition_s)

    preempt_box = {"rc": None, "orphans": None}

    def _do_preempt(i):
        # spot reclaim (ISSUE 20): notice + timer kill via the fleet
        # verb; NO driver restart either way — with the controller on,
        # quorum restore replaces the member, and without it the
        # survivors absorb the traffic through client failover. The
        # victim's own exit line reports what it spilled.
        _note("preempt", at_request=i, replica=preempt_victim,
              grace_s=args.preempt_grace_s)
        h = fleet.replicas[preempt_victim]
        fleet.preempt(preempt_victim, grace_s=args.preempt_grace_s)

        def _reap():
            try:
                rc = h.proc.wait(args.preempt_grace_s + 120)
            except Exception:
                return
            preempt_box["rc"] = rc
            try:
                with open(h.log_path) as fh:
                    for line in fh:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if rec.get("preempted"):
                            preempt_box["orphans"] = rec.get("orphans")
            except OSError:
                pass
            _note("preempted", rc=preempt_box["rc"],
                  orphans=preempt_box["orphans"])

        t = threading.Thread(target=_reap, daemon=True)
        restart_threads.append(t)   # joined before the truth snapshot
        t.start()

    def _do_drain(i):
        # burst a few submits straight at the victim so the drain has
        # in-flight work to finish — their traces carry the drain span
        transport = client.transports[drain_victim]
        reqs = synthetic_requests(
            jax.random.PRNGKey(4242), num=2 * args.max_batch,
            lengths=lengths, msa_depth=args.msa_depth)
        tickets = []
        for r in reqs:
            req = serve.FoldRequest(seq=r.seq, msa=r.msa,
                                    deadline_s=deadline_s)
            try:
                tickets.append((req, transport.submit(req)))
            except Exception:
                tickets.append((req, None))   # raced the drain: refold
        burst_box["tickets"] = tickets
        burst_box["transport"] = transport
        _note("drain", at_request=i, replica=drain_victim,
              burst=len(tickets))
        drain_rc[0] = fleet.sigterm(drain_victim)
        _note("drained", rc=drain_rc[0])
        fleet.restart(drain_victim)
        _reannounce(drain_victim)
        _note("drain_restarted", replica=drain_victim,
              healthz=fleet.healthz(drain_victim))

    def _submit_one(i, via=None):
            proto = pool[schedule[i % len(schedule)]]
            req = serve.FoldRequest(seq=proto.seq, msa=proto.msa,
                                    deadline_s=deadline_s)
            trace = (tracer.start_trace(req.request_id) if tracer
                     else NULL_TRACE)
            t_submit = time.monotonic()
            # an over-length request (no bucket admits it) still gets a
            # sample — attributed to its raw length, which only the
            # bucketless "all" class covers; bucket_for raising here
            # would kill the submitter thread from inside the very
            # except handler that records failures
            try:
                req_bucket = bucketer.bucket_for(req.length)
            except ValueError:
                req_bucket = req.length

            def _sample(ok):
                now = time.monotonic()
                with lock:
                    slo_samples.append(
                        {"t": now - run_t0[0],
                         "lat": now - t_submit,
                         "bucket": req_bucket,
                         "ok": ok})

            try:
                resp = (via or client).fold(req, hint=i % n,
                                            trace=trace)
            except Exception as exc:
                trace.finish("error", error=repr(exc))
                _sample(False)
                with lock:
                    failures.append(repr(exc))
                return
            # the driver never folds: its traces are forwarded-sourced
            # so obs_report's fold-span rule applies to replica traces
            trace.finish(resp.status, source="forwarded",
                         error=resp.error)
            _sample(bool(resp.ok))
            with lock:
                statuses[resp.status] = statuses.get(resp.status, 0) + 1
            if not resp.ok:
                with lock:
                    failures.append(f"{resp.status}: {resp.error}")
            elif resp.coords.shape != (req.length, 3) or \
                    not np.isfinite(resp.coords).all():
                with lock:
                    failures.append(
                        f"bad coords {resp.coords.shape} for "
                        f"n={req.length}")

    def run_submitter():
        while True:
            with lock:
                i = counter[0]
                if i >= budget:
                    return
                counter[0] = i + 1
            if kill_at and i == kill_at:
                _fire("kill", i, _do_kill)
            if part_at and i == part_at:
                _fire("partition", i, _do_partition)
            if bump_at and i == bump_at:
                rolled["tag"] = rolled_tag
                if controller_on:
                    # ONE verb, controller-owned: fan-out with retry/
                    # backoff + convergence check; stragglers and late
                    # joiners are re-rolled by every later reconcile
                    _note("rollout", at_request=i,
                          report=fleet.controller.rollout(rolled_tag))
                else:
                    _note("rollout", at_request=i,
                          epochs=fleet.rollout(rolled_tag))
            if drain_at and i == drain_at:
                _fire("drain", i, _do_drain)
            if preempt_at and i == preempt_at:
                _fire("preempt", i, _do_preempt)
            _submit_one(i)

    # --traffic-wave F0:F1:MULT: while the shared counter sits inside
    # [F0, F1) of the budget, MULT x concurrency EXTRA threads submit
    # on top of it — a spike the controller must absorb by scaling up
    wave_counter = [0]

    def run_wave_submitter():
        lo = int(wave[0] * budget)
        hi = int(wave[1] * budget)
        while True:
            with lock:
                i = counter[0]
            if i >= budget or i >= hi:
                return
            if i < lo:
                time.sleep(0.02)
                continue
            with lock:
                wave_counter[0] += 1
                j = wave_counter[0]
            _submit_one(budget + j)

    t0 = time.monotonic()
    run_t0[0] = t0

    # with the controller on, a daemon watches the fleet's endpoint
    # set: the driver's client learns controller-spawned replicas (so
    # traffic actually reaches them) and the report gets a
    # replicas-over-time series
    replica_samples = []
    mon_stop = threading.Event()

    def _monitor():
        while not mon_stop.is_set():
            try:
                eps = fleet.endpoints()
                client.set_urls(list(eps.values()))
                with events_lock:
                    replica_samples.append(
                        {"t": round(time.monotonic() - run_t0[0], 2),
                         "replicas": len(eps)})
            except Exception:
                pass
            mon_stop.wait(0.5)

    monitor_thread = None
    if controller_on:
        monitor_thread = threading.Thread(target=_monitor, daemon=True)
        monitor_thread.start()

    threads = [threading.Thread(target=run_submitter, daemon=True)
               for _ in range(max(args.concurrency, 1))]
    if wave:
        threads += [threading.Thread(target=run_wave_submitter,
                                     daemon=True)
                    for _ in range(max(args.concurrency, 1) * wave[2])]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # settle the drain burst: every ticket owes a terminal; a slot the
    # drained process never answered (or answered with the transport
    # marker) is re-folded through the live fleet — zero lost requests
    burst_lost = 0
    for req, ticket in burst_box["tickets"]:
        resp = None
        if ticket is not None:
            try:
                resp = ticket.result(timeout=60)
            except Exception:
                resp = None
        if resp is not None and resp.status == "error" and resp.error \
                and "rpc_transport" in resp.error:
            resp = None
        if resp is None:
            try:
                resp = client.fold(req)
            except Exception as exc:
                burst_lost += 1
                failures.append(f"burst lost: {exc!r}")
                continue
        statuses[resp.status] = statuses.get(resp.status, 0) + 1
        if not resp.ok:
            failures.append(f"burst {resp.status}: {resp.error}")
    serving_wall = time.monotonic() - t0

    # a short budget can drain before the kill-restart finishes: the
    # tag snapshot and teardown below must not race a replica mid-boot
    for t in restart_threads:
        t.join(timeout=240)

    # with the controller on, the driver fired no recovery verbs —
    # give the reconcile loop a bounded window to finish restoring
    # quorum and converging the rollout before the truth snapshot
    converged = {"replicas": not controller_on,
                 "tag": not (controller_on and rolled["tag"])}
    if controller_on:
        target_min = args.scale_min or n
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            live_hz = {idx: hz for idx, hz in
                       ((idx, fleet.healthz(idx))
                        for idx in range(len(fleet.replicas)))
                       if hz and hz.get("running")}
            converged["replicas"] = len(live_hz) >= target_min
            if rolled["tag"]:
                live_tags = {(hz.get("model_tag") or hz.get("tag"))
                             for hz in live_hz.values()}
                converged["tag"] = live_tags == {rolled_tag}
            if all(converged.values()):
                break
            time.sleep(0.5)
        _note("converged", **converged)
    # post-convergence recovery probe: the driver fired no recovery
    # verbs, so the claim worth gating on is that the HEALED fleet —
    # restored quorum, rolled replicas — serves within SLO. A
    # replacement replica's boot can outlast the serving window on a
    # slow machine, so the main run's tail windows can't show this;
    # probe traffic after convergence can. Probes go through a FRESH
    # client built from CURRENT membership (the long-lived client's
    # failover set is add-only, so it still sprays the kill victim's
    # dead seat and pays the deliberately heavy backoff — a penalty
    # the healed fleet doesn't deserve) at the main run's concurrency
    # (so batches form at the warmed shapes), after one unmeasured
    # shakeout round that flushes any one-off cold compiles on the
    # replacement. Reported as slo["recovery"].
    recovery_from = None
    probe_count = [0]
    if controller_on and all(converged.values()) and args.slo:
        probe_client = FleetClient(
            list(fleet.endpoints().values()),
            retry=client_retry, result_timeout_s=180.0)
        conc = max(args.concurrency, 1)

        def _run_probes(lo, hi):
            ths = [threading.Thread(
                target=lambda off=k: [_submit_one(i, via=probe_client)
                                      for i in range(lo + off, hi,
                                                     conc)],
                daemon=True) for k in range(conc)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            probe_count[0] += hi - lo

        shake_n = 2 * conc
        probe_n = max(12, 4 * conc)
        # sequential shakeout: single submits form batch-of-1, the
        # one serving shape warmup doesn't pre-compile — flush that
        # cold path per bucket before measuring
        for i in range(budget, budget + shake_n):
            _submit_one(i, via=probe_client)
        probe_count[0] += shake_n
        recovery_from = time.monotonic() - run_t0[0]
        _run_probes(budget + shake_n, budget + shake_n + probe_n)
        _note("recovery_probe", probes=probe_n, shakeout=shake_n,
              from_t=round(recovery_from, 3))
    mon_stop.set()
    if monitor_thread is not None:
        monitor_thread.join(timeout=10)

    # fleet-wide truth BEFORE teardown: per-replica stats + health.
    # Controller mode: a dead handle is an EXPECTED shape (the kill
    # victim stays dead; its replacement is a new handle) — only live
    # replicas owe a tag
    per_replica, stale_tag_hits, replica_failovers = {}, 0, 0
    tags = {}
    for i, h in enumerate(fleet.replicas):
        snap = fleet.stats(i)
        hz = fleet.healthz(i)
        # a dead handle is an expected shape under the controller (the
        # kill victim stays dead) and for the preempt victim (reclaimed
        # for real; only a controller-spawned replacement succeeds it)
        dead_ok = controller_on or (preempt_at and i == preempt_victim)
        if not dead_ok or (hz and hz.get("running")):
            tags[h.replica_id] = (hz or {}).get("model_tag") or \
                (hz or {}).get("tag")
        if snap is None:
            per_replica[h.replica_id] = None
            continue
        extra = snap.get("extra", {})
        stale_tag_hits += extra.get("peer", {}).get("stale_tag_hits", 0)
        replica_failovers += snap.get("failovers", 0)
        per_replica[h.replica_id] = {
            "served": snap.get("served"),
            "batches": snap.get("batches"),
            "failovers": snap.get("failovers"),
            "drains": snap.get("drains"),
            "errors": snap.get("errors"),
            "rollout": extra.get("rollout"),
            # the replica-side SLO engine's view (ISSUE 15): which
            # classes it reports and whether each met its objectives
            "slo": (None if "slo" not in snap else {
                name: cls.get("ok")
                for name, cls in snap["slo"].get("classes",
                                                 {}).items()}),
        }
    # fleet observability artifacts (ISSUE 15): scrape each replica's
    # GET /metrics (the slo_* gauges + every serve_*/fleet_* series)
    # into --obs-fleet-out, the file set tools/obs_fleet.py aggregates
    scraped_slo_gauges = 0
    if args.obs_fleet_out:
        from urllib import request as _urlrequest
        os.makedirs(args.obs_fleet_out, exist_ok=True)
        for h in fleet.replicas:
            try:
                with _urlrequest.urlopen(h.frontdoor_url + "/metrics",
                                         timeout=5) as resp:
                    text = resp.read().decode("utf-8")
            except Exception:
                continue
            scraped_slo_gauges += text.count("\nslo_")
            with open(os.path.join(args.obs_fleet_out,
                                   f"{h.replica_id}.prom"), "w") as fh:
                fh.write(text)
    fleet.stop()

    span_counts = {}
    if tracer is not None:
        tracer.close()
        fleet.merge_traces(args.trace_path,
                           extra_paths=(driver_trace_path,))
        with open(args.trace_path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                for s in rec.get("spans", ()):
                    name = s.get("name", "?")
                    span_counts[name] = span_counts.get(name, 0) + 1
    if args.prom_path:
        from alphafold2_tpu import obs as _obs
        _obs.write_prometheus(args.prom_path)

    slo_report = None
    if args.slo and slo_samples:
        slo_report = _driver_slo_report(
            args, slo_samples, chaos_t, chaos_t.get("kill"),
            recovery_from=recovery_from)
        if args.obs_fleet_out:
            with open(os.path.join(args.obs_fleet_out,
                                   "slo_driver.json"), "w") as fh:
                json.dump(slo_report, fh, indent=1)

    expected_tag = rolled_tag if bump_at else model_tag
    total = counter[0] + len(burst_box["tickets"]) + wave_counter[0] \
        + probe_count[0]
    ctrl_snap = (fleet.controller.snapshot()
                 if controller_on and fleet.controller is not None
                 else None)
    report = {
        "metric": "serve_loadtest_procs",
        "platform": args.platform,
        "procs": n,
        "run_dir": run_dir,
        "requests": total,
        "serving_wall_s": round(serving_wall, 3),
        "requests_per_hour": round(total / serving_wall * 3600.0, 1)
        if serving_wall else 0.0,
        "statuses": statuses,
        "lost": burst_lost,
        "client": client.snapshot(),
        "replica_failovers": replica_failovers,
        "stale_tag_hits": stale_tag_hits,
        "drain_exit_code": drain_rc[0],
        "tags": tags,
        "expected_tag": expected_tag,
        "events": events,
        "per_replica": per_replica,
        "span_counts": {k: span_counts[k]
                        for k in ("rpc", "drain", "forward", "fold",
                                  "preempt", "adopt")
                        if k in span_counts},
        "preemption": (None if not preempt_at else {
            "victim": preempt_victim,
            "grace_s": args.preempt_grace_s,
            "exit_code": preempt_box["rc"],
            "orphans": preempt_box["orphans"],
            "adoptions": (None if ctrl_snap is None
                          else ctrl_snap.get("orphan_adoptions")),
        }),
        "trace_path": args.trace_path or None,
        "slo": slo_report,
        "slo_gauges_scraped": scraped_slo_gauges,
        "obs_fleet_out": args.obs_fleet_out or None,
        "controller": (None if ctrl_snap is None else dict(
            ctrl_snap,
            converged=converged,
            replicas_over_time=replica_samples[-240:])),
        "wave": (None if not wave else {
            "window": [wave[0], wave[1]], "mult": wave[2],
            "extra_requests": wave_counter[0]}),
        "failures": failures[:8],
    }
    print(json.dumps(report))

    if not args.smoke:
        return 0
    problems = []
    ok_n = statuses.get("ok", 0)
    if failures:
        problems.append(f"{len(failures)} failed requests "
                        f"(first: {failures[0]})")
    if burst_lost:
        problems.append(f"{burst_lost} LOST requests")
    if ok_n != total:
        problems.append(f"{ok_n}/{total} requests ok "
                        f"(statuses {statuses})")
    if drain_at and drain_rc[0] != 0:
        problems.append(f"drained replica exited {drain_rc[0]}, not 0")
    if kill_at and "killed" not in {e["event"] for e in events}:
        problems.append("kill never fired")
    if preempt_at:
        if "preempted" not in {e["event"] for e in events}:
            problems.append("preempt armed but the victim never "
                            "exited inside the reap window")
        elif preempt_box["rc"] != 0:
            problems.append(
                f"preempted replica exited {preempt_box['rc']}, not 0 "
                f"(the grace-budgeted drain should beat the hard "
                f"kill)")
        orphans_n = preempt_box["orphans"] or 0
        ads = ((ctrl_snap or {}).get("orphan_adoptions") or {})
        if controller_on and orphans_n and not ads.get("adopted"):
            problems.append(
                f"{orphans_n} orphans published but the controller "
                f"adopted none (expected active /admin/adopt "
                f"assignment, not lazy peer probes)")
        if tracer is not None and orphans_n \
                and not span_counts.get("preempt"):
            problems.append("orphans spilled but no preempt spans in "
                            "the merged traces")
        if tracer is not None and ads.get("adopted") \
                and not span_counts.get("adopt"):
            problems.append("controller adoptions landed but no adopt "
                            "spans in the merged traces")
    if stale_tag_hits:
        problems.append(f"{stale_tag_hits} stale-tag peer hits")
    bad_tags = {r: t for r, t in tags.items() if t != expected_tag}
    if bad_tags:
        problems.append(f"replicas on the wrong tag after "
                        f"rollout/restart: {bad_tags} "
                        f"(expected {expected_tag!r})")
    if controller_on:
        if not converged["replicas"]:
            problems.append(
                f"controller never restored quorum "
                f"(live < {args.scale_min or n} after the grace "
                f"window, zero operator verbs fired)")
        if rolled["tag"] and not converged["tag"]:
            problems.append(
                "controller never converged the rollout on the live "
                "replicas")
        if kill_at and ctrl_snap is not None \
                and ctrl_snap.get("scale_ups", 0) < 1:
            problems.append(
                "kill fired but the controller recorded no scale_up "
                "action (quorum restore should have spawned a "
                "replacement)")
    if tracer is not None and not span_counts.get("rpc"):
        problems.append("no rpc spans in the merged traces")
    if tracer is not None and drain_at and not span_counts.get("drain"):
        problems.append("drain ran but no drain spans in the traces")
    if args.slo:
        if slo_report is None:
            problems.append("--slo set but no SLO samples recorded")
        elif kill_at and "kill" in chaos_t:
            if not slo_report.get("kill_window_burn"):
                problems.append(
                    f"kill fired at t={chaos_t['kill']:.1f}s but the "
                    f"SLO burn rate stayed 0 in the killed window "
                    f"(max overall {slo_report['max_burn_rate']})")
        missing_slo = [rid for rid, per in per_replica.items()
                       if per is not None and not per.get("slo")]
        if missing_slo:
            problems.append(
                f"replicas reporting no serve_stats()['slo'] block: "
                f"{missing_slo}")
        if args.obs_fleet_out and scraped_slo_gauges == 0:
            problems.append("no slo_* gauges in the scraped /metrics "
                            "expositions")
    if problems:
        print("SMOKE FAIL (procs): " + "; ".join(problems),
              file=sys.stderr)
        return 1
    print(f"SMOKE OK (procs): {ok_n}/{total} ok across {n} processes "
          f"(client failover {client.snapshot()}, replica failovers "
          f"{replica_failovers}, drain rc {drain_rc[0]}, "
          f"0 stale-tag hits, spans {report['span_counts']})",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
