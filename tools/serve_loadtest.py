"""Offline load-test driver for `alphafold2_tpu.serve`.

Closed-loop harness: `--concurrency` submitter threads each submit a
synthetic request, wait for its result, and repeat — either for a fixed
`--requests` count or until `--duration-s` of wall clock. Warmup
(per-bucket compiles) is timed separately and excluded from throughput,
so the reported folds/hour is steady-state serving, comparable to
STATUS.md's raw `predict.fold` numbers — the delta between the two is
the scheduling + padding overhead this subsystem is supposed to keep
small.

Prints ONE JSON line:
  {"folds_per_hour": N, "padding_waste": F, "shed": 0, ...}

`--dup-rate F` makes fraction F of submissions repeats of earlier
sequences with a Zipf-ish popularity skew (rank r re-requested with
weight 1/(r+1) — the head-heavy shape of real serving traffic per
ParaFold's workload analysis). `--cache {auto,on,off}` controls the
content-addressed result cache + in-flight coalescing (auto = on iff
dup-rate > 0); the report then carries the cache section (hit ratio,
coalesced count) and `executor_calls_avoided` — requests that never
occupied the accelerator — next to folds/hour and padding waste.

`--trace-path F` enables request-scoped tracing (`obs.Tracer`): one
JSONL record per completed request covering submit -> terminal with
per-stage spans (submit/queue/batch_form/compile/fold/writeback),
rendered by `tools/obs_report.py`; `--prom-path F` dumps the process
metrics registry as Prometheus text exposition on exit. Together they
are the observability phase of tools/serve_smoke.sh.

`--smoke` (tools/serve_smoke.sh) exits 1 on ANY shed / timeout / error /
rejected request at trivial load — the serving regression tripwire. With
a duplicated workload (`--dup-rate` > 0, cache on) it additionally fails
when the cache never hits or any coalesced ticket fails to resolve.

Runs on CPU by default (__graft_entry__.force_cpu_fallback); pass
--platform ambient to target the real chip.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=64,
                    help="total requests (ignored when --duration-s > 0)")
    ap.add_argument("--duration-s", type=float, default=0.0,
                    help="run this many seconds instead of a fixed count")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed-loop submitter threads")
    ap.add_argument("--lengths", default="24,48,96",
                    help="comma-separated request lengths (cycled)")
    ap.add_argument("--buckets", default="",
                    help="comma-separated bucket edges; default: "
                         "powers-of-two covering --lengths")
    ap.add_argument("--msa-depth", type=int, default=3)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-wait-ms", type=float, default=25.0)
    ap.add_argument("--num-recycles", type=int, default=0)
    ap.add_argument("--deadline-s", type=float, default=0.0,
                    help="per-request deadline; 0 = none")
    ap.add_argument("--dup-rate", type=float, default=0.0,
                    help="fraction of submissions repeating an earlier "
                         "sequence (Zipf-ish popularity skew)")
    ap.add_argument("--cache", default="auto",
                    choices=("auto", "on", "off"),
                    help="result cache + coalescing; auto = on iff "
                         "--dup-rate > 0")
    ap.add_argument("--cache-dir", default="",
                    help="optional on-disk tier for the result cache")
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--depth", type=int, default=1)
    ap.add_argument("--metrics-path", default="/tmp/serve_loadtest.jsonl")
    ap.add_argument("--trace-path", default="",
                    help="enable request tracing (obs.Tracer) and append "
                         "one JSONL record per completed trace here; "
                         "render with tools/obs_report.py")
    ap.add_argument("--trace-slow-k", type=int, default=8,
                    help="slowest traces retained in serve_stats()")
    ap.add_argument("--prom-path", default="",
                    help="dump the process metrics registry as "
                         "Prometheus text exposition here on exit")
    ap.add_argument("--platform", default="cpu",
                    choices=("cpu", "ambient"))
    ap.add_argument("--smoke", action="store_true",
                    help="exit 1 on any shed/timeout/error/rejection")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    import __graft_entry__
    if args.platform == "cpu":
        __graft_entry__.force_cpu_fallback()

    import jax
    import jax.numpy as jnp

    from alphafold2_tpu import Alphafold2, serve
    from alphafold2_tpu.data.synthetic import synthetic_requests
    from alphafold2_tpu.utils.profiling import StepTimer

    lengths = tuple(int(x) for x in args.lengths.split(",") if x)
    if args.buckets:
        policy = serve.BucketPolicy(
            int(x) for x in args.buckets.split(",") if x)
    else:
        policy = serve.BucketPolicy.powers_of_two(
            min(lengths), max(max(lengths), min(lengths)))

    model = Alphafold2(dim=args.dim, depth=args.depth, heads=2,
                       dim_head=16, predict_coords=True,
                       structure_module_depth=1)
    n0 = policy.edges[0]
    seq = jnp.zeros((1, n0), jnp.int32)
    init_kwargs = dict(mask=jnp.ones((1, n0), bool))
    if args.msa_depth > 0:
        init_kwargs["msa"] = jnp.zeros((1, args.msa_depth, n0), jnp.int32)
        init_kwargs["msa_mask"] = jnp.ones((1, args.msa_depth, n0), bool)
    params = model.init(jax.random.PRNGKey(0), seq, **init_kwargs)

    executor = serve.FoldExecutor(model, params,
                                  max_entries=policy.num_buckets)
    metrics = serve.ServeMetrics(args.metrics_path)
    config = serve.SchedulerConfig(
        max_batch_size=args.max_batch, max_wait_ms=args.max_wait_ms,
        num_recycles=args.num_recycles, msa_depth=args.msa_depth)
    cache_on = args.cache == "on" or (args.cache == "auto"
                                      and args.dup_rate > 0)
    cache = None
    if cache_on:
        cache = serve.FoldCache(disk_dir=args.cache_dir or None)
    tracer = None
    if args.trace_path:
        from alphafold2_tpu import obs
        tracer = obs.Tracer(jsonl_path=args.trace_path,
                            slow_k=args.trace_slow_k)
    scheduler = serve.Scheduler(executor, policy, config, metrics,
                                cache=cache, model_tag="serve_loadtest",
                                tracer=tracer)

    warmup_timer = StepTimer()
    with warmup_timer.measure():
        compiles = scheduler.warmup()
    scheduler.start()

    import numpy as np

    deadline_s = args.deadline_s or None
    # duration-mode cache runs need unique headroom: a 64-prototype pool
    # under a 4096-entry schedule would force-duplicate almost every
    # submission regardless of --dup-rate. The report's
    # unique_requests/requests ratio is the effective duplicate rate.
    pool_n = max(args.requests, 64)
    if args.duration_s > 0 and (args.cache == "on" or args.dup_rate > 0):
        pool_n = max(pool_n, 1024)
    pool = synthetic_requests(
        jax.random.PRNGKey(1), num=pool_n,
        lengths=lengths, msa_depth=args.msa_depth, deadline_s=deadline_s)

    # submission schedule over prototype indices: with --dup-rate, a
    # submission repeats an ALREADY-USED prototype with probability
    # dup_rate, picking it Zipf-ishly (first-seen rank r with weight
    # 1/(r+1)) — duplicates are exact (same seq AND msa), so they are
    # cache/coalesce candidates. dup_rate=0 degenerates to the old
    # round-robin over unique prototypes.
    sched_rng = np.random.default_rng(2)
    schedule_len = args.requests if args.duration_s <= 0 else 4096
    schedule, used = [], []
    fresh_i = 0

    def zipf_pick():
        w = 1.0 / (np.arange(len(used)) + 1.0)
        return used[int(sched_rng.choice(len(used), p=w / w.sum()))]

    for _ in range(max(schedule_len, 1)):
        if used and sched_rng.random() < args.dup_rate:
            j = zipf_pick()
        elif fresh_i < len(pool):
            j = fresh_i
            fresh_i += 1
            used.append(j)
        elif args.dup_rate > 0:
            # unique budget exhausted on a duplicate-heavy run: an
            # explicit Zipf repeat, keeping `used` duplicate-free so the
            # 1/(rank+1) weights stay meaningful
            j = zipf_pick()
        else:
            # dup_rate=0: plain round-robin over the pool, exactly the
            # pre-cache behavior (no popularity skew in baselines)
            j = fresh_i % len(pool)
            fresh_i += 1
        schedule.append(j)

    failures = []
    lock = threading.Lock()
    counter = [0]

    def run_submitter(stop_at, budget):
        while True:
            with lock:
                i = counter[0]
                if (stop_at and time.monotonic() >= stop_at) or \
                        (budget and i >= budget):
                    return
                counter[0] = i + 1
            req_proto = pool[schedule[i % len(schedule)]]
            req = serve.FoldRequest(seq=req_proto.seq, msa=req_proto.msa,
                                    deadline_s=deadline_s)
            try:
                resp = scheduler.submit(req).result(timeout=600)
            except Exception as exc:
                with lock:
                    failures.append(repr(exc))
                return  # a broken loop would spin; one strike ends it
            if not resp.ok:
                with lock:
                    failures.append(f"{resp.status}: {resp.error}")
            elif resp.coords.shape != (req.length, 3) or \
                    not np.isfinite(resp.coords).all():
                with lock:
                    failures.append(
                        f"bad coords {resp.coords.shape} for n={req.length}")

    t0 = time.monotonic()
    stop_at = t0 + args.duration_s if args.duration_s > 0 else 0.0
    budget = 0 if args.duration_s > 0 else args.requests
    threads = [threading.Thread(target=run_submitter,
                                args=(stop_at, budget), daemon=True)
               for _ in range(max(args.concurrency, 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    serving_wall = time.monotonic() - t0
    scheduler.stop()

    snap = scheduler.serve_stats()
    total = counter[0]
    cache_snap = snap["cache"]
    avoided = cache_snap["hits"] + cache_snap["coalesced"]
    report = {
        "metric": "serve_loadtest",
        "platform": args.platform,
        "folds_per_hour": round(snap["served"] / serving_wall * 3600.0, 1),
        "requests_per_hour": round(total / serving_wall * 3600.0, 1),
        "serving_wall_s": round(serving_wall, 3),
        "warmup_s": round(warmup_timer.mean * warmup_timer.count, 3),
        "compiles": compiles,
        "bucket_edges": snap["bucket_edges"],
        "padding_waste": round(snap["padding_waste"], 4),
        "requests": total,
        "unique_requests": len({schedule[i % len(schedule)]
                                for i in range(total)}),
        "dup_rate": args.dup_rate,
        "served": snap["served"],
        "shed": snap["shed"],
        "errors": snap["errors"],
        "rejected": snap["rejected"],
        "batches": snap["batches"],
        "cache_enabled": cache_on,
        "cache_hit_ratio": round(cache_snap["hit_ratio"], 4),
        "coalesced": cache_snap["coalesced"],
        "executor_calls_avoided": avoided,
        "latency_by_bucket": snap["latency_by_bucket"],
        "executor": {k: snap["executor"][k]
                     for k in ("hits", "misses", "evictions")},
        "metrics_path": args.metrics_path,
        "failures": failures[:8],
    }
    if tracer is not None:
        tracer.close()
        slowest = snap["traces"]
        report["trace_path"] = args.trace_path
        report["traces_completed"] = tracer.completed
        report["slowest_trace_s"] = (slowest[0]["duration_s"]
                                     if slowest else 0.0)
    if args.prom_path:
        from alphafold2_tpu import obs
        obs.write_prometheus(args.prom_path)
        report["prom_path"] = args.prom_path
    if cache_on:
        report["cache_store"] = {
            k: cache_snap["store"][k]
            for k in ("hits", "misses", "disk_hits", "disk_errors",
                      "evictions", "bytes_resident", "entries_resident")}
    metrics.close()
    print(json.dumps(report))

    if args.smoke:
        bad = snap["shed"] + snap["errors"] + snap["rejected"] \
            + len(failures)
        if bad or snap["served"] == 0:
            print(f"SMOKE FAIL: {bad} bad outcomes, "
                  f"{snap['served']} served", file=sys.stderr)
            return 1
        if cache_on and args.dup_rate > 0 and cache_snap["hits"] == 0:
            # a duplicated workload that never hits the store means the
            # cache subsystem is broken (every ticket still resolved:
            # coalesced-only would show up here as hits == 0)
            print(f"SMOKE FAIL: dup-rate {args.dup_rate} workload with "
                  f"0 cache hits ({cache_snap['coalesced']} coalesced)",
                  file=sys.stderr)
            return 1
        extra = (f", {cache_snap['hits']} cache hits, "
                 f"{cache_snap['coalesced']} coalesced"
                 if cache_on else "")
        print(f"SMOKE OK: {snap['served']} folds, 0 shed/errors{extra}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
