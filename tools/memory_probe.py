"""Peak-memory probe for the depth-48 activation story (BASELINE.md
config #5; round-1 VERDICT #8: prove O(1)-in-depth activations, don't
just claim them).

AOT-compiles one full training step (loss + grads + adam update) at a
sweep of depths and reports XLA's own memory analysis (argument/output/
temp/generated-code bytes). Compile-only: nothing executes, so a config
that would OOM at runtime still yields its planned peak. With scan+remat
the temp (activation) bytes must stay ~flat in depth; without remat they
grow linearly.

Usage:
  python tools/memory_probe.py [--depths 2,8,48] [--len 384] [--dim 256]
                               [--reversible] [--run]
`--run` additionally executes one step at the largest depth and prints
live device memory stats (jax.local_devices()[0].memory_stats()).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import (_enable_compile_cache, force_cpu_fallback,  # noqa: E402
                             jax_backends_initialized, tiny_op_probe)

# same wedged-tunnel hardening as bench.py/bench_suite.py: fall back to
# CPU with a message instead of hanging inside backend init
if not jax_backends_initialized() and \
        os.environ.get("BENCH_NO_FALLBACK") != "1" and not tiny_op_probe():
    force_cpu_fallback("memory_probe: default platform unreachable")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def analyze(depth: int, seq_len: int, dim: int, reversible: bool,
            use_scan: bool = True, run: bool = False):
    from alphafold2_tpu import Alphafold2
    from alphafold2_tpu.data.synthetic import synthetic_batch
    from alphafold2_tpu.train import TrainState, adam, make_train_step

    model = Alphafold2(dim=dim, depth=depth, heads=8, dim_head=64,
                       dtype=jnp.bfloat16, reversible=reversible,
                       use_scan=use_scan)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=seq_len,
                            msa_depth=5, with_coords=True)
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(1), batch["seq"],
                           msa=batch["msa"], mask=batch["mask"],
                           msa_mask=batch["msa_mask"]))
    params = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), params)
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=adam(3e-4), rng=jax.random.PRNGKey(2))
    step = jax.jit(make_train_step(model), donate_argnums=(0,))
    compiled = step.lower(state, batch).compile()
    mem = compiled.memory_analysis()
    out = {
        "depth": depth, "seq_len": seq_len, "dim": dim,
        "reversible": reversible, "use_scan": use_scan,
        "platform": jax.default_backend(),
    }
    if mem is not None:
        for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(mem, k, None)
            if v is not None:
                out[k.replace("_in_bytes", "_mb")] = round(v / 2**20, 1)
    if run:
        # reuse the AOT-compiled executable; calling `step` would
        # re-trace and re-compile (jit's call cache is separate)
        state, metrics = compiled(state, batch)
        jax.block_until_ready(metrics["loss"])
        out["loss"] = float(metrics["loss"])
        stats = jax.local_devices()[0].memory_stats() or {}
        for k in ("bytes_in_use", "peak_bytes_in_use"):
            if k in stats:
                out[k.replace("bytes", "mb")] = round(stats[k] / 2**20, 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depths", default="2,8,48")
    ap.add_argument("--len", dest="seq_len", type=int, default=384)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--reversible", action="store_true")
    ap.add_argument("--no-scan", action="store_true",
                    help="disable scan+remat (linear-memory comparison)")
    ap.add_argument("--run", action="store_true")
    args = ap.parse_args()

    _enable_compile_cache()
    depths = [int(d) for d in args.depths.split(",")]
    for i, d in enumerate(depths):
        res = analyze(d, args.seq_len, args.dim, args.reversible,
                      use_scan=not args.no_scan,
                      run=args.run and d == max(depths))
        print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
