"""Measure the reference on the BASELINE suite configs it can actually run.

Fills the torch-CPU columns of tools/bench_suite_results_cpu.json
(round-4 VERDICT #6). What is genuinely measurable:

- config 2 (trRosetta angles): REAL — `Alphafold2(predict_angles=True)`
  is live reference code (alphafold2.py:559-562); the timed step is
  distogram CE + theta/phi/omega CEs + backward + Adam.
- configs 3/4 (EGNN e2e / SE3+reversible): the reference CANNOT run
  these end-to-end anywhere — train_end2end.py is stale/broken as
  written (undefined names, removed kwargs; SURVEY.md §2.6), the EGNN
  path lives only in a Colab notebook against pip packages not in the
  repo's deps, and the reversible trunk is vestigial (not constructible
  through Alphafold2 v0.4.32). The honest matched number is the shared
  TRUNK work at the config's dims (dim128/depth2/64res distogram step),
  recorded as `torch_cpu_trunk_only_s` with this provenance note.
- fold (3-recycle inference): the reference's structure module needs the
  external `invariant-point-attention` CUDA-backed package (stubbed here
  with a no-op — timing it would be fiction); no honest column exists.

Writes tools/reference_suite_baseline.json (kept separate from
reference_baseline.json, whose entries key on dims alone and would
collide with the angle variant at equal dims).

Usage: python tools/measure_reference_suite.py [--iters 3]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, "/root/reference")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _reference_stubs  # noqa: F401
import torch
import torch.nn.functional as F

from alphafold2_pytorch import Alphafold2
from alphafold2_pytorch.utils import get_bucketed_distance_matrix

MSA, B = 5, 1
OUT = os.path.join(os.path.dirname(__file__),
                   "reference_suite_baseline.json")


def _inputs(L):
    torch.manual_seed(0)
    seq = torch.randint(0, 21, (B, L))
    msa = torch.randint(0, 21, (B, MSA, L))
    mask = torch.ones(B, L).bool()
    msa_mask = torch.ones(B, MSA, L).bool()
    coords = torch.cumsum(torch.randn(B, L, 3), dim=1)
    return seq, msa, mask, msa_mask, coords


def _time_steps(step, iters):
    step()  # warmup (includes any lazy init)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    return min(times)


def measure_angles(dim, depth, L, iters):
    """Config 2: distogram + trRosetta anglegram training step
    (reference alphafold2.py:559-562, :815-836; buckets constants.py:
    THETA=25, PHI=13, OMEGA=25)."""
    model = Alphafold2(dim=dim, depth=depth, heads=8, dim_head=64,
                       predict_angles=True)
    opt = torch.optim.Adam(model.parameters(), lr=3e-4)
    seq, msa, mask, msa_mask, coords = _inputs(L)
    theta = torch.randint(0, 25, (B, L, L))
    phi = torch.randint(0, 13, (B, L, L))
    omega = torch.randint(0, 25, (B, L, L))

    def step():
        ret = model(seq, msa, mask=mask, msa_mask=msa_mask)
        target = get_bucketed_distance_matrix(coords, mask)
        loss = F.cross_entropy(ret.distance.reshape(-1, 37),
                               target.reshape(-1), ignore_index=-100)
        # the reference sets theta_logits/phi_logits/omega_logits as
        # dynamic attributes (its declared ReturnValues.theta field stays
        # None - alphafold2.py:816-836)
        loss = loss + F.cross_entropy(ret.theta_logits.reshape(-1, 25),
                                      theta.reshape(-1))
        loss = loss + F.cross_entropy(ret.phi_logits.reshape(-1, 13),
                                      phi.reshape(-1))
        loss = loss + F.cross_entropy(ret.omega_logits.reshape(-1, 25),
                                      omega.reshape(-1))
        if ret.msa_mlm_loss is not None:
            loss = loss + ret.msa_mlm_loss
        loss.backward()
        opt.step()
        opt.zero_grad()
        return float(loss)

    return _time_steps(step, iters)


def measure_trunk(dim, depth, L, iters):
    """Configs 3/4 proxy: the shared trunk work at their dims (the
    reference's own e2e paths are unrunnable — see module docstring)."""
    model = Alphafold2(dim=dim, depth=depth, heads=8, dim_head=64)
    opt = torch.optim.Adam(model.parameters(), lr=3e-4)
    seq, msa, mask, msa_mask, coords = _inputs(L)

    def step():
        ret = model(seq, msa, mask=mask, msa_mask=msa_mask)
        target = get_bucketed_distance_matrix(coords, mask)
        loss = F.cross_entropy(ret.distance.reshape(-1, 37),
                               target.reshape(-1), ignore_index=-100)
        if ret.msa_mlm_loss is not None:
            loss = loss + ret.msa_mlm_loss
        loss.backward()
        opt.step()
        opt.zero_grad()
        return float(loss)

    return _time_steps(step, iters)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()
    torch.set_num_threads(os.cpu_count())

    out = {"note": __doc__.split("Usage:")[0].strip(),
           "threads": torch.get_num_threads(), "entries": []}

    t = measure_angles(256, 2, 128, args.iters)
    out["entries"].append({
        "config": "2_trrosetta_angles(dim256,depth2,128res)",
        "torch_cpu_train_step_s": round(t, 3), "kind": "real"})
    print(json.dumps(out["entries"][-1]), flush=True)

    t = measure_trunk(128, 2, 64, args.iters)
    out["entries"].append({
        "config": "3/4_trunk_at_dims(dim128,depth2,64res)",
        "torch_cpu_train_step_s": round(t, 3), "kind": "trunk-only",
        "why": "reference e2e/SE3/reversible paths unrunnable "
               "(broken script, external CUDA deps, vestigial module)"})
    print(json.dumps(out["entries"][-1]), flush=True)

    with open(OUT, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
