#!/usr/bin/env bash
# Serving smoke, three phases over the serve.Scheduler on CPU:
#
#   1. 30-second mixed-length load test. FAILS (exit 1) on any shed,
#      timeout, error, or rejected request at this trivial load — the
#      serving regression tripwire.
#   2. duplicated workload (--dup-rate 0.5, result cache on). FAILS if
#      the cache never hits, any coalesced ticket deadlocks/times out,
#      or any request sheds/errors — the dedup-subsystem tripwire
#      (serve_loadtest.py --smoke enforces all of it in-process).
#   3. observability: both phases ran with request tracing + a
#      Prometheus registry dump; tools/obs_report.py --check FAILS on
#      any trace missing its schema version, any incomplete trace or
#      orphan span, any accelerator-served request without a non-zero
#      fold span, or unparseable Prometheus exposition — the
#      obs-subsystem tripwire.
#
# Invoked standalone from the test-tier docs (README "Tests");
# tests/test_serve.py + tests/test_cache.py + tests/test_obs.py cover
# the same paths in-process under `-m 'not slow'`.
#
#   bash tools/serve_smoke.sh            # default 30s serving window
#   SMOKE_DURATION_S=10 bash tools/serve_smoke.sh
#
# The overall timeouts leave headroom for the cold per-bucket compiles
# (warmup is excluded from the serving window but not from wall clock).
set -euo pipefail
cd "$(dirname "$0")/.."

DURATION="${SMOKE_DURATION_S:-30}"

rm -f /tmp/serve_smoke_traces.jsonl /tmp/serve_smoke_dup_traces.jsonl

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --duration-s "$DURATION" \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 2 \
    --deadline-s 120 \
    --num-recycles 0 \
    --metrics-path /tmp/serve_smoke.jsonl \
    --trace-path /tmp/serve_smoke_traces.jsonl \
    --prom-path /tmp/serve_smoke.prom

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --requests 48 \
    --dup-rate 0.5 \
    --cache on \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 2 \
    --deadline-s 120 \
    --num-recycles 0 \
    --metrics-path /tmp/serve_smoke_dup.jsonl \
    --trace-path /tmp/serve_smoke_dup_traces.jsonl \
    --prom-path /tmp/serve_smoke_dup.prom

# phase 3: every completed request left exactly one complete trace
# (non-zero fold span for accelerator-served ones, no orphan spans,
# schema-versioned) and the Prometheus exposition parses
timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_traces.jsonl \
    --check --prom /tmp/serve_smoke.prom

exec timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_dup_traces.jsonl \
    --check --prom /tmp/serve_smoke_dup.prom
