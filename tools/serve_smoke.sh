#!/usr/bin/env bash
# Serving smoke, two phases over the serve.Scheduler on CPU:
#
#   1. 30-second mixed-length load test. FAILS (exit 1) on any shed,
#      timeout, error, or rejected request at this trivial load — the
#      serving regression tripwire.
#   2. duplicated workload (--dup-rate 0.5, result cache on). FAILS if
#      the cache never hits, any coalesced ticket deadlocks/times out,
#      or any request sheds/errors — the dedup-subsystem tripwire
#      (serve_loadtest.py --smoke enforces all of it in-process).
#
# Invoked standalone from the test-tier docs (README "Tests");
# tests/test_serve.py + tests/test_cache.py cover the same paths
# in-process under `-m 'not slow'`.
#
#   bash tools/serve_smoke.sh            # default 30s serving window
#   SMOKE_DURATION_S=10 bash tools/serve_smoke.sh
#
# The overall timeouts leave headroom for the cold per-bucket compiles
# (warmup is excluded from the serving window but not from wall clock).
set -euo pipefail
cd "$(dirname "$0")/.."

DURATION="${SMOKE_DURATION_S:-30}"

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --duration-s "$DURATION" \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 2 \
    --deadline-s 120 \
    --num-recycles 0 \
    --metrics-path /tmp/serve_smoke.jsonl

exec timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --requests 48 \
    --dup-rate 0.5 \
    --cache on \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 2 \
    --deadline-s 120 \
    --num-recycles 0 \
    --metrics-path /tmp/serve_smoke_dup.jsonl
