#!/usr/bin/env bash
# Serving smoke: 30-second CPU load test over the serve.Scheduler with
# synthetic mixed-length requests. FAILS (exit 1) on any shed, timeout,
# error, or rejected request at this trivial load — the serving
# regression tripwire. Invoked standalone from the test-tier docs
# (README "Tests"); tests/test_serve.py covers the same path in-process
# under `-m 'not slow'`.
#
#   bash tools/serve_smoke.sh            # default 30s serving window
#   SMOKE_DURATION_S=10 bash tools/serve_smoke.sh
#
# The overall timeout leaves headroom for the cold per-bucket compiles
# (warmup is excluded from the serving window but not from wall clock).
set -euo pipefail
cd "$(dirname "$0")/.."

DURATION="${SMOKE_DURATION_S:-30}"

exec timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --duration-s "$DURATION" \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 2 \
    --deadline-s 120 \
    --num-recycles 0 \
    --metrics-path /tmp/serve_smoke.jsonl
