#!/usr/bin/env bash
# Serving smoke, six phases over the serve.Scheduler on CPU:
#
#   1. 30-second mixed-length load test. FAILS (exit 1) on any shed,
#      timeout, error, or rejected request at this trivial load — the
#      serving regression tripwire.
#   2. duplicated workload (--dup-rate 0.5, result cache on). FAILS if
#      the cache never hits, any coalesced ticket deadlocks/times out,
#      or any request sheds/errors — the dedup-subsystem tripwire
#      (serve_loadtest.py --smoke enforces all of it in-process).
#   3. observability: both phases ran with request tracing + a
#      Prometheus registry dump; tools/obs_report.py --check FAILS on
#      any trace missing its schema version, any incomplete trace or
#      orphan span, any accelerator-served request without a non-zero
#      fold span, or unparseable Prometheus exposition — the
#      obs-subsystem tripwire.
#   4. fleet: the same --dup-rate 0.5 workload split round-robin across
#      TWO in-process replicas, run twice — --fleet off (independent
#      replicas, the baseline) then --fleet on (consistent-hash routing
#      + peer cache tier) with a mid-run model-tag epoch bump in BOTH
#      runs (symmetric handicap). FAILS if the fleet run's fleet-wide
#      hit ratio is not ABOVE the baseline's, its executor batch
#      executions are not BELOW the baseline's, any stale-tag cache hit
#      follows the epoch bump, or tools/obs_report.py --check finds
#      orphan routing spans in the fleet run's traces — the
#      fleet-subsystem tripwire.
#   5. chaos: the phase-2 workload re-run under seeded fault injection
#      (--chaos: 10% injected transient executor failures + one poison
#      request) with the failure-domain hardening on (RetryPolicy).
#      serve_loadtest.py --smoke --chaos FAILS unless every ticket
#      reaches a terminal state (zero hung tickets), every innocent
#      request resolves ok (shed/errors/rejected == 0 — i.e. the
#      innocent ok-rate matches the no-chaos phase-1 baseline), exactly
#      ONE request is quarantined (status "poisoned"), and the poison
#      was cornered within log2(max_batch)+1 batch executions; then
#      tools/obs_report.py --check over the chaos traces proves no
#      orphan retry/watchdog spans — recovery cost is fully accounted
#      in the waterfall. The resilience-subsystem tripwire.
#   6. multi-process fleet (--procs 3, fleet.procfleet): THREE real
#      replica processes behind HTTP front doors (fleet.frontdoor),
#      surviving one kill -9 + restart, one induced network partition,
#      a fleet-wide model-tag rollout, and one rolling drain-restart
#      (SIGTERM -> Scheduler.drain -> exit 0 -> respawn at the
#      PERSISTED rollout epoch + poison quarantine). FAILS unless
#      every request reaches an ok terminal state (zero lost across
#      all three faults), the drained replica exits 0, every replica
#      ends on the rolled tag (restart included), zero stale-tag
#      serves, and obs_report --check is clean over the merged
#      driver + replica traces with rpc/drain spans present in the
#      waterfall. The deployment-seam tripwire.
#   8. iteration-level recycle scheduling (--recycle-sched,
#      serve.RecyclePolicy): a skewed 3:1 short+long workload at
#      num-recycles 2 run TWICE — the opaque-fold baseline, then the
#      step-scheduled run with convergence injected (--converge-tol
#      1e9: every element retires after recycle 1 — the max-win bound
#      that exercises the full early-exit path honestly) + streaming +
#      tight deadlines on the short class. FAILS unless the
#      step-scheduled run's total executor step-executions are BELOW
#      the baseline's on the identical schedule, recycles were
#      actually skipped, every request still resolves ok with correct
#      shapes (zero wrong-result serves — early-exit results key under
#      their own cache extras, so nothing can cross-serve), and
#      obs_report --check finds no orphan recycle spans. The
#      iteration-level-scheduling tripwire.
#   9. feature-pipeline disaggregation (--feature-latency-ms /
#      --feature-pool, serve.FeaturePool): the identical raw-submission
#      workload with synthetic featurize latency comparable to fold
#      time, run TWICE — the serialized featurize-in-submit baseline
#      (--feature-pool 0: every submit pays featurization inline),
#      then the pipelined path (a 4-worker FeaturePool + FeatureCache +
#      in-flight featurize coalescing, duplicate raw traffic at rate
#      0.5). FAILS unless the pipelined run shows STRICTLY higher
#      folds/hour and STRICTLY lower executor idle fraction than the
#      baseline on the equal workload, the feature cache hit ratio is
#      > 0 under the duplicate traffic, featurize executions equal
#      unique raw keys (zero duplicate featurize work for coalesced/
#      cached keys — serve_loadtest --smoke enforces it in-process),
#      every request resolves ok, and obs_report --check is clean over
#      the pipelined traces with featurize spans present in the
#      waterfall. The feature-pipeline tripwire.
#  10. continuous batching (--continuous, RecyclePolicy(continuous)):
#      a single-bucket workload at num-recycles 3 with MEASURED skewed
#      convergence (--converge-percentile 50 calibrates the tol at the
#      median recycle-1 delta, so ~half of each batch early-exits at
#      recycle 1 and the rest outlives it — the freed-rows shape), run
#      TWICE on the identical schedule: early-exit-only baseline, then
#      --continuous (freed rows refilled mid-loop from the pending
#      queue via the row-masked init program). FAILS unless the
#      continuous run's rows-occupied fraction is STRICTLY above the
#      baseline's AND its folds/hour is no worse, rows were actually
#      admitted (row_admissions > 0), every request resolves ok in
#      both runs (admitted-row numerics are pinned byte-equal in
#      tests/test_continuous.py), and obs_report --check is clean over
#      the continuous traces with admit spans present in the
#      waterfall. The continuous-batching tripwire.
#  11. per-bucket kernel selection (--kernel-policy,
#      serve.KernelPolicy): the identical long-bucket step-scheduled
#      workload run TWICE — dense baseline, then a blocksparse policy
#      routing the bucket onto the block-skipping attention kernel.
#      FAILS unless the sparse arm actually served through
#      sparse-keyed ExecKey executables with kernel-tagged fold/
#      recycle spans in its traces, the interpret-mode numerics check
#      (kernel vs dense+mask reference on the served pattern) stays
#      within 1e-3, and every request resolves ok in both arms; on a
#      real TPU it additionally fails when the sparse arm loses
#      folds/hour (skipped when clamped to CPU, where the masked-dense
#      fallback serves and only routing + numerics are meaningful).
#      The kernel-selection tripwire.
#  12. cross-bucket continuous batching (--cross-bucket --eager-form,
#      RecyclePolicy(cross_bucket)): a skewed mixed-bucket workload
#      (3:1 short vs flagship-bucket) with measured skewed
#      convergence, run TWICE on the identical schedule — the PR-11
#      same-bucket-only continuous baseline, then --cross-bucket
#      --eager-form (freed flagship rows admit pending SHORT folds at
#      the host shape, priced per admit; thin queues form eagerly and
#      let admission top them up). FAILS unless cross-bucket
#      admissions actually fired, rows occupied is STRICTLY above the
#      baseline, the SHORT bucket's p99 is STRICTLY below the
#      baseline's, every request resolves ok in both runs
#      (admitted-row numerics pinned byte-equal-to-host-shape in
#      tests/test_crossbucket.py), and obs_report --check is clean
#      with native_bucket-tagged admit spans present. The
#      cross-bucket-batching tripwire.
#  13. chaos under continuous batching (ISSUE 14, --chaos-step-at +
#      --checkpoint-every + --row-isolation): the phase-10-shaped
#      continuous workload with ~15% injected MID-LOOP transient
#      step faults at recycles 1-3 plus one raise-mode poison, run
#      TWICE on the identical chaos schedule — the PR-5
#      requeue-from-zero recovery baseline, then with step-loop fault
#      domains on (carry checkpointing at every recycle + per-row
#      poison isolation). FAILS unless BOTH arms leave zero innocent
#      casualties with every ticket terminal and the poison
#      quarantined, the hardened arm actually RESUMED from checkpoints
#      (checkpoint_resumes > 0) with measured recycles_lost within
#      checkpoint_every x injected failures (enforced in-process by
#      serve_loadtest --smoke --chaos; the baseline's requeue path
#      pays ~num_recycles x survivors instead, visible as retries with
#      zero resumes), the poison cost zero innocent restarts in the
#      hardened arm (row_poison_isolations > 0, bisections == 0), and
#      obs_report --check is clean over the chaos traces with resume
#      spans present in the waterfall. The step-loop-fault-domain
#      tripwire.
#  14. fleet-wide observability (ISSUE 15, --slo + --obs-fleet-out +
#      tools/obs_fleet.py): a 3-process fleet with consistent-hash
#      forwarding and one kill -9 + restart mid-run, with tracing ON
#      everywhere (origin-tagged tracers, cross-process trace
#      contexts) and SLO objectives declared on every replica AND the
#      driver. FAILS unless every request still resolves ok (the
#      phase-6 contract), the driver's windowed SLO report shows
#      burn-rate > 0 in the killed window (the failover penalty
#      exceeds the auto-calibrated latency target by construction)
#      while replicas report serve_stats()["slo"] and their scraped
#      GET /metrics expositions carry slo_* gauges, obs_fleet --check
#      is green over the merged driver+replica traces + scrapes —
#      0 broken stitches (every forwarded fold's segments share one
#      trace id and hang under the sender's rpc span), every
#      rpc/forward span explicitly closed with an outcome (a
#      transport-death failover never leaves a dangling span) — and
#      at least one multi-hop stitched trace exists. The
#      fleet-observability tripwire.
#  15. control-plane actuation (ISSUE 16, --controller): a 3-process
#      fleet run by its OWN FleetController — the driver fires ZERO
#      operator recovery verbs. Chaos: a traffic wave (2x extra
#      submitters over the middle of the run), one kill -9 (NOT
#      restarted by the driver — the controller must notice the
#      missing endpoint and spawn a replacement to restore quorum),
#      and a mid-run rollout issued through the controller's one
#      retry/backoff/convergence verb. FAILS unless every request
#      resolves ok with 0 lost, quorum and the rolled tag converge on
#      the live replicas, the controller recorded >= 1 scale_up, a
#      post-convergence recovery probe through the HEALED fleet
#      (replacement included) attains its SLO targets, obs_fleet --check
#      is green over traces + scrapes + the controller's decision log
#      (including the replica-identity pins), and cache_warm
#      --from-serve-log can rebuild a warm profile from the run's own
#      keys.jsonl telemetry. The fleet-runs-itself tripwire.
#  18. spot-preemptible serving (ISSUE 20, --preempt-at): a
#      3-process fleet + controller loses one replica to a real spot
#      reclaim (notice -> grace-budgeted drain -> kill -9). FAILS
#      unless the victim spills what the grace window can't fit,
#      publishes its orphan manifest, and exits 0 before the kill;
#      the controller adopts EVERY orphan onto a survivor through
#      POST /admin/adopt; 0 folds are lost; and preempt/adopt spans
#      are present with obs_report --check clean. The spot-reclaim
#      tripwire.
#   7. multi-chip mesh serving (--mesh-policy, serve.MeshPolicy) under
#      XLA_FLAGS=--xla_force_host_platform_device_count=8: a mixed
#      short+long workload where the long bucket is pinned to a 4-chip
#      pair-sharded slice and short folds stay single-chip. FAILS
#      unless every request resolves ok, at least one sharded-bucket
#      batch actually executed on a >1-chip mesh (serve_loadtest
#      --smoke enforces it from serve_stats()["mesh"]["folds"]; the
#      assertion is skipped cleanly when only 1 device is visible),
#      and obs_report --check finds no orphan shard spans in the
#      traces. The mesh-serving tripwire.
#
# SMOKE_PHASES selects phases without forking the script (constrained
# runners skip the multi-process phase): a comma-separated list, e.g.
#   SMOKE_PHASES=1,2,3 bash tools/serve_smoke.sh
#   SMOKE_PHASES=6 bash tools/serve_smoke.sh
# Default: all phases. Phase 3 checks phase 1+2's artifacts — select
# them together.
#
# Invoked standalone from the test-tier docs (README "Tests");
# tests/test_serve.py + tests/test_cache.py + tests/test_obs.py +
# tests/test_frontdoor.py cover the same paths in-process under
# `-m 'not slow'` (the multi-process tier is `-m slow`).
#
#   bash tools/serve_smoke.sh            # default 30s serving window
#   SMOKE_DURATION_S=10 bash tools/serve_smoke.sh
#
# The overall timeouts leave headroom for the cold per-bucket compiles
# (warmup is excluded from the serving window but not from wall clock).
set -euo pipefail
cd "$(dirname "$0")/.."

DURATION="${SMOKE_DURATION_S:-30}"
PHASES="${SMOKE_PHASES:-1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16,17,18}"

phase_on() {
    case ",${PHASES}," in
        *",$1,"*) return 0 ;;
        *) return 1 ;;
    esac
}

if phase_on 1; then
rm -f /tmp/serve_smoke_traces.jsonl

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --duration-s "$DURATION" \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 2 \
    --deadline-s 120 \
    --num-recycles 0 \
    --metrics-path /tmp/serve_smoke.jsonl \
    --trace-path /tmp/serve_smoke_traces.jsonl \
    --prom-path /tmp/serve_smoke.prom
fi

if phase_on 2; then
rm -f /tmp/serve_smoke_dup_traces.jsonl

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --requests 48 \
    --dup-rate 0.5 \
    --cache on \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 2 \
    --deadline-s 120 \
    --num-recycles 0 \
    --metrics-path /tmp/serve_smoke_dup.jsonl \
    --trace-path /tmp/serve_smoke_dup_traces.jsonl \
    --prom-path /tmp/serve_smoke_dup.prom
fi

# phase 3: every completed request left exactly one complete trace
# (non-zero fold span for accelerator-served ones, no orphan spans,
# schema-versioned) and the Prometheus exposition parses
if phase_on 3; then
timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_traces.jsonl \
    --check --prom /tmp/serve_smoke.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_dup_traces.jsonl \
    --check --prom /tmp/serve_smoke_dup.prom
fi

# phase 4: two-replica fleet vs the two-independent-replica baseline on
# the identical duplicated workload (same schedule, same round-robin
# split, same mid-run epoch bump)
if phase_on 4; then
rm -f /tmp/serve_smoke_fleet_traces.jsonl

fleet_phase() {  # $1 = on|off, $2 = report path, extra args follow
    local mode="$1" out="$2"; shift 2
    timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
        python tools/serve_loadtest.py \
        --smoke \
        --requests 48 \
        --dup-rate 0.5 \
        --cache on \
        --replicas 2 \
        --fleet "$mode" \
        --rollout-at 0.75 \
        --lengths 24,48 \
        --buckets 32,64 \
        --msa-depth 3 \
        --max-batch 2 \
        --concurrency 2 \
        --deadline-s 120 \
        --num-recycles 0 \
        "$@" > "$out"
    cat "$out"
}

fleet_phase off /tmp/serve_smoke_fleet_base.json \
    --metrics-path /tmp/serve_smoke_fleet_base.jsonl
fleet_phase on /tmp/serve_smoke_fleet.json \
    --metrics-path /tmp/serve_smoke_fleet.jsonl \
    --trace-path /tmp/serve_smoke_fleet_traces.jsonl \
    --prom-path /tmp/serve_smoke_fleet.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_fleet_traces.jsonl \
    --check --prom /tmp/serve_smoke_fleet.prom

# the fleet must measurably beat independent replicas on the same
# duplicated traffic, and the epoch bump must have produced zero
# stale-tag hits
env -u PYTHONPATH python - <<'EOF'
import json, sys
base = json.load(open("/tmp/serve_smoke_fleet_base.json"))
fleet = json.load(open("/tmp/serve_smoke_fleet.json"))
problems = []
if fleet["hit_ratio"] <= base["hit_ratio"]:
    problems.append(f"fleet hit_ratio {fleet['hit_ratio']} <= "
                    f"baseline {base['hit_ratio']}")
if fleet["batches"] >= base["batches"]:
    problems.append(f"fleet batches {fleet['batches']} >= "
                    f"baseline {base['batches']}")
rollout = fleet.get("rollout") or {}
if rollout.get("stale_tag_hits", 0):
    problems.append(f"{rollout['stale_tag_hits']} stale-tag cache hits "
                    "after the epoch bump")
probe = rollout.get("stale_probe") or {}
if probe and (probe.get("returned_value")
              or not probe.get("refusals_409")):
    problems.append(f"old-tag peer probe not refused: {probe}")
if problems:
    print("FLEET SMOKE FAIL: " + "; ".join(problems), file=sys.stderr)
    sys.exit(1)
print(f"FLEET SMOKE OK: hit_ratio {fleet['hit_ratio']} > "
      f"{base['hit_ratio']}, batches {fleet['batches']} < "
      f"{base['batches']}, {fleet['forwards']} forwards, "
      f"{fleet['peer_hits']} peer hits, 0 stale-tag hits",
      file=sys.stderr)
EOF
fi

# phase 5: the phase-2 workload under seeded chaos — 10% transient
# executor faults + one poison request; the hardened scheduler must
# leave zero collateral damage (serve_loadtest --smoke --chaos enforces
# terminal tickets / innocent ok-rate / exactly-one quarantine / the
# log2(max_batch)+1 bisection bound in-process), and the recovery must
# be fully accounted in the traces (no orphan retry/watchdog spans)
if phase_on 5; then
rm -f /tmp/serve_smoke_chaos_traces.jsonl

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --chaos \
    --chaos-exec-rate 0.10 \
    --chaos-poison 1 \
    --requests 48 \
    --dup-rate 0.5 \
    --cache on \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 2 \
    --deadline-s 120 \
    --num-recycles 0 \
    --metrics-path /tmp/serve_smoke_chaos.jsonl \
    --trace-path /tmp/serve_smoke_chaos_traces.jsonl \
    --prom-path /tmp/serve_smoke_chaos.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_chaos_traces.jsonl \
    --check --prom /tmp/serve_smoke_chaos.prom
fi

# phase 6: THREE real replica processes (fleet.procfleet) behind HTTP
# front doors, one kill -9 + restart, one induced partition, a
# fleet-wide rollout, one rolling drain-restart — zero lost requests,
# drain exits 0, every replica ends on the rolled tag, zero stale-tag
# serves (serve_loadtest --smoke --procs enforces all of it), then
# obs_report --check over the merged driver+replica traces proves the
# new rpc/drain spans are orphan-free in the waterfall
if phase_on 6; then
rm -rf /tmp/serve_smoke_procs
rm -f /tmp/serve_smoke_procs_traces.jsonl

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --procs 3 \
    --proc-run-dir /tmp/serve_smoke_procs \
    --proc-kill-at 0.3 \
    --proc-partition-at 0.5 \
    --proc-partition-s 2 \
    --rollout-at 0.65 \
    --proc-drain-at 0.8 \
    --requests 60 \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 3 \
    --deadline-s 120 \
    --num-recycles 0 \
    --trace-path /tmp/serve_smoke_procs_traces.jsonl \
    --prom-path /tmp/serve_smoke_procs.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_procs_traces.jsonl \
    --check --prom /tmp/serve_smoke_procs.prom
fi

# phase 7: mesh serving — 8 virtual devices, short bucket single-chip,
# long bucket on a 2x2 pair-sharded slice; serve_loadtest --smoke fails
# unless sharded batches actually executed on the multi-chip mesh (or
# skips that assertion cleanly when only 1 device is visible), then
# obs_report --check proves the new shard spans (and mesh-tagged fold
# spans) are orphan-free
if phase_on 7; then
rm -f /tmp/serve_smoke_mesh_traces.jsonl

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python tools/serve_loadtest.py \
    --smoke \
    --requests 48 \
    --lengths 24,48 \
    --buckets 32,64 \
    --mesh-policy 32=1,64=4 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 2 \
    --deadline-s 120 \
    --num-recycles 0 \
    --metrics-path /tmp/serve_smoke_mesh.jsonl \
    --trace-path /tmp/serve_smoke_mesh_traces.jsonl \
    --prom-path /tmp/serve_smoke_mesh.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_mesh_traces.jsonl \
    --check --prom /tmp/serve_smoke_mesh.prom
fi

# phase 8: iteration-level recycle scheduling — the identical skewed
# short+long workload at num-recycles 2, opaque baseline vs
# step-scheduled with convergence injected; early exit must reduce
# executor step-executions with zero wrong-result serves, and the new
# recycle spans must be orphan-free in the waterfall
if phase_on 8; then
rm -f /tmp/serve_smoke_recycle_traces.jsonl

recycle_phase() {  # $1 = report path, extra args follow
    local out="$1"; shift
    timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
        python tools/serve_loadtest.py \
        --smoke \
        --requests 48 \
        --lengths 24,24,24,48 \
        --buckets 32,64 \
        --msa-depth 3 \
        --max-batch 2 \
        --concurrency 2 \
        --deadline-s 120 \
        --num-recycles 2 \
        "$@" > "$out"
    cat "$out"
}

recycle_phase /tmp/serve_smoke_recycle_base.json \
    --metrics-path /tmp/serve_smoke_recycle_base.jsonl
recycle_phase /tmp/serve_smoke_recycle.json \
    --recycle-sched --converge-tol 1e9 --stream \
    --metrics-path /tmp/serve_smoke_recycle.jsonl \
    --trace-path /tmp/serve_smoke_recycle_traces.jsonl \
    --prom-path /tmp/serve_smoke_recycle.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_recycle_traces.jsonl \
    --check --prom /tmp/serve_smoke_recycle.prom

env -u PYTHONPATH python - <<'EOF'
import json, sys
base = json.load(open("/tmp/serve_smoke_recycle_base.json"))
sched = json.load(open("/tmp/serve_smoke_recycle.json"))
problems = []
if sched["executor_steps"] >= base["executor_steps"]:
    problems.append(f"step-scheduled executor steps "
                    f"{sched['executor_steps']} >= opaque baseline "
                    f"{base['executor_steps']}")
if sched.get("recycles_saved", 0) <= 0:
    problems.append("no recycles were skipped despite injected "
                    "convergence")
for rep in (base, sched):
    bad = rep["shed"] + rep["errors"] + rep["rejected"] + \
        len(rep["failures"])
    if bad or rep["served"] == 0:
        problems.append(f"{bad} bad outcomes / {rep['served']} served "
                        f"in {'sched' if rep is sched else 'base'} run")
if not sched.get("progress_updates", 0):
    problems.append("--stream published no progressive updates")
if problems:
    print("RECYCLE SMOKE FAIL: " + "; ".join(problems), file=sys.stderr)
    sys.exit(1)
print(f"RECYCLE SMOKE OK: executor steps {sched['executor_steps']} < "
      f"{base['executor_steps']} on the identical workload, "
      f"{sched['recycles_saved']} recycles skipped, "
      f"{sched['recycle']['preemptions']} preemptions, "
      f"{sched.get('progress_updates', 0)} progressive updates, "
      f"p99 by class {sched.get('latency_by_class')}", file=sys.stderr)
EOF
fi

# phase 9: feature-pipeline disaggregation — the identical raw
# (AA-string) workload with synthetic featurize latency ~ fold time,
# serialized featurize-in-submit baseline vs the FeaturePool pipeline;
# the pipelined path must win folds/hour AND executor idle outright,
# with zero duplicate featurize executions and a live feature cache
if phase_on 9; then
rm -f /tmp/serve_smoke_feat_traces.jsonl

feature_phase() {  # $1 = report path, extra args follow
    local out="$1"; shift
    timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
        python tools/serve_loadtest.py \
        --smoke \
        --requests 32 \
        --lengths 24,48 \
        --buckets 32,64 \
        --msa-depth 3 \
        --max-batch 2 \
        --concurrency 2 \
        --num-recycles 0 \
        --feature-latency-ms 250 \
        --feature-dup-rate 0.5 \
        "$@" > "$out"
    cat "$out"
}

feature_phase /tmp/serve_smoke_feat_base.json \
    --feature-pool 0 \
    --metrics-path /tmp/serve_smoke_feat_base.jsonl
feature_phase /tmp/serve_smoke_feat.json \
    --feature-pool 4 \
    --metrics-path /tmp/serve_smoke_feat.jsonl \
    --trace-path /tmp/serve_smoke_feat_traces.jsonl \
    --prom-path /tmp/serve_smoke_feat.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_feat_traces.jsonl \
    --check --prom /tmp/serve_smoke_feat.prom

env -u PYTHONPATH python - <<'EOF'
import json, sys
base = json.load(open("/tmp/serve_smoke_feat_base.json"))
pipe = json.load(open("/tmp/serve_smoke_feat.json"))
problems = []
if pipe["folds_per_hour"] <= base["folds_per_hour"]:
    problems.append(f"pipelined folds/hour {pipe['folds_per_hour']} <= "
                    f"serialized baseline {base['folds_per_hour']}")
if pipe["executor_idle_fraction"] >= base["executor_idle_fraction"]:
    problems.append(
        f"pipelined executor idle {pipe['executor_idle_fraction']} >= "
        f"baseline {base['executor_idle_fraction']}")
feat = pipe.get("featurize") or {}
if feat.get("hit_ratio", 0) <= 0:
    problems.append("feature cache never hit under duplicate traffic")
if feat.get("executions") != pipe["unique_raw_keys"]:
    problems.append(f"{feat.get('executions')} featurize executions != "
                    f"{pipe['unique_raw_keys']} unique raw keys")
for rep in (base, pipe):
    bad = rep["shed"] + rep["errors"] + rep["rejected"] + \
        len(rep["failures"])
    if bad or rep["served"] == 0:
        problems.append(f"{bad} bad outcomes / {rep['served']} served "
                        f"in {'pipe' if rep is pipe else 'base'} run")
spans = {}
for line in open("/tmp/serve_smoke_feat_traces.jsonl"):
    try:
        rec = json.loads(line)
    except ValueError:
        continue
    for s in rec.get("spans", ()):
        spans[s.get("name")] = spans.get(s.get("name"), 0) + 1
if not spans.get("featurize"):
    problems.append("no featurize spans in the pipelined traces")
if problems:
    print("FEATURE SMOKE FAIL: " + "; ".join(problems), file=sys.stderr)
    sys.exit(1)
print(f"FEATURE SMOKE OK: folds/hour {pipe['folds_per_hour']} > "
      f"{base['folds_per_hour']}, executor idle "
      f"{pipe['executor_idle_fraction']} < "
      f"{base['executor_idle_fraction']}, feature hit_ratio "
      f"{feat['hit_ratio']}, {feat['executions']} featurize execs == "
      f"{pipe['unique_raw_keys']} unique keys, "
      f"{spans['featurize']} featurize spans", file=sys.stderr)
EOF
fi

# phase 10: continuous batching — the identical single-bucket workload
# with measured skewed convergence (median recycle-1 delta as tol: ~half
# of each batch early-exits at recycle 1), early-exit-only baseline vs
# --continuous; the continuous run must hold rows occupied strictly
# above the baseline at folds/hour no worse, with rows actually
# admitted mid-loop, zero bad outcomes, and orphan-free admit spans
if phase_on 10; then
rm -f /tmp/serve_smoke_cont_traces.jsonl

cont_phase() {  # $1 = report path, extra args follow
    local out="$1"; shift
    timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
        python tools/serve_loadtest.py \
        --smoke \
        --requests 64 \
        --lengths 24 \
        --buckets 32 \
        --msa-depth 3 \
        --max-batch 4 \
        --max-wait-ms 10 \
        --concurrency 8 \
        --deadline-s 120 \
        --num-recycles 3 \
        --recycle-sched \
        --converge-percentile 50 \
        "$@" > "$out"
    cat "$out"
}

cont_phase /tmp/serve_smoke_cont_base.json \
    --metrics-path /tmp/serve_smoke_cont_base.jsonl
cont_phase /tmp/serve_smoke_cont.json \
    --continuous \
    --metrics-path /tmp/serve_smoke_cont.jsonl \
    --trace-path /tmp/serve_smoke_cont_traces.jsonl \
    --prom-path /tmp/serve_smoke_cont.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_cont_traces.jsonl \
    --check --prom /tmp/serve_smoke_cont.prom

env -u PYTHONPATH python - <<'EOF'
import json, sys
base = json.load(open("/tmp/serve_smoke_cont_base.json"))
cont = json.load(open("/tmp/serve_smoke_cont.json"))
problems = []
if cont["rows_occupied_fraction"] <= base["rows_occupied_fraction"]:
    problems.append(
        f"continuous rows occupied {cont['rows_occupied_fraction']} <= "
        f"baseline {base['rows_occupied_fraction']}")
if cont["folds_per_hour"] < base["folds_per_hour"]:
    problems.append(f"continuous folds/hour {cont['folds_per_hour']} < "
                    f"baseline {base['folds_per_hour']}")
if cont.get("row_admissions", 0) <= 0:
    problems.append("no rows were admitted mid-loop")
if base.get("row_admissions", 0):
    problems.append(f"baseline (continuous off) admitted "
                    f"{base['row_admissions']} rows")
for rep in (base, cont):
    bad = rep["shed"] + rep["errors"] + rep["rejected"] + \
        len(rep["failures"])
    if bad or rep["served"] == 0:
        problems.append(f"{bad} bad outcomes / {rep['served']} served "
                        f"in {'cont' if rep is cont else 'base'} run")
spans = {}
for line in open("/tmp/serve_smoke_cont_traces.jsonl"):
    try:
        rec = json.loads(line)
    except ValueError:
        continue
    for s in rec.get("spans", ()):
        spans[s.get("name")] = spans.get(s.get("name"), 0) + 1
if not spans.get("admit"):
    problems.append("no admit spans in the continuous traces")
if problems:
    print("CONTINUOUS SMOKE FAIL: " + "; ".join(problems),
          file=sys.stderr)
    sys.exit(1)
print(f"CONTINUOUS SMOKE OK: rows occupied "
      f"{cont['rows_occupied_fraction']} > "
      f"{base['rows_occupied_fraction']}, folds/hour "
      f"{cont['folds_per_hour']} >= {base['folds_per_hour']}, "
      f"{cont['row_admissions']} row admissions "
      f"({cont['rows_dead_steps']} dead row-steps vs "
      f"{base['rows_dead_steps']}), {spans['admit']} admit spans",
      file=sys.stderr)
EOF
fi

# phase 11: per-bucket kernel selection (ISSUE 12) — the identical
# long-bucket workload run TWICE: the dense baseline, then with a
# blocksparse kernel policy routing the bucket onto the block-skipping
# attention kernel. serve_loadtest --smoke fails in-process if the
# sparse arm never executes a sparse-keyed ExecKey or its kernel
# diverges from the dense+mask reference in the interpret-mode
# numerics check; the compare below additionally fails on any bad
# outcome, on missing kernel-tagged fold spans, and — on a real TPU —
# on the sparse arm losing folds/hour (the speed gate is skipped when
# the run is clamped to CPU, where the masked-dense fallback serves
# and only routing + numerics are meaningful).
if phase_on 11; then
rm -f /tmp/serve_smoke_kernel_traces.jsonl

kernel_phase() {  # $1 = report path, extra args follow
    local out="$1"; shift
    timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
        python tools/serve_loadtest.py \
        --smoke \
        --requests 32 \
        --lengths 48,56,64 \
        --buckets 64 \
        --msa-depth 3 \
        --max-batch 2 \
        --concurrency 4 \
        --deadline-s 120 \
        --num-recycles 2 \
        --recycle-sched \
        "$@" > "$out"
    cat "$out"
}

kernel_phase /tmp/serve_smoke_kernel_base.json \
    --metrics-path /tmp/serve_smoke_kernel_base.jsonl
kernel_phase /tmp/serve_smoke_kernel.json \
    --kernel-policy blocksparse --sparse-block 8 \
    --metrics-path /tmp/serve_smoke_kernel.jsonl \
    --trace-path /tmp/serve_smoke_kernel_traces.jsonl \
    --prom-path /tmp/serve_smoke_kernel.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_kernel_traces.jsonl \
    --check --prom /tmp/serve_smoke_kernel.prom

env -u PYTHONPATH python - <<'EOF2'
import json, sys
base = json.load(open("/tmp/serve_smoke_kernel_base.json"))
sparse = json.load(open("/tmp/serve_smoke_kernel.json"))
problems = []
kern = sparse.get("kernel") or {}
bs_served = sum(v["served"] for k, v in kern.get("folds", {}).items()
                if k.startswith("blocksparse"))
if bs_served == 0:
    problems.append("the sparse arm never served through a "
                    "blocksparse executable")
bad_num = {b: d for b, d in kern.get("numerics_max_diff", {}).items()
           if d > 1e-3}
if bad_num:
    problems.append(f"kernel numerics diverge: {bad_num}")
for rep in (base, sparse):
    bad = rep["shed"] + rep["errors"] + rep["rejected"] + \
        len(rep["failures"])
    if bad or rep["served"] == 0:
        problems.append(f"{bad} bad outcomes / {rep['served']} served "
                        f"in {'sparse' if rep is sparse else 'base'} "
                        "run")
# kernel-tagged accelerator spans must be present and orphan-free
# (obs --check above proved orphan-free; presence is checked here)
tagged = 0
for line in open("/tmp/serve_smoke_kernel_traces.jsonl"):
    try:
        rec = json.loads(line)
    except ValueError:
        continue
    for s in rec.get("spans", ()):
        if s.get("name") in ("fold", "recycle") and \
                (s.get("attrs") or {}).get("kernel"):
            tagged += 1
if tagged == 0:
    problems.append("no kernel-tagged fold/recycle spans in the "
                    "sparse arm's traces")
speed_gate = sparse.get("platform") != "cpu"
if speed_gate and sparse["folds_per_hour"] < base["folds_per_hour"]:
    problems.append(f"sparse folds/hour {sparse['folds_per_hour']} < "
                    f"dense baseline {base['folds_per_hour']} on TPU")
if problems:
    print("KERNEL SMOKE FAIL: " + "; ".join(problems), file=sys.stderr)
    sys.exit(1)
note = "" if speed_gate else \
    " (CPU masked-dense fallback: speed gate skipped)"
print(f"KERNEL SMOKE OK: {bs_served} folds through blocksparse "
      f"executables, {tagged} kernel-tagged spans, numerics "
      f"{kern.get('numerics_max_diff')}, folds/hour "
      f"{sparse['folds_per_hour']} vs dense {base['folds_per_hour']}"
      f"{note}", file=sys.stderr)
EOF2
fi

# phase 12: cross-bucket continuous batching (ISSUE 13) — a skewed
# mixed-bucket workload (3:1 short vs flagship-bucket) at THIN
# concurrency with a meaningful max_wait window (the regime the
# feature owns: flagship loops run under-filled while short folds
# trickle in), run TWICE on the identical schedule: the PR-11
# same-bucket-only continuous baseline, then with --cross-bucket
# --eager-form. The cross run must admit across buckets (the priced
# padding-vs-dead-row trade actually firing), hold rows occupied
# strictly above the baseline (freed/never-filled flagship rows carry
# short folds instead of padding dead), and beat the baseline's
# SHORT-fold p99 (shorts ride the running loop or form eagerly
# instead of waiting out max_wait behind it), with zero bad outcomes
# in both runs and orphan-free native_bucket-tagged admit spans in
# the waterfall. No deadlines on purpose: deadline traffic is served
# by preemption (phase 8); this phase isolates the admission trade.
if phase_on 12; then
rm -f /tmp/serve_smoke_xb_traces.jsonl

xb_phase() {  # $1 = report path, extra args follow
    local out="$1"; shift
    timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
        python tools/serve_loadtest.py \
        --smoke \
        --requests 64 \
        --lengths 12,12,12,28 \
        --buckets 16,32 \
        --msa-depth 3 \
        --max-batch 4 \
        --max-wait-ms 150 \
        --concurrency 4 \
        --num-recycles 3 \
        --continuous \
        "$@" > "$out"
    cat "$out"
}

xb_phase /tmp/serve_smoke_xb_base.json \
    --metrics-path /tmp/serve_smoke_xb_base.jsonl
xb_phase /tmp/serve_smoke_xb.json \
    --cross-bucket --eager-form \
    --metrics-path /tmp/serve_smoke_xb.jsonl \
    --trace-path /tmp/serve_smoke_xb_traces.jsonl \
    --prom-path /tmp/serve_smoke_xb.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_xb_traces.jsonl \
    --check --prom /tmp/serve_smoke_xb.prom

env -u PYTHONPATH python - <<'EOF'
import json, sys
base = json.load(open("/tmp/serve_smoke_xb_base.json"))
xb = json.load(open("/tmp/serve_smoke_xb.json"))
problems = []
if xb.get("cross_bucket_admissions", 0) <= 0:
    problems.append("no cross-bucket admissions fired")
if base.get("cross_bucket_admissions", 0):
    problems.append(f"baseline (cross-bucket off) admitted "
                    f"{base['cross_bucket_admissions']} across buckets")
if xb["rows_occupied_fraction"] <= base["rows_occupied_fraction"]:
    problems.append(
        f"cross-bucket rows occupied {xb['rows_occupied_fraction']} <= "
        f"baseline {base['rows_occupied_fraction']}")
short = str(min(int(b) for b in xb["bucket_edges"]))
xb_p99 = xb["latency_by_bucket"][short]["p99_s"]
base_p99 = base["latency_by_bucket"][short]["p99_s"]
if xb_p99 >= base_p99:
    problems.append(f"short-fold p99 {xb_p99} >= baseline {base_p99}")
xb_p50 = xb["latency_by_bucket"][short]["p50_s"]
base_p50 = base["latency_by_bucket"][short]["p50_s"]
if xb_p50 >= base_p50:
    # the baseline's max_wait formation floor should dominate its
    # whole short-fold distribution, not just the tail
    problems.append(f"short-fold p50 {xb_p50} >= baseline {base_p50}")
for rep in (base, xb):
    bad = rep["shed"] + rep["errors"] + rep["rejected"] + \
        len(rep["failures"])
    if bad or rep["served"] == 0:
        problems.append(f"{bad} bad outcomes / {rep['served']} served "
                        f"in {'xb' if rep is xb else 'base'} run")
admit_tagged = 0
for line in open("/tmp/serve_smoke_xb_traces.jsonl"):
    try:
        rec = json.loads(line)
    except ValueError:
        continue
    for s in rec.get("spans", ()):
        if s.get("name") == "admit" and \
                (s.get("attrs") or {}).get("native_bucket"):
            admit_tagged += 1
if admit_tagged == 0:
    problems.append("no native_bucket-tagged admit spans in the "
                    "cross-bucket traces")
if problems:
    print("CROSS-BUCKET SMOKE FAIL: " + "; ".join(problems),
          file=sys.stderr)
    sys.exit(1)
print(f"CROSS-BUCKET SMOKE OK: {xb['cross_bucket_admissions']} "
      f"cross-bucket admits ({xb['cross_bucket_refusals']} refused), "
      f"rows occupied {xb['rows_occupied_fraction']} > "
      f"{base['rows_occupied_fraction']}, short-fold p99 {xb_p99} < "
      f"{base_p99} (p50 {xb_p50} < {base_p50}), waste admitted "
      f"{xb['padding_waste_admitted']} (formation said "
      f"{xb['padding_waste']}), {admit_tagged} "
      f"native_bucket-tagged admit spans", file=sys.stderr)
EOF
fi

# phase 13: chaos under continuous batching (ISSUE 14) — the
# phase-10-shaped continuous workload with 15% injected mid-loop
# transient step faults (recycles 1-3) + one raise-mode poison on
# the identical seeded chaos schedule, run TWICE: the PR-5
# requeue-from-zero recovery baseline, then with step-loop fault
# domains on (--checkpoint-every 1 --row-isolation). Both arms must
# leave zero innocent casualties (serve_loadtest --smoke --chaos
# enforces terminal tickets / innocent ok-rate / quarantine / the
# recycles_lost <= checkpoint_every x failures bound in-process); the
# compare below additionally gates that the hardened arm actually
# resumed (vs the baseline's retries-with-zero-resumes), isolated the
# poison per-row without bisection, and left resume spans in an
# orphan-free waterfall.
if phase_on 13; then
rm -f /tmp/serve_smoke_stepfault_traces.jsonl

stepfault_phase() {  # $1 = report path, extra args follow
    local out="$1"; shift
    timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
        python tools/serve_loadtest.py \
        --smoke \
        --chaos \
        --chaos-exec-rate 0 \
        --chaos-step-at 1=0.15,2=0.15,3=0.15 \
        --chaos-poison 1 \
        --retry on \
        --retry-max-attempts 6 \
        --requests 48 \
        --lengths 24 \
        --buckets 32 \
        --msa-depth 3 \
        --max-batch 4 \
        --max-wait-ms 10 \
        --concurrency 8 \
        --deadline-s 300 \
        --num-recycles 3 \
        --continuous \
        "$@" > "$out"
    cat "$out"
}

stepfault_phase /tmp/serve_smoke_stepfault_base.json \
    --metrics-path /tmp/serve_smoke_stepfault_base.jsonl
stepfault_phase /tmp/serve_smoke_stepfault.json \
    --checkpoint-every 1 --row-isolation \
    --metrics-path /tmp/serve_smoke_stepfault.jsonl \
    --trace-path /tmp/serve_smoke_stepfault_traces.jsonl \
    --prom-path /tmp/serve_smoke_stepfault.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_stepfault_traces.jsonl \
    --check --prom /tmp/serve_smoke_stepfault.prom

env -u PYTHONPATH python - <<'EOF'
import json, sys
base = json.load(open("/tmp/serve_smoke_stepfault_base.json"))
hard = json.load(open("/tmp/serve_smoke_stepfault.json"))
problems = []
# the hardened arm recovered by RESUMING, not restarting: mid-loop
# faults actually fired and every one of them cost at most
# checkpoint_every recycles (the in-process --smoke check bounded it)
if hard.get("checkpoint_resumes", 0) <= 0:
    problems.append("hardened arm never resumed from a checkpoint")
if hard["chaos"]["injected"].get("step_fail", 0) <= 0:
    problems.append("no mid-loop step faults were injected")
# the poison cost zero innocent restarts: isolated per-row, never
# bisected a cohort
if hard.get("row_poison_isolations", 0) <= 0:
    problems.append("poison was not isolated per-row")
if hard["resilience"].get("bisections", 0):
    problems.append(f"hardened arm bisected "
                    f"{hard['resilience']['bisections']} cohorts")
if hard.get("poisoned", 0) != 1 or base.get("poisoned", 0) != 1:
    problems.append(f"expected exactly 1 quarantined poison per arm, "
                    f"got {base.get('poisoned')} / "
                    f"{hard.get('poisoned')}")
# the baseline took the PR-5 path on the same chaos: requeues fired,
# zero checkpoint machinery
if base["resilience"].get("retries", 0) <= 0:
    problems.append("baseline chaos never exercised the requeue path")
if base.get("checkpoint_resumes", 0):
    problems.append(f"baseline (knobs off) reported "
                    f"{base['checkpoint_resumes']} resumes")
resume_spans = 0
for line in open("/tmp/serve_smoke_stepfault_traces.jsonl"):
    try:
        rec = json.loads(line)
    except ValueError:
        continue
    for s in rec.get("spans", ()):
        if s.get("name") == "resume":
            resume_spans += 1
if resume_spans == 0:
    problems.append("no resume spans in the hardened arm's traces")
if problems:
    print("STEPFAULT SMOKE FAIL: " + "; ".join(problems),
          file=sys.stderr)
    sys.exit(1)
n_fail = hard["chaos"]["injected"]["step_fail"]
print(f"STEPFAULT SMOKE OK: {hard['checkpoint_resumes']} checkpoint "
      f"resumes over {n_fail} injected mid-loop faults, "
      f"{hard['recycles_lost']} recycles lost (bound "
      f"{hard['resilience']['checkpoint_every']} x {n_fail}), "
      f"{hard['row_poison_isolations']} row poison isolations / 0 "
      f"bisections vs baseline {base['resilience']['retries']} "
      f"requeue retries, {resume_spans} resume spans", file=sys.stderr)
EOF
fi

# phase 14: fleet-wide observability (ISSUE 15) — 3 real replica
# processes with forwarding, one kill -9 + restart mid-run, tracing on
# everywhere (origin-tagged, cross-process contexts) and SLO
# objectives on every replica + the driver. serve_loadtest --smoke
# enforces in-process: all requests ok, burn-rate > 0 in the killed
# window, serve_stats()["slo"] on every replica, slo_* gauges in the
# scraped /metrics. obs_fleet --check then proves the stitching: every
# forwarded fold is ONE trace spanning both replicas, every
# rpc/forward span explicitly closed with an outcome.
if phase_on 14; then
rm -rf /tmp/serve_smoke_obsfleet /tmp/serve_smoke_obsfleet_out
rm -f /tmp/serve_smoke_obsfleet_traces.jsonl

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --procs 3 \
    --proc-run-dir /tmp/serve_smoke_obsfleet \
    --proc-kill-at 0.35 \
    --requests 48 \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 3 \
    --deadline-s 120 \
    --num-recycles 0 \
    --slo 32=auto,all=auto \
    --slo-window-s 3 \
    --obs-fleet-out /tmp/serve_smoke_obsfleet_out \
    --trace-path /tmp/serve_smoke_obsfleet_traces.jsonl \
    --prom-path /tmp/serve_smoke_obsfleet.prom \
    > /tmp/serve_smoke_obsfleet.json
cat /tmp/serve_smoke_obsfleet.json

# the merged driver+replica trace file + the per-replica /metrics
# scrapes, through the fleet aggregator's tripwire
timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_fleet.py /tmp/serve_smoke_obsfleet_traces.jsonl \
    --prom-dir /tmp/serve_smoke_obsfleet_out \
    --check --json > /tmp/serve_smoke_obsfleet_fleet.json
cat /tmp/serve_smoke_obsfleet_fleet.json

env -u PYTHONPATH python - <<'EOF'
import json, sys
run = json.load(open("/tmp/serve_smoke_obsfleet.json"))
agg = json.load(open("/tmp/serve_smoke_obsfleet_fleet.json"))
problems = []
slo = run.get("slo") or {}
if not slo.get("kill_window_burn"):
    problems.append(f"no SLO burn in the killed window "
                    f"(report {slo.get('kill_window_burn')})")
if run.get("slo_gauges_scraped", 0) <= 0:
    problems.append("no slo_* gauges in the scraped /metrics")
missing = [r for r, per in (run.get("per_replica") or {}).items()
           if not (per or {}).get("slo")]
if missing:
    problems.append(f"replicas without serve_stats()['slo']: {missing}")
if agg.get("stitched_traces", 0) <= 0:
    problems.append("no multi-hop stitched traces in the fleet set")
if agg.get("broken_stitches", 0):
    problems.append(f"{agg['broken_stitches']} broken stitches")
want_origins = {"driver", "r0", "r1", "r2"}
if not want_origins <= set(agg.get("origins", [])):
    problems.append(f"origins {agg.get('origins')} missing some of "
                    f"{sorted(want_origins)}")
if problems:
    print("OBS-FLEET SMOKE FAIL: " + "; ".join(problems),
          file=sys.stderr)
    sys.exit(1)
print(f"OBS-FLEET SMOKE OK: {agg['stitched_traces']} stitched traces "
      f"(max {agg['max_hops']} hops) across {agg['origins']}, "
      f"0 broken stitches, kill-window burn "
      f"{slo['kill_window_burn']:.2f} (max {slo['max_burn_rate']:.2f}),"
      f" {run['slo_gauges_scraped']} slo gauge lines scraped",
      file=sys.stderr)
EOF
fi

# phase 15: control-plane actuation (ISSUE 16) — the fleet runs
# itself. 3 replica processes + FleetController; the driver submits
# traffic and chaos (wave + kill -9 + rollout) but fires NO recovery
# verbs: the controller restores quorum after the kill, converges the
# rollout on stragglers/late joiners, resizes pools, and warms from
# the fleet's own key telemetry. obs_fleet --check must be green over
# traces + scrapes + controller decisions (identity pins included),
# and cache_warm --from-serve-log must rebuild a profile from the
# run's keys.jsonl.
if phase_on 15; then
rm -rf /tmp/serve_smoke_ctrl /tmp/serve_smoke_ctrl_out \
       /tmp/serve_smoke_ctrl_warmcache
rm -f /tmp/serve_smoke_ctrl_traces.jsonl

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --procs 3 \
    --controller \
    --scale-min 3 \
    --scale-max 5 \
    --traffic-wave 0.10:0.40:1 \
    --proc-kill-at 0.35 \
    --rollout-at 0.55 \
    --requests 48 \
    --lengths 24,48 \
    --buckets 32,64 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 3 \
    --deadline-s 120 \
    --num-recycles 0 \
    --slo 32=auto,all=auto \
    --slo-window-s 3 \
    --obs-fleet-out /tmp/serve_smoke_ctrl_out \
    --proc-run-dir /tmp/serve_smoke_ctrl \
    --trace-path /tmp/serve_smoke_ctrl_traces.jsonl \
    > /tmp/serve_smoke_ctrl.json
cat /tmp/serve_smoke_ctrl.json

# merged traces + run dir (controller traces, decision log, keys) +
# scrapes through the fleet aggregator — identity pins included
timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_fleet.py /tmp/serve_smoke_ctrl_traces.jsonl \
    /tmp/serve_smoke_ctrl \
    --prom-dir /tmp/serve_smoke_ctrl_out \
    --check --json > /tmp/serve_smoke_ctrl_fleet.json
cat /tmp/serve_smoke_ctrl_fleet.json

# the telemetry-driven warm: rebuild a profile from the run's own
# keys.jsonl records and warm its head into a fresh cache dir
timeout -k 10 300 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/cache_warm.py \
    --from-serve-log /tmp/serve_smoke_ctrl \
    --top 2 \
    --cache-dir /tmp/serve_smoke_ctrl_warmcache \
    --model-tag procfleet@v1+rolled \
    --msa-depth 3 \
    > /tmp/serve_smoke_ctrl_warm.json
cat /tmp/serve_smoke_ctrl_warm.json

env -u PYTHONPATH python - <<'EOF'
import json, sys
run = json.load(open("/tmp/serve_smoke_ctrl.json"))
agg = json.load(open("/tmp/serve_smoke_ctrl_fleet.json"))
warm = json.load(open("/tmp/serve_smoke_ctrl_warm.json"))
problems = []
ctrl = run.get("controller") or {}
conv = ctrl.get("converged") or {}
if not conv.get("replicas"):
    problems.append("controller never restored quorum")
if not conv.get("tag"):
    problems.append("controller never converged the rollout")
if ctrl.get("scale_ups", 0) < 1:
    problems.append("no controller scale_up recorded after the kill")
if run.get("lost", 0):
    problems.append(f"{run['lost']} LOST requests")
wave = run.get("wave") or {}
if wave.get("extra_requests", 0) <= 0:
    problems.append("traffic wave submitted no extra requests")
slo = run.get("slo") or {}
if not slo.get("kill_window_burn"):
    problems.append("kill fired but no SLO burn in the killed window")
# recovery is proven by traffic on the HEALED fleet, not by the main
# run's tail (the replacement's boot can outlast the serving window
# on a slow machine): the post-convergence probe must burn nothing
rec = slo.get("recovery") or {}
if not rec.get("samples"):
    problems.append("no post-convergence recovery probe samples")
else:
    # gate fleet-wide attainment at a bar the probe's sample size can
    # support (>= 0.9 over ~12 probes tolerates one cold-path
    # straggler; the per-bucket classes are reported, not gated)
    att = ((rec.get("classes") or {}).get("all")
           or {}).get("attainment", 0.0)
    if att < 0.9:
        problems.append(
            f"healed fleet still degraded: recovery probe "
            f"attainment {att:.2f} < 0.90 over {rec['samples']} "
            f"probes (burn {rec.get('burn', 0):.2f}, "
            f"latencies {rec.get('latencies_s')})")
if agg.get("problems"):
    problems.append(f"obs_fleet check problems: {agg['problems'][:3]}")
actrl = agg.get("controller") or {}
if actrl.get("reconciles", 0) <= 0:
    problems.append("obs_fleet saw no controller reconcile decisions")
if warm.get("profile_source") != "serve_log" or \
        warm.get("unique_in_profile", 0) <= 0:
    problems.append(f"cache_warm --from-serve-log found no key "
                    f"telemetry ({warm.get('unique_in_profile')})")
if warm.get("predicted_hit_ratio", 0.0) <= 0.0:
    problems.append("warm predicted_hit_ratio is 0")
if problems:
    print("CONTROL-PLANE SMOKE FAIL: " + "; ".join(problems),
          file=sys.stderr)
    sys.exit(1)
print(f"CONTROL-PLANE SMOKE OK: zero operator verbs — "
      f"{ctrl.get('scale_ups')} scale-up(s), quorum + rollout "
      f"converged, {wave.get('extra_requests')} wave requests "
      f"absorbed, recovery probe attainment "
      f"{((rec.get('classes') or {}).get('all') or {}).get('attainment', 0):.2f} "
      f"over {rec.get('samples')} probes on the healed fleet, "
      f"{actrl.get('reconciles')} reconciles logged, "
      f"warm from telemetry predicted "
      f"{warm.get('predicted_hit_ratio'):.2f} "
      f"(realized {warm.get('realized_hit_ratio'):.2f})",
      file=sys.stderr)
EOF
fi


# phase 16: migratable folds + the bulk tier (ISSUE 18) — one replica
# process with durable checkpoint spill + the bulk QoS class, a
# proteome campaign (tools/bulk_submit.py: FASTA manifest -> durable
# idempotent ledger) running UNDER an online wave, then a kill -9 +
# restart + campaign re-run. Gates: bulk admits freeze at ZERO while
# online work is pending and recover after the wave (the tier never
# founds a batch ahead of online traffic); checkpoints actually
# spill; the post-kill re-run skips already-done sequences
# (idempotent ledger) and ends with EVERY manifest sequence in a
# terminal state. The burn-rate yield choreography is pinned
# in-process by tests/test_bulk.py (a stub SLO engine makes it
# deterministic; wall-clock burn in a smoke is not).
if phase_on 16; then
rm -rf /tmp/serve_smoke_bulk
mkdir -p /tmp/serve_smoke_bulk

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python - <<'EOF'
import json
import os
import random
import subprocess
import sys
import threading
import time

sys.path.insert(0, ".")
from alphafold2_tpu.data.featurize import tokenize
from alphafold2_tpu.fleet.procfleet import ProcFleet
from alphafold2_tpu.fleet.rpc import HttpTransport
from alphafold2_tpu.serve import FoldRequest

ROOT = "/tmp/serve_smoke_bulk"
MANIFEST = os.path.join(ROOT, "proteome.fasta")
LEDGER = os.path.join(ROOT, "campaign.jsonl")
AAS = "ACDEFGHIKLMNPQRSTVWY"
N_SEQS = 32

# unique lengths/content per entry: no two campaign folds coalesce
rng = random.Random(18)
with open(MANIFEST, "w") as fh:
    for i in range(N_SEQS):
        seq = "".join(rng.choice(AAS) for _ in range(rng.randint(12, 24)))
        fh.write(f">seq{i:03d}\n{seq}\n")


def campaign(tag):
    """One bulk_submit run; returns (exit_code, stdout)."""
    p = subprocess.run(
        [sys.executable, "tools/bulk_submit.py", MANIFEST,
         "--url", URL, "--ledger", LEDGER, "--max-inflight", "4",
         "--retry-wait", "0.25", "--submit-tries", "40",
         "--poll-budget-s", "240"],
        capture_output=True, text=True)
    sys.stderr.write(f"[campaign {tag}] exit={p.returncode}\n"
                     f"{p.stdout}{p.stderr}\n")
    return p.returncode, p.stdout


def ledger_counts():
    done, seen = 0, set()
    state = {}
    if os.path.exists(LEDGER):
        with open(LEDGER) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                state[rec.get("id")] = rec.get("status")
    seen = set(state)
    done = sum(1 for s in state.values()
               if s in ("ok", "poisoned", "too_large"))
    return done, seen


problems = []
# recycle= turns the step loop on: durable spill rides the step-mode
# cadence gaps, so an opaque-fold replica would never spill
fleet = ProcFleet(1, os.path.join(ROOT, "fleet"), buckets=(32,),
                  max_batch=2, num_recycles=2,
                  model={"dim": 32, "depth": 1, "msa_depth": 0},
                  recycle={"converge_tol": 0.0},
                  checkpoint_spill=True,
                  bulk={"max_burn": 1.0, "check_interval_s": 0.25})
fleet.start()
try:
    URL = fleet.replicas[0].frontdoor_url

    def bulk_stats():
        s = fleet.stats(0) or {}
        return s.get("bulk") or {}

    # run 1 rides in the background while the online wave lands
    t0 = time.monotonic()
    c1 = {}
    th1 = threading.Thread(
        target=lambda: c1.update(zip(("rc", "out"), campaign("run1"))),
        daemon=True)
    th1.start()

    # the campaign must actually be folding before the wave starts
    while not bulk_stats().get("admits"):
        if time.monotonic() - t0 > 120:
            problems.append("no bulk admits within 120s of campaign "
                            f"start (stats {bulk_stats()})")
            break
        time.sleep(0.2)

    # ONLINE WAVE: 24 folds submitted at once — while any of them is
    # pending, the bulk tier must not found a single batch
    transport = HttpTransport(URL, poll_budget_s=240.0)
    wave_rng = random.Random(81)
    tickets = []
    for i in range(24):
        seq = "".join(wave_rng.choice(AAS)
                      for _ in range(wave_rng.randint(12, 24)))
        tickets.append(transport.submit(
            FoldRequest(seq=tokenize(seq))))
    admits_a = bulk_stats().get("admits", 0)
    mid = [t.result(timeout=240) for t in tickets[:12]]
    admits_b = bulk_stats().get("admits", 0)
    rest = [t.result(timeout=240) for t in tickets[12:]]
    wave_ok = sum(1 for r in mid + rest if r.ok)
    if wave_ok != 24:
        problems.append(f"online wave: {wave_ok}/24 ok")
    if admits_b != admits_a:
        problems.append(
            f"bulk admitted {admits_b - admits_a} batch slots while "
            f"online work was pending (the tier must starve, not "
            f"compete)")

    # recovery: with the wave done, the campaign's admits move again
    rec_t0 = time.monotonic()
    while bulk_stats().get("admits", 0) <= admits_b:
        if c1.get("rc") is not None and th1 is not None \
                and not th1.is_alive():
            break            # run 1 already finished — also recovery
        if time.monotonic() - rec_t0 > 120:
            problems.append("bulk admits never recovered after the "
                            "online wave")
            break
        time.sleep(0.2)

    # kill -9 mid-campaign (if run 1 is still going), restart, re-run:
    # the ledger is the only state — the re-run must skip done work
    # and finish the rest
    killed = False
    if th1.is_alive():
        fleet.kill(0)
        killed = True
        th1.join(timeout=300)
        fleet.restart(0)
    else:
        sys.stderr.write("[phase16] run 1 finished before the kill "
                         "window; kill exercised on the re-run fleet\n")
        fleet.kill(0)
        killed = True
        fleet.restart(0)

    done_before, seen_before = ledger_counts()
    rc2, out2 = campaign("run2")
    if rc2 != 0:
        # one more pass: run 2 itself may have straddled the restart
        rc3, out3 = campaign("run3")
        if rc3 != 0:
            problems.append(f"campaign re-run exit {rc3} (run2 {rc2})")
    done_after, seen_after = ledger_counts()
    if done_after != N_SEQS:
        problems.append(f"{N_SEQS - done_after} sequences not "
                        f"terminal-done after re-run")
    if killed and done_before < 1:
        problems.append("kill landed before ANY sequence was done — "
                        "idempotent-skip path never exercised")

    stats = fleet.stats(0) or {}
    spill = (stats.get("resilience") or {}).get("checkpoint_spill") or {}
    spill_stats = spill.get("stats") or {}
    final_bulk = stats.get("bulk") or {}
    if not final_bulk.get("admits"):
        problems.append("restarted replica shows no bulk admits")
    # spills happen at every cadence gap while the knob is on — a run
    # with zero spills means the spill store never engaged
    if not spill_stats.get("spills"):
        problems.append(f"no checkpoint spills recorded ({spill})")
finally:
    fleet.stop()

summary = dict(problems=problems, wave_ok=wave_ok,
               admits_frozen=(admits_b - admits_a) == 0,
               done=done_after, total=N_SEQS,
               done_before_rerun=done_before,
               spills=spill_stats.get("spills"),
               spill_resumes=spill.get("spill_resumes"),
               survivors_at_boot=spill.get("survivors_at_boot"),
               bulk=final_bulk)
print(json.dumps(summary, indent=1, sort_keys=True, default=str))
if problems:
    print("BULK SMOKE FAIL: " + "; ".join(problems), file=sys.stderr)
    sys.exit(1)
print(f"BULK SMOKE OK: {N_SEQS}/{N_SEQS} sequences terminal across a "
      f"kill -9 (ledger-idempotent re-run, {done_before} already done"
      f"), bulk admits frozen at {admits_a} through a 24-fold online "
      f"wave and recovered, {spill_stats.get('spills')} checkpoint "
      f"spills, "
      f"{spill.get('spill_resumes')} spill resumes, "
      f"{spill.get('survivors_at_boot')} survivors at boot",
      file=sys.stderr)
EOF
fi

# phase 17: speculative model cascade + express lane (ISSUE 19) — the
# IDENTICAL mixed workload (24/48-length, 25% express-QoS submissions
# on the short class) run TWICE: the flagship-only baseline, then the
# cascade arm (--cascade: a half-size 0-recycle draft tier in front,
# scripted 0.6 accept rate so both gate paths run at a known mix).
# Gates: both arms 0 bad outcomes with every request served; the
# cascade arm executes STRICTLY FEWER flagship folds than the baseline
# (accepted drafts never reach the flagship); both cascade paths
# actually ran (accepted > 0 AND escalated > 0 — every low-confidence
# fold resolved ok from the flagship, since 0 bad outcomes); the
# express lane's client-side p99 beats the online lane's; and ZERO
# cross-tier cache hits, pinned twice — the report's
# cascade.cross_tier_hits field and the
# serve_cascade_cross_tier_hits_total counter in the Prometheus
# exposition (family must be PRESENT — proving the tripwire was armed
# — with no nonzero sample). The cascade-subsystem tripwire.
if phase_on 17; then
casc_phase() {  # $1 = report path, extra args follow
    local out="$1"; shift
    timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
        python tools/serve_loadtest.py \
        --smoke \
        --requests 48 \
        --lengths 24,48 \
        --buckets 32,64 \
        --msa-depth 3 \
        --max-batch 2 \
        --concurrency 2 \
        --num-recycles 0 \
        --cache on \
        --express-rate 0.25 \
        --metrics-path /tmp/serve_smoke_casc.jsonl \
        "$@" > "$out"
}

casc_phase /tmp/serve_smoke_casc_base.json
casc_phase /tmp/serve_smoke_casc_on.json \
    --cascade --draft-accept-rate 0.6 \
    --prom-path /tmp/serve_smoke_casc.prom

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python - <<'EOF'
import json
import sys

base = json.load(open("/tmp/serve_smoke_casc_base.json"))
casc = json.load(open("/tmp/serve_smoke_casc_on.json"))
problems = []
for name, rep in (("baseline", base), ("cascade", casc)):
    bad = rep["shed"] + rep["errors"] + rep["rejected"] \
        + len(rep["failures"])
    # "ok" counts every resolved ticket — executed folds AND store
    # hits (the express short-class substitution repeats prototypes,
    # so a few folds legitimately resolve from the cache)
    ok = (rep.get("statuses") or {}).get("ok", 0)
    if bad or ok != rep["requests"]:
        problems.append(f"{name} arm: {bad} bad outcomes, "
                        f"{ok}/{rep['requests']} ok")

c = casc.get("cascade") or {}
# the efficiency gate: accepted drafts must actually displace
# flagship executions on the identical schedule
if c.get("flagship_folds", 10**9) >= base["served"]:
    problems.append(
        f"cascade arm executed {c.get('flagship_folds')} flagship "
        f"folds — not fewer than the baseline's {base['served']}")
if not c.get("draft_accepted") or not c.get("escalated"):
    problems.append(f"cascade never exercised both gate paths "
                    f"(accepted {c.get('draft_accepted')}, "
                    f"escalated {c.get('escalated')})")
if c.get("cross_tier_hits"):
    problems.append(f"{c['cross_tier_hits']} cross-tier cache hits "
                    f"in the report")

lanes = casc.get("latency_by_lane") or {}
exp, onl = lanes.get("express"), lanes.get("online")
if not exp or not onl:
    problems.append(f"lane latency split missing ({lanes})")
elif exp["p99_s"] >= onl["p99_s"]:
    problems.append(f"express p99 {exp['p99_s']}s not under online "
                    f"p99 {onl['p99_s']}s")

# counter pin: the family must exist (tripwire armed) with no
# nonzero sample — a zero labelless counter exports HELP/TYPE only
prom = open("/tmp/serve_smoke_casc.prom").read()
fam = "serve_cascade_cross_tier_hits_total"
if fam not in prom:
    problems.append(f"{fam} missing from the Prometheus exposition")
for line in prom.splitlines():
    if line.startswith(fam) and not line.startswith("#"):
        if float(line.split()[-1]) != 0.0:
            problems.append(f"{fam} nonzero in the exposition: {line}")

if problems:
    print("CASCADE SMOKE FAIL: " + "; ".join(problems),
          file=sys.stderr)
    sys.exit(1)
print(f"CASCADE SMOKE OK: {c['draft_accepted']} drafts accepted / "
      f"{c['escalated']} escalated (accept rate "
      f"{round(c['accept_rate'], 3)}), flagship folds "
      f"{c['flagship_folds']} < baseline {base['served']}, "
      f"0 cross-tier hits, express p99 {exp['p99_s']}s < online "
      f"{onl['p99_s']}s, "
      f"{c['accel_seconds_per_accepted']} accel-seconds per "
      f"accepted fold", file=sys.stderr)
EOF
fi

# phase 18: spot-preemptible serving (ISSUE 20) — a 3-process fleet
# with the preemption knob + FleetController loses one replica to a
# REAL spot reclaim mid-campaign: the preempt() verb delivers a
# notice file, the victim's PreemptionWatcher flips its scheduler
# into reclaim mode, the grace-budgeted drain spills every mid-loop
# fold the window can't fit (num-recycles is deliberately far larger
# than the grace window buys, so the spill-over-finish decision MUST
# fire), the orphan manifest lands in the shared backend, the victim
# exits 0 BEFORE the hard kill -9, and the controller actively
# assigns the orphans to a least-loaded survivor through
# POST /admin/adopt. FAILS unless every request resolves ok with 0
# lost (the survivors + client fast failover absorb the window),
# the victim exited 0, >= 1 orphan was spilled AND every orphan was
# adopted by controller assignment (not lazy peer probes), preempt +
# adopt spans are present in the merged traces, and obs_report
# --check is clean over them. The spot-reclaim tripwire.
if phase_on 18; then
rm -rf /tmp/serve_smoke_preempt
rm -f /tmp/serve_smoke_preempt_traces.jsonl

timeout -k 10 600 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/serve_loadtest.py \
    --smoke \
    --procs 3 \
    --controller \
    --scale-min 3 \
    --scale-max 5 \
    --preempt-at 0.4 \
    --preempt-grace-s 3 \
    --requests 36 \
    --lengths 48,96 \
    --buckets 64,128 \
    --msa-depth 3 \
    --max-batch 2 \
    --concurrency 3 \
    --deadline-s 180 \
    --num-recycles 32 \
    --proc-run-dir /tmp/serve_smoke_preempt \
    --trace-path /tmp/serve_smoke_preempt_traces.jsonl \
    > /tmp/serve_smoke_preempt.json
cat /tmp/serve_smoke_preempt.json

timeout -k 10 120 env -u PYTHONPATH JAX_PLATFORMS=cpu \
    python tools/obs_report.py /tmp/serve_smoke_preempt_traces.jsonl \
    --check --json > /tmp/serve_smoke_preempt_obs.json

env -u PYTHONPATH python - <<'EOF'
import json, sys
run = json.load(open("/tmp/serve_smoke_preempt.json"))
obs = json.load(open("/tmp/serve_smoke_preempt_obs.json"))
problems = []
pre = run.get("preemption") or {}
if run.get("lost", 0):
    problems.append(f"{run['lost']} LOST requests")
if pre.get("exit_code") != 0:
    problems.append(f"victim exited {pre.get('exit_code')}, not 0 "
                    f"(grace drain should beat the kill -9)")
orphans = pre.get("orphans") or 0
if orphans < 1:
    problems.append("no orphans spilled — the grace window fit the "
                    "whole backlog and the spill decision never ran")
ads = pre.get("adoptions") or {}
if ads.get("adopted", 0) < orphans:
    problems.append(f"{ads.get('adopted', 0)}/{orphans} orphans "
                    f"adopted by the controller")
if not (ads.get("by_source") or {}):
    problems.append("no adoption source recorded (expected notice "
                    "or sweep)")
spans = run.get("span_counts") or {}
if orphans and not spans.get("preempt"):
    problems.append("no preempt spans in the merged traces")
if ads.get("adopted") and not spans.get("adopt"):
    problems.append("no adopt spans in the merged traces")
if obs.get("problems"):
    problems.append(f"obs_report check: {obs['problems'][:3]}")
if problems:
    print("PREEMPT SMOKE FAIL: " + "; ".join(problems),
          file=sys.stderr)
    sys.exit(1)
print(f"PREEMPT SMOKE OK: victim exited 0 inside "
      f"{pre.get('grace_s')}s grace, {orphans} orphan(s) spilled "
      f"and {ads.get('adopted')} adopted via "
      f"{list((ads.get('by_source') or {}).keys())}, 0 lost folds, "
      f"preempt/adopt spans present", file=sys.stderr)
EOF
fi
