"""Measure the reference implementation's step time on matched configs.

The reference (lucidrains/alphafold2) publishes no numbers (BASELINE.md), so
the baseline is measured here: its distogram training step (forward + CE
loss + backward + Adam step) at dim=256, depth=2, 256-res crop, batch 1,
5-row MSA — torch CPU (the only backend the reference can use in this
container). Writes tools/reference_baseline.json.
"""
import json, os, sys, time

sys.path.insert(0, "/root/reference")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _reference_stubs  # noqa: F401
import torch
import torch.nn.functional as F

from alphafold2_pytorch import Alphafold2
from alphafold2_pytorch.utils import get_bucketed_distance_matrix

torch.manual_seed(0)
torch.set_num_threads(os.cpu_count())
DIM, DEPTH, L, MSA, B = 256, 2, 256, 5, 1

model = Alphafold2(dim=DIM, depth=DEPTH, heads=8, dim_head=64)
opt = torch.optim.Adam(model.parameters(), lr=3e-4)

seq = torch.randint(0, 21, (B, L))
msa = torch.randint(0, 21, (B, MSA, L))
mask = torch.ones(B, L).bool()
msa_mask = torch.ones(B, MSA, L).bool()
coords = torch.cumsum(torch.randn(B, L, 3), dim=1)

def step():
    ret = model(seq, msa, mask=mask, msa_mask=msa_mask)
    target = get_bucketed_distance_matrix(coords, mask)
    loss = F.cross_entropy(ret.distance.reshape(-1, 37), target.reshape(-1),
                           ignore_index=-100)
    if ret.msa_mlm_loss is not None:
        loss = loss + ret.msa_mlm_loss
    loss.backward()
    opt.step(); opt.zero_grad()
    return float(loss)

# warmup
step()
times = []
for _ in range(3):
    t0 = time.perf_counter(); step(); times.append(time.perf_counter() - t0)

fwd_times = []
with torch.no_grad():
    model.eval()
    for _ in range(3):
        t0 = time.perf_counter()
        model(seq, msa, mask=mask, msa_mask=msa_mask)
        fwd_times.append(time.perf_counter() - t0)

out = {
    "config": {"dim": DIM, "depth": DEPTH, "seq_len": L, "msa_depth": MSA,
               "batch": B, "backend": "torch-cpu",
               "threads": torch.get_num_threads()},
    "train_step_seconds": min(times),
    "forward_seconds": min(fwd_times),
}
with open(os.path.join(os.path.dirname(__file__), "reference_baseline.json"),
          "w") as f:
    json.dump(out, f, indent=2)
print(json.dumps(out))
