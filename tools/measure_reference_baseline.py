"""Measure the reference implementation's step time on matched configs.

The reference (lucidrains/alphafold2) publishes no numbers (BASELINE.md), so
the baseline is measured here: its distogram training step (forward + CE
loss + backward + Adam step) — torch CPU (the only backend the reference can
use in this container) — at the bench's full config (dim=256, depth=2,
256-res) and at bench.py's CPU-fallback ladder configs, so a fallback bench
run still gets a matched-config `vs_baseline`. Merges into
tools/reference_baseline.json: top-level keys keep the full-config
measurement (original schema); `entries` holds every measured config.

Usage: python tools/measure_reference_baseline.py [dimxdepthxlen ...]
(default: 128x2x128 64x2x64; pass 256x2x256 to re-measure the full config,
~15 min on this 1-core host).
"""
import json, os, sys, time

sys.path.insert(0, "/root/reference")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _reference_stubs  # noqa: F401
import torch
import torch.nn.functional as F

from alphafold2_pytorch import Alphafold2
from alphafold2_pytorch.utils import get_bucketed_distance_matrix

MSA, B = 5, 1
_OUT = os.path.join(os.path.dirname(__file__), "reference_baseline.json")


def measure(dim: int, depth: int, L: int, iters: int = 3) -> dict:
    torch.manual_seed(0)
    model = Alphafold2(dim=dim, depth=depth, heads=8, dim_head=64)
    opt = torch.optim.Adam(model.parameters(), lr=3e-4)

    seq = torch.randint(0, 21, (B, L))
    msa = torch.randint(0, 21, (B, MSA, L))
    mask = torch.ones(B, L).bool()
    msa_mask = torch.ones(B, MSA, L).bool()
    coords = torch.cumsum(torch.randn(B, L, 3), dim=1)

    def step():
        ret = model(seq, msa, mask=mask, msa_mask=msa_mask)
        target = get_bucketed_distance_matrix(coords, mask)
        loss = F.cross_entropy(ret.distance.reshape(-1, 37),
                               target.reshape(-1), ignore_index=-100)
        if ret.msa_mlm_loss is not None:
            loss = loss + ret.msa_mlm_loss
        loss.backward()
        opt.step(); opt.zero_grad()
        return float(loss)

    step()  # warmup
    times = []
    for _ in range(iters):
        t0 = time.perf_counter(); step()
        times.append(time.perf_counter() - t0)

    fwd_times = []
    with torch.no_grad():
        model.eval()
        for _ in range(iters):
            t0 = time.perf_counter()
            model(seq, msa, mask=mask, msa_mask=msa_mask)
            fwd_times.append(time.perf_counter() - t0)

    return {
        "config": {"dim": dim, "depth": depth, "seq_len": L,
                   "msa_depth": MSA, "batch": B, "backend": "torch-cpu",
                   "threads": torch.get_num_threads()},
        "train_step_seconds": min(times),
        "forward_seconds": min(fwd_times),
    }


def main():
    torch.set_num_threads(os.cpu_count())
    configs = [tuple(int(x) for x in a.split("x")) for a in sys.argv[1:]] \
        or [(128, 2, 128), (64, 2, 64)]

    data = {}
    if os.path.exists(_OUT):
        with open(_OUT) as f:
            data = json.load(f)
    entries = data.get("entries", [])
    if "config" in data:  # fold the original top-level entry in
        entries.append({"config": data["config"],
                        "train_step_seconds": data["train_step_seconds"],
                        "forward_seconds": data.get("forward_seconds")})

    for dim, depth, L in configs:
        e = measure(dim, depth, L)
        print(json.dumps(e), flush=True)
        entries = [x for x in entries if x["config"] != e["config"]] + [e]

    # de-dup by (dim, depth, seq_len, msa, batch); last write wins
    seen, merged = {}, []
    for e in entries:
        c = e["config"]
        seen[(c["dim"], c["depth"], c["seq_len"],
              c["msa_depth"], c["batch"])] = e
    merged = sorted(seen.values(), key=lambda e: -e["config"]["dim"])

    out = {"entries": merged}
    full = seen.get((256, 2, 256, MSA, B))
    if full:  # keep original top-level schema for the full config
        out.update(full)
    with open(_OUT, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {_OUT} with {len(merged)} entries")


if __name__ == "__main__":
    main()
