"""Round-long TPU tunnel probe daemon (VERDICT round-4 item #1).

The tunnel's observed failure modes (rounds 2-4): the tiny-op probe times
out, or — half-wedged — tiny-op passes and the model compile hangs. This
daemon spreads cheap probes across the whole round so a briefly-live
tunnel is caught, logs EVERY attempt with timestamps to
tools/tpu_probe_log.json (the committed evidence either way), and on the
first success immediately spends the window running the prepared on-chip
suite in priority order:

  1. python bench.py                       -> tools/tpu_bench_live.json
  2. BENCH_PALLAS=1 python bench.py        -> tools/tpu_bench_pallas.json
  3. python tools/bench_blocksparse.py     -> tools/tpu_blocksparse.json
  4. python tools/bench_suite.py (on-chip) -> tools/tpu_bench_suite.json

Artifacts land in tools/ (never auto-committed — the foreground session
commits them); tools/TPU_WOKE is touched as a flag. Runs until killed or
--max-hours elapses.

Usage: python tools/tpu_probe.py [--interval 600] [--max-hours 11]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

LOG = os.path.join(_REPO, "tools", "tpu_probe_log.json")
WOKE = os.path.join(_REPO, "tools", "TPU_WOKE")


def _load_log() -> dict:
    if os.path.exists(LOG):
        try:
            with open(LOG) as f:
                return json.load(f)
        except Exception:
            pass
    return {"probes": [], "runs": []}


def _save_log(log: dict) -> None:
    tmp = LOG + ".tmp"
    with open(tmp, "w") as f:
        json.dump(log, f, indent=1)
    os.replace(tmp, LOG)


def _probe(timeout_s: int = 90) -> tuple[bool, float]:
    from __graft_entry__ import tiny_op_probe
    t0 = time.monotonic()
    ok = tiny_op_probe(timeout_s=timeout_s)
    return ok, round(time.monotonic() - t0, 1)


def _run(cmd: list[str], env_extra: dict, timeout_s: float, out_path: str,
         log: dict, label: str) -> bool:
    """Run one on-chip command; capture its last JSON line to out_path."""
    env = dict(os.environ)
    env.update(env_extra)
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, env=env, cwd=_REPO, capture_output=True,
                              text=True, timeout=timeout_s)
        note, rc = "done", proc.returncode
        stdout = proc.stdout
    except subprocess.TimeoutExpired as e:
        note, rc = f"timeout after {timeout_s:.0f}s", -1
        stdout = (e.stdout.decode(errors="replace")
                  if isinstance(e.stdout, bytes) else (e.stdout or ""))
    payload = None
    for line in reversed((stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                payload = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    def _is_tpu(p) -> bool:
        plat = (p or {}).get("platform") or ""
        return "tpu" in plat or plat == "axon"

    wrote = False
    if payload is not None:
        # write-once-if-better: never clobber a previously captured
        # on-chip artifact with a CPU-fallback/skipped payload from a
        # later, degraded window
        existing = None
        if os.path.exists(out_path):
            try:
                with open(out_path) as f:
                    existing = json.load(f)
            except Exception:
                existing = None
        if _is_tpu(payload) or not _is_tpu(existing):
            with open(out_path, "w") as f:
                json.dump(payload, f, indent=1)
            wrote = True
    log["runs"].append({
        "label": label, "ts": time.time(),
        "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "cmd": " ".join(cmd), "rc": rc, "note": note,
        "seconds": round(time.time() - t0, 1),
        "artifact": out_path if wrote else None,
        "platform": (payload or {}).get("platform"),
        "value": (payload or {}).get("value"),
    })
    _save_log(log)
    # success for our purposes = a JSON artifact whose platform is the TPU
    return _is_tpu(payload)


def _on_chip_suite(log: dict) -> None:
    t = os.path.join(_REPO, "tools")
    py = sys.executable
    _run([py, "bench.py"], {"BENCH_TIMEOUT_S": "1500",
                            "BENCH_NO_FALLBACK": "1"},
         1520, os.path.join(t, "tpu_bench_live.json"), log, "bench-tpu")
    _run([py, "bench.py"], {"BENCH_PALLAS": "1", "BENCH_TIMEOUT_S": "1200",
                            "BENCH_NO_FALLBACK": "1"},
         1220, os.path.join(t, "tpu_bench_pallas.json"), log, "bench-pallas")
    _run([py, os.path.join(t, "bench_blocksparse.py")], {},
         1200, os.path.join(t, "tpu_blocksparse.json"), log, "blocksparse")
    _run([py, os.path.join(t, "bench_suite.py"), "--configs", "1,2"], {},
         2400, os.path.join(t, "tpu_bench_suite.json"), log, "suite-onchip")
    with open(WOKE, "w") as f:
        f.write(time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600.0,
                    help="seconds between probes")
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--once", action="store_true",
                    help="single probe, no loop")
    args = ap.parse_args()

    log = _load_log()
    t_end = time.monotonic() + args.max_hours * 3600
    while True:
        ok, latency = _probe()
        log["probes"].append({
            "ts": time.time(),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "ok": ok, "latency_s": latency,
        })
        _save_log(log)
        print(f"probe ok={ok} latency={latency}s "
              f"({len(log['probes'])} total)", flush=True)
        if ok:
            _on_chip_suite(log)
            # keep probing afterwards (cheaper cadence) in case a later,
            # longer window allows a re-run of anything that timed out
            args.interval = max(args.interval, 900.0)
        if args.once or time.monotonic() > t_end:
            break
        time.sleep(args.interval)
    return 0


if __name__ == "__main__":
    sys.exit(main())
