"""Round-long TPU tunnel probe daemon (VERDICT round-4 item #1).

The tunnel's observed failure modes (rounds 2-4): the tiny-op probe times
out, or — half-wedged — tiny-op passes and the model compile hangs. This
daemon spreads cheap probes across the whole round so a briefly-live
tunnel is caught, logs EVERY attempt with timestamps to
tools/tpu_probe_log.json (the committed evidence either way), and on the
first success immediately spends the window running the prepared on-chip
suite in priority order:

  1. python bench.py                       -> tools/tpu_bench_live.json
  2. BENCH_PALLAS=1 python bench.py        -> tools/tpu_bench_pallas.json
  3. python tools/bench_blocksparse.py     -> tools/tpu_blocksparse.json
  4. python tools/bench_suite.py (on-chip) -> tools/tpu_bench_suite.json

Artifacts land in tools/ (never auto-committed — the foreground session
commits them); tools/TPU_WOKE is touched as a flag. Runs until killed or
--max-hours elapses.

Usage: python tools/tpu_probe.py [--interval 600] [--max-hours 11]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

LOG = os.path.join(_REPO, "tools", "tpu_probe_log.json")
WOKE = os.path.join(_REPO, "tools", "TPU_WOKE")


def _load_log() -> dict:
    if os.path.exists(LOG):
        try:
            with open(LOG) as f:
                return json.load(f)
        except Exception:
            pass
    return {"probes": [], "runs": []}


def _save_log(log: dict) -> None:
    tmp = LOG + ".tmp"
    with open(tmp, "w") as f:
        json.dump(log, f, indent=1)
    os.replace(tmp, LOG)


def _probe(timeout_s: int = 90) -> tuple[bool, float]:
    from __graft_entry__ import tiny_op_probe
    t0 = time.monotonic()
    ok = tiny_op_probe(timeout_s=timeout_s)
    return ok, round(time.monotonic() - t0, 1)


def _run(cmd: list[str], env_extra: dict, timeout_s: float, out_path: str,
         log: dict, label: str) -> bool:
    """Run one on-chip command; persist ALL its JSON output lines (a
    multi-config suite prints one per config) plus a raw-stdout sidecar,
    so nothing from a rare live-tunnel window is lost."""
    env = dict(os.environ)
    env.update(env_extra)
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, env=env, cwd=_REPO, capture_output=True,
                              text=True, timeout=timeout_s)
        note, rc = "done", proc.returncode
        stdout = proc.stdout
    except subprocess.TimeoutExpired as e:
        note, rc = f"timeout after {timeout_s:.0f}s", -1
        stdout = (e.stdout.decode(errors="replace")
                  if isinstance(e.stdout, bytes) else (e.stdout or ""))
    payloads = []
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                payloads.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    payload = payloads[-1] if payloads else None

    from __graft_entry__ import is_tpu_platform

    def _is_tpu(p) -> bool:
        return is_tpu_platform((p or {}).get("platform"))

    wrote = False
    if payloads:
        # raw stdout sidecar: the artifact can never silently drop
        # evidence the subprocess printed (a multi-config suite emits
        # one JSON line PER config)
        with open(out_path + ".stdout.txt", "w") as f:
            f.write(stdout or "")
        # write-once-if-better: never clobber a previously captured
        # on-chip artifact with a CPU-fallback/skipped payload from a
        # later, degraded window
        existing = None
        if os.path.exists(out_path):
            try:
                with open(out_path) as f:
                    existing = json.load(f)
            except Exception:
                existing = None
        exist_list = existing if isinstance(existing, list) else \
            [existing] if existing else []
        if any(map(_is_tpu, payloads)) or \
                not any(map(_is_tpu, exist_list)):
            with open(out_path, "w") as f:
                json.dump(payloads if len(payloads) > 1 else payload,
                          f, indent=1)
            wrote = True
    log["runs"].append({
        "label": label, "ts": time.time(),
        "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "cmd": " ".join(cmd), "rc": rc, "note": note,
        "seconds": round(time.time() - t0, 1),
        "artifact": out_path if wrote else None,
        "platform": (payload or {}).get("platform"),
        "value": (payload or {}).get("value"),
    })
    _save_log(log)
    # success for our purposes = any JSON payload whose platform is the TPU
    return any(map(_is_tpu, payloads))


def _on_chip_suite(log: dict, budget_s: float) -> None:
    """Run the prepared on-chip commands in priority order, skipping any
    whose timeout no longer fits the remaining --max-hours budget (so the
    daemon cannot overrun the round boundary by a suite length)."""
    t = os.path.join(_REPO, "tools")
    py = sys.executable
    t_stop = time.monotonic() + budget_s
    plan = [
        ([py, "bench.py"], {"BENCH_TIMEOUT_S": "1500",
                            "BENCH_NO_FALLBACK": "1"},
         1520, os.path.join(t, "tpu_bench_live.json"), "bench-tpu"),
        ([py, "bench.py"], {"BENCH_PALLAS": "1", "BENCH_TIMEOUT_S": "1200",
                            "BENCH_NO_FALLBACK": "1"},
         1220, os.path.join(t, "tpu_bench_pallas.json"), "bench-pallas"),
        ([py, os.path.join(t, "bench_blocksparse.py")], {},
         1200, os.path.join(t, "tpu_blocksparse.json"), "blocksparse"),
        ([py, os.path.join(t, "bench_suite.py"), "--configs", "1,2"], {},
         2400, os.path.join(t, "tpu_bench_suite.json"), "suite-onchip"),
    ]
    for cmd, env_extra, timeout_s, out_path, label in plan:
        remaining = t_stop - time.monotonic()
        if remaining < min(timeout_s, 300):
            log["runs"].append({
                "label": label, "ts": time.time(),
                "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "note": f"skipped: {remaining:.0f}s budget left",
                "rc": None, "seconds": 0, "artifact": None,
                "platform": None, "value": None, "cmd": " ".join(cmd)})
            _save_log(log)
            continue
        _run(cmd, env_extra, min(timeout_s, remaining), out_path, log,
             label)
    with open(WOKE, "w") as f:
        f.write(time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600.0,
                    help="seconds between probes")
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--once", action="store_true",
                    help="single probe, no loop")
    args = ap.parse_args()

    log = _load_log()
    t_end = time.monotonic() + args.max_hours * 3600
    while True:
        ok, latency = _probe()
        log["probes"].append({
            "ts": time.time(),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "ok": ok, "latency_s": latency,
        })
        _save_log(log)
        print(f"probe ok={ok} latency={latency}s "
              f"({len(log['probes'])} total)", flush=True)
        if ok:
            _on_chip_suite(log, budget_s=t_end - time.monotonic())
            # keep probing afterwards (cheaper cadence) in case a later,
            # longer window allows a re-run of anything that timed out
            args.interval = max(args.interval, 900.0)
        if args.once or time.monotonic() + args.interval > t_end:
            break
        time.sleep(args.interval)
    return 0


if __name__ == "__main__":
    sys.exit(main())
