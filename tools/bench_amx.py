"""Microbenchmark the native AMX GEMM against XLA:CPU's dot.

Times the four FFI entry points (plain, transposed-B, and the two
natural-layout attention ops) at the model's Dense and attention shapes,
next to the matching XLA contraction. One JSON line per shape.

Caveat on this host: sustained AMX load power-licenses the core, so
absolute GFLOP/s swing ~25% run to run — compare the paired ours/xla
numbers within one invocation, not across invocations.

Usage: python tools/bench_amx.py [--iters 10]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PYTHONPATH", None)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from alphafold2_tpu.ops import cpu_gemm  # noqa: E402


def _time(fn, *args, iters=10):
    fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) else \
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    cpu_gemm.use_amx_dense(True)
    if not cpu_gemm.amx_dense_enabled():
        print(json.dumps({"error": "AMX unavailable on this host"}))
        return 1

    key = jax.random.PRNGKey(0)

    # Dense shapes at the bench full config (dim 256, 256res: 65536 pair
    # tokens) and the attention shapes (256 rows x 8 heads, 256 keys, 64)
    shapes = [
        ("dense_qkv", "gemm", (65536, 256, 512)),
        ("dense_ff", "gemm", (65536, 256, 2048)),
        ("attn_qk", "attn", (256, 256, 256, 8, 64)),
    ]
    for name, kind, dims in shapes:
        if kind == "gemm":
            m, k, n = dims
            a = jax.random.normal(key, (m, k), jnp.float32)
            b = jax.random.normal(key, (k, n), jnp.float32)
            t_amx = _time(jax.jit(cpu_gemm.amx_matmul), a, b,
                          iters=args.iters)
            t_xla = _time(jax.jit(jnp.matmul), a, b, iters=args.iters)
            flops = 2.0 * m * k * n
        else:
            b_, n, m, h, d = dims
            q = jax.random.normal(key, (b_ // h, n, h, d), jnp.float32)
            kk = jax.random.normal(key, (b_ // h, m, h, d), jnp.float32)
            t_amx = _time(jax.jit(cpu_gemm.amx_attn_qk), q, kk,
                          iters=args.iters)
            t_xla = _time(
                jax.jit(lambda q, k: jnp.einsum("bnhd,bmhd->bhnm", q, k)),
                q, kk, iters=args.iters)
            flops = 2.0 * (b_ // h) * h * n * m * d
        print(json.dumps({
            "shape": name, "dims": dims,
            "amx_gflops": round(flops / t_amx / 1e9, 1),
            "xla_gflops": round(flops / t_xla / 1e9, 1),
            "speedup": round(t_xla / t_amx, 2)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
