#!/usr/bin/env python
"""Proteome-scale bulk campaign driver (ISSUE 18).

Reads a manifest of sequences (FASTA or JSONL), tokenizes CLIENT-side
(data.featurize.tokenize — the bulk tier rides the tokenized front-door
path; the raw/featurize pipeline stays online-only), and submits every
unfinished sequence as `FoldRequest(qos="bulk")` against a replica's
front door. The receiving scheduler parks bulk work in its BulkQueue:
admitted only by work-stealing through freed batch rows, never ahead of
online traffic, throttled by the SLO engine's burn rate
(`serve.BulkPolicy`).

Campaign sharding (ISSUE 19): `--fleet ID=URL,...` spreads the
manifest across replicas by fold-key RING OWNER — the client computes
each sequence's `fold_key` and the same blake2b/vnode consistent hash
the data plane's `ConsistentHashRouter` builds
(`fleet.router.static_owner_for`), so every fold lands where
coalescing leadership, the peer-cache home, and checkpoint spill
locality already are. A submit refused by the owner fails over around
the ring (the receiving scheduler serves bulk locally either way).
For the client key to equal the server's, --model-tag /
--num-recycles / --msa-depth must match the fleet config; the ring
shard is deterministic across re-runs regardless.

Every ledger record carries the `fold_key`, which is what the control
plane's checkpoint GC (`fleet.CheckpointGC` ->
`CheckpointStore.sweep_orphans`) matches terminal folds against.

The campaign is DURABLE and IDEMPOTENT:

- every terminal result appends one JSONL record to the --ledger
  (`{"id", "key", "status", "ts", ...}`);
- a re-run loads the ledger first and skips sequences whose latest
  status is done ("ok", "poisoned", "too_large" — refolding a poison
  input or an impossible shape buys nothing), while "error"/"shed"/
  "cancelled"/"degraded"/unrecorded sequences are submitted again;
- kill the driver at any point and re-run with the same flags — the
  ledger is the only state.

--max-inflight bounds outstanding submissions (the replica's bulk
queue has its own max_pending; a full queue or closed front door is
retried with --retry-wait backoff). Exit 0 iff every manifest sequence
has a terminal ledger state when the run ends.

Usage:
    python tools/bulk_submit.py proteome.fasta \
        --url http://127.0.0.1:8000 --ledger campaign.jsonl \
        --max-inflight 8
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# latest ledger status in this set == done forever; anything else is
# retried on the next run
DONE_STATUSES = ("ok", "poisoned", "too_large")


def parse_manifest(path):
    """Yield (id, seq_string) from FASTA (>id\\nSEQ) or JSONL
    ({"id":..., "seq":...}) — sniffed per file from the first
    non-blank character."""
    with open(path) as fh:
        first = ""
        for line in fh:
            if line.strip():
                first = line.strip()[0]
                break
    if first == ">":
        return list(_parse_fasta(path))
    return list(_parse_jsonl(path))


def _parse_fasta(path):
    name, chunks = None, []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            if line.startswith(">"):
                if name is not None and chunks:
                    yield name, "".join(chunks)
                name, chunks = line[1:].split()[0], []
            else:
                chunks.append(line)
    if name is not None and chunks:
        yield name, "".join(chunks)


def _parse_jsonl(path):
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            rid = str(row.get("id", f"row-{lineno}"))
            yield rid, str(row["seq"])


def load_ledger(path):
    """id -> latest recorded status (later lines win: the ledger is
    append-only, one record per terminal result)."""
    state = {}
    if not path or not os.path.exists(path):
        return state
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue          # torn tail from a killed driver
            if "id" in rec and "status" in rec:
                state[str(rec["id"])] = str(rec["status"])
    return state


def parse_fleet(spec):
    """`ID=URL,ID=URL,...` -> ordered [(rid, url)]. Raises ValueError
    on malformed items or duplicate ids — a typo'd fleet map must fail
    loudly, not silently shard everything onto one replica."""
    pairs = []
    seen = set()
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(f"fleet item {item!r} is not ID=URL")
        rid, _, url = item.partition("=")
        rid, url = rid.strip(), url.strip()
        if not rid or not url:
            raise ValueError(f"fleet item {item!r} is not ID=URL")
        if rid in seen:
            raise ValueError(f"duplicate fleet replica id {rid!r}")
        seen.add(rid)
        pairs.append((rid, url))
    if not pairs:
        raise ValueError(f"empty fleet spec {spec!r}")
    return pairs


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("manifest", help="FASTA or JSONL sequence manifest")
    ap.add_argument("--url",
                    help="replica front-door base URL (single-replica "
                         "campaign; exactly one of --url/--fleet)")
    ap.add_argument("--fleet",
                    help="ID=URL,... replica map: shard the manifest "
                         "by fold-key ring owner with submit failover "
                         "around the ring")
    ap.add_argument("--model-tag", default="",
                    help="serving model tag for client-side fold_key "
                         "(match the fleet config so ledger keys equal "
                         "server cache/checkpoint keys)")
    ap.add_argument("--num-recycles", type=int, default=0,
                    help="serving num_recycles for client-side fold_key")
    ap.add_argument("--msa-depth", type=int, default=None,
                    help="serving msa_depth for client-side fold_key "
                         "(default: unset, like SchedulerConfig)")
    ap.add_argument("--ledger", required=True,
                    help="campaign ledger JSONL (created if missing)")
    ap.add_argument("--max-inflight", type=int, default=8,
                    help="outstanding submissions bound")
    ap.add_argument("--deadline-s", type=float, default=0.0,
                    help="per-fold deadline (0 = none; bulk work "
                         "usually wants none — the tier already "
                         "yields to online load)")
    ap.add_argument("--retry-wait", type=float, default=0.5,
                    help="backoff when submit itself is refused "
                         "(full bulk queue, draining front door)")
    ap.add_argument("--submit-tries", type=int, default=20,
                    help="submit attempts per sequence before "
                         "recording a transport error for this run")
    ap.add_argument("--poll-budget-s", type=float, default=600.0,
                    help="max wait for one fold's terminal result")
    args = ap.parse_args(argv)
    if bool(args.url) == bool(args.fleet):
        ap.error("exactly one of --url / --fleet is required")

    import numpy as np  # noqa: F401  (transport decodes need it)

    from alphafold2_tpu.cache import fold_key
    from alphafold2_tpu.data.featurize import tokenize
    from alphafold2_tpu.fleet.router import static_owner_for
    from alphafold2_tpu.fleet.rpc import HttpTransport
    from alphafold2_tpu.serve import FoldRequest

    rows = parse_manifest(args.manifest)
    done = load_ledger(args.ledger)
    todo = [(rid, seq) for rid, seq in rows
            if done.get(rid) not in DONE_STATUSES]
    print(f"manifest: {len(rows)} sequences, "
          f"{len(rows) - len(todo)} already done, {len(todo)} to fold")
    if not todo:
        return 0

    if args.fleet:
        fleet = parse_fleet(args.fleet)
    else:
        fleet = [("replica", args.url)]
    ring_ids = [rid for rid, _ in fleet]
    transports = {rid: HttpTransport(url,
                                     poll_budget_s=args.poll_budget_s)
                  for rid, url in fleet}
    ledger_lock = threading.Lock()
    ledger_fh = open(args.ledger, "a")
    sem = threading.Semaphore(max(1, args.max_inflight))
    outstanding = []              # (id, ticket) for the final wait
    statuses = {}

    def record(rid, status, **extra):
        rec = dict(id=rid, status=status, ts=time.time(), **extra)
        with ledger_lock:
            statuses[rid] = status
            ledger_fh.write(json.dumps(rec) + "\n")
            ledger_fh.flush()

    def on_done(rid, t0, fk, owner):
        def _cb(resp):
            record(rid, resp.status, key=resp.request_id,
                   fold_key=fk, replica=owner,
                   latency_s=round(time.monotonic() - t0, 3),
                   source=resp.source,
                   **({"error": resp.error} if resp.error else {}))
            sem.release()
        return _cb

    for rid, seq in todo:
        sem.acquire()
        try:
            tokens = tokenize(seq)
            fk = fold_key(tokens, msa_depth=args.msa_depth,
                          num_recycles=args.num_recycles,
                          model_tag=args.model_tag)
        except Exception as exc:
            record(rid, "error", error=f"tokenize: {exc}")
            sem.release()
            continue
        req = FoldRequest(
            seq=tokens, qos="bulk",
            deadline_s=(args.deadline_s or None))
        # shard by ring owner; failover walks the rest of the ring in
        # deterministic order before backing off (any replica SERVES
        # bulk locally — the shard is a locality preference, never a
        # correctness requirement)
        owner = static_owner_for(fk, ring_ids)
        candidates = [owner] + [r for r in ring_ids if r != owner]
        ticket = None
        used = owner
        for attempt in range(max(1, args.submit_tries)):
            target = candidates[attempt % len(candidates)]
            try:
                ticket = transports[target].submit(req)
                used = target
                break
            except Exception as exc:
                err = str(exc)
                if attempt % len(candidates) == len(candidates) - 1:
                    # the whole ring refused this round: back off
                    time.sleep(args.retry_wait)
        if ticket is None:
            # transport never accepted it: NOT terminal-done — the
            # next run retries this sequence
            record(rid, "error", fold_key=fk, error=f"submit: {err}")
            sem.release()
            continue
        t0 = time.monotonic()
        ticket.add_done_callback(on_done(rid, t0, fk, used))
        outstanding.append((rid, ticket))

    for rid, ticket in outstanding:
        try:
            ticket.result(timeout=args.poll_budget_s + 30.0)
        except TimeoutError:
            record(rid, "error", error="result timeout")
    ledger_fh.close()

    final = load_ledger(args.ledger)
    missing = [rid for rid, _ in rows
               if final.get(rid) not in DONE_STATUSES]
    counts = {}
    for rid, _ in rows:
        counts[final.get(rid, "missing")] = \
            counts.get(final.get(rid, "missing"), 0) + 1
    print(f"campaign: {json.dumps(counts, sort_keys=True)}")
    if missing:
        print(f"{len(missing)} sequences NOT terminal-done "
              f"(re-run to retry): {missing[:8]}"
              f"{'...' if len(missing) > 8 else ''}")
        return 1
    print("campaign complete: every sequence terminal")
    return 0


if __name__ == "__main__":
    sys.exit(main())
