"""Offline cache warming: fold the traffic head before traffic does.

The loadtest's Zipf-skewed duplicate model (rank r re-requested with
weight 1/(r+1)) is the shape of real serving traffic; its head is
known ahead of time from yesterday's logs. This tool reads a
sequence-frequency file, folds the head set through
`predict.fold_and_write(cache=...)` — the same content-addressed
memoization the servers read — and reports what the warm bought:
bytes written per tier and the PREDICTED hit ratio (the frequency mass
of the warmed head over the whole profile: if tomorrow's traffic
matches the profile, that fraction of requests starts as a cache hit).

Frequency file: JSONL, one record per unique sequence —
    {"seq": "MKV...", "count": 123}            # AA string, or
    {"seq": [12, 4, ...], "count": 123}        # token list
    {"seq": ..., "count": ..., "msa": [[...]]} # optional MSA tokens
`--emit-synthetic F` writes a synthetic Zipf-skewed profile (the
loadtest's traffic model) to F and exits — the self-contained demo /
test path.

`--from-serve-log DIR` (ISSUE 16 satellite) derives the profile from
SERVED traffic instead of an offline file: it walks DIR for the
`keys.jsonl` key-frequency records the serving scheduler writes when
armed with `Scheduler(key_log=...)` / `ProcFleet(key_log=True)`,
merges them across replicas (summing counts by content digest), and
warms the head of what the fleet actually folded. The report then
carries BOTH ratios: `predicted_hit_ratio` (frequency mass of the
warmed head — what the warm buys if tomorrow looks like the log) and
`realized_hit_ratio` (the mass that was ALREADY resident when probed
— what previous warming/serving had realized); the delta is this
run's purchase.

`--fleet ID=DIR,...` warms FLEET-SCOPE (ISSUE 10 satellite): every key
routes through the serving fleet's own `ConsistentHashRouter` and is
folded into its OWNER replica's cache dir, so each warm entry lands
exactly where forwarded requests and peer-cache fetches will look for
it. Run once against every replica's mounted cache dir instead of once
per replica; the report carries `warmed_per_replica`.

Key-regime note (predict.fold_and_write docstring has the contract):
entries are keyed with msa_depth=None semantics, so they cross-hit a
serving scheduler configured with `msa_depth=None`, any other
`fold_and_write(cache=)` caller, and — through the fleet peer tier —
every replica mounting this store. Warming SKIPS already-cached heads
(the fold is elided when every element hits), so re-running after a
partial warm only pays for what's missing.

Runs on CPU by default; one JSON report line on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--freq", default="",
                    help="sequence-frequency JSONL (seq + count per line)")
    ap.add_argument("--from-serve-log", default="",
                    help="derive the profile from served traffic: walk "
                         "this directory for the scheduler's keys.jsonl "
                         "key-frequency records (ProcFleet run_dir "
                         "layout), merge counts across replicas by "
                         "content digest, and warm that head. "
                         "Alternative to --freq.")
    ap.add_argument("--emit-synthetic", default="",
                    help="write a synthetic Zipf profile here and exit")
    ap.add_argument("--num", type=int, default=32,
                    help="unique sequences for --emit-synthetic")
    ap.add_argument("--lengths", default="24,48",
                    help="lengths cycled by --emit-synthetic")
    ap.add_argument("--total-requests", type=int, default=1024,
                    help="frequency mass distributed Zipf-ishly by "
                         "--emit-synthetic")
    ap.add_argument("--top", type=int, default=0,
                    help="warm only the K most frequent (0 = all, "
                         "subject to --budget-bytes)")
    ap.add_argument("--budget-bytes", type=int, default=0,
                    help="stop once this many cache bytes are resident "
                         "(0 = unbounded)")
    ap.add_argument("--cache-dir", default="",
                    help="on-disk cache tier to warm (strongly "
                         "recommended: a memory-only warm dies with "
                         "this process)")
    ap.add_argument("--fleet", default="",
                    help="FLEET-SCOPE warming: 'ID=DIR,ID=DIR,...' "
                         "replica cache directories. Each key is "
                         "routed through the same ConsistentHashRouter "
                         "the serving fleet uses and warmed into its "
                         "OWNER replica's cache dir — so warm entries "
                         "land exactly where forwarded/peer traffic "
                         "will look for them, instead of all in one "
                         "replica's tier. Overrides --cache-dir.")
    ap.add_argument("--model-tag", default="",
                    help="model identity for the cache keys; MUST match "
                         "the serving fleet's tag or the warm is "
                         "unreachable")
    ap.add_argument("--msa-depth", type=int, default=3,
                    help="MSA depth for synthetic profiles / model init")
    ap.add_argument("--num-recycles", type=int, default=0)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--depth", type=int, default=1)
    ap.add_argument("--out-dir", default="/tmp/cache_warm_pdbs",
                    help="where fold_and_write drops the PDB traces")
    ap.add_argument("--platform", default="cpu",
                    choices=("cpu", "ambient"))
    return ap.parse_args(argv)


def emit_synthetic(args) -> int:
    """Zipf-skewed profile from synthetic sequences: rank r gets
    frequency mass proportional to 1/(r+1) — the loadtest's duplicate
    model, reusable as a warming demo and test fixture."""
    import jax
    import numpy as np

    from alphafold2_tpu.data.synthetic import synthetic_requests

    lengths = tuple(int(x) for x in args.lengths.split(",") if x)
    pool = synthetic_requests(jax.random.PRNGKey(1), num=args.num,
                              lengths=lengths, msa_depth=args.msa_depth)
    weights = 1.0 / (np.arange(len(pool)) + 1.0)
    weights /= weights.sum()
    with open(args.emit_synthetic, "w") as fh:
        for rank, req in enumerate(pool):
            rec = {"seq": np.asarray(req.seq).tolist(),
                   "count": max(1, int(round(
                       args.total_requests * weights[rank])))}
            if req.msa is not None:
                rec["msa"] = np.asarray(req.msa).tolist()
            fh.write(json.dumps(rec) + "\n")
    print(json.dumps({"metric": "cache_warm_synthetic",
                      "path": args.emit_synthetic,
                      "unique": len(pool)}))
    return 0


def load_profile(path: str):
    """[(count, seq tokens (n,), msa tokens (m, n) or None)], any order."""
    import numpy as np

    from alphafold2_tpu.data.featurize import tokenize

    entries = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            seq = rec["seq"]
            seq = (tokenize(seq) if isinstance(seq, str)
                   else np.asarray(seq, np.int32))
            msa = rec.get("msa")
            msa = None if msa is None else np.asarray(msa, np.int32)
            count = int(rec.get("count", 1))
            if count < 1 or seq.ndim != 1:
                raise ValueError(f"{path}:{lineno}: bad profile record")
            entries.append((count, seq, msa))
    return entries


def load_serve_log_profile(log_dir: str):
    """Profile entries from the fleet's own key-frequency telemetry.

    Walks `log_dir` for `keys.jsonl` files (one per replica in the
    ProcFleet run_dir layout), merges records across replicas by
    content digest via the controller's merge, and returns
    ([(count, seq, msa)], n_files) hottest-first.
    """
    import numpy as np

    from alphafold2_tpu.fleet.controlplane import merge_key_profiles

    paths = []
    for root, _, files in os.walk(log_dir):
        paths.extend(os.path.join(root, f) for f in files
                     if f == "keys.jsonl" or f.endswith(".keys.jsonl"))
    merged = merge_key_profiles(sorted(paths))
    entries = []
    for rec in merged:
        seq = np.asarray(rec["seq"], np.int32)
        msa = rec.get("msa")
        msa = None if msa is None else np.asarray(msa, np.int32)
        if seq.ndim != 1 or rec["count"] < 1:
            continue
        entries.append((int(rec["count"]), seq, msa))
    return entries, len(paths)


def main(argv=None) -> int:
    args = parse_args(argv)
    import __graft_entry__
    if args.platform == "cpu":
        __graft_entry__.force_cpu_fallback()
    if args.emit_synthetic:
        return emit_synthetic(args)
    if not args.freq and not args.from_serve_log:
        print("cache_warm: need --freq, --from-serve-log, or "
              "--emit-synthetic", file=sys.stderr)
        return 2

    import jax
    import jax.numpy as jnp

    from alphafold2_tpu import Alphafold2, predict
    from alphafold2_tpu.cache import FoldCache

    serve_log_files = 0
    if args.from_serve_log:
        entries, serve_log_files = load_serve_log_profile(
            args.from_serve_log)
        if not entries:
            print(f"cache_warm: no keys.jsonl records under "
                  f"{args.from_serve_log}", file=sys.stderr)
            return 2
    else:
        entries = load_profile(args.freq)
        if not entries:
            print(f"cache_warm: empty profile {args.freq}",
                  file=sys.stderr)
            return 2
    entries.sort(key=lambda e: -e[0])
    total_freq = sum(c for c, _, _ in entries)

    model = Alphafold2(dim=args.dim, depth=args.depth, heads=2,
                      dim_head=16, predict_coords=True,
                      structure_module_depth=1)
    n0 = int(entries[0][1].shape[0])
    init_kwargs = dict(mask=jnp.ones((1, n0), bool))
    if args.msa_depth > 0:
        init_kwargs["msa"] = jnp.zeros((1, args.msa_depth, n0), jnp.int32)
        init_kwargs["msa_mask"] = jnp.ones((1, args.msa_depth, n0), bool)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, n0), jnp.int32), **init_kwargs)

    # --fleet: one cache per replica dir + the serving fleet's own
    # consistent-hash routing, so each key is warmed into its OWNER
    # replica's tier (ROADMAP fleet-scope warming: a warm that piles
    # everything into one replica's dir only helps that replica's
    # local traffic — forwarded and peer-fetched traffic looks on the
    # ring owner)
    router = None
    caches = {}
    if args.fleet:
        from alphafold2_tpu.cache import fold_key
        from alphafold2_tpu.fleet.registry import ReplicaRegistry
        from alphafold2_tpu.fleet.router import ConsistentHashRouter

        registry = ReplicaRegistry(model_tag=args.model_tag)
        for kv in args.fleet.split(","):
            try:
                rid, cdir = kv.split("=", 1)
            except ValueError:
                print(f"cache_warm: bad --fleet entry {kv!r} "
                      f"(want ID=DIR)", file=sys.stderr)
                return 2
            registry.register(rid.strip())
            caches[rid.strip()] = FoldCache(disk_dir=cdir.strip() or None)
        router = ConsistentHashRouter(registry,
                                      next(iter(caches)))
        cache = None
    else:
        cache = FoldCache(disk_dir=args.cache_dir or None)
    os.makedirs(args.out_dir, exist_ok=True)

    def _resident_bytes():
        if cache is not None:
            return cache.bytes_resident
        return sum(c.bytes_resident for c in caches.values())

    t0 = time.monotonic()
    warmed, warmed_freq, skipped, skipped_freq = 0, 0, 0, 0
    per_replica = {rid: 0 for rid in caches}
    head = entries[:args.top] if args.top > 0 else entries
    for rank, (count, seq, msa) in enumerate(head):
        if args.budget_bytes and _resident_bytes() >= args.budget_bytes:
            break
        target = cache
        if router is not None:
            # the SAME key fold_and_write will compute below (no mask,
            # trivial msa_mask, no extras): its ring owner's cache is
            # where serving-time peer fetches and forwards will look
            key = fold_key(seq, msa, num_recycles=args.num_recycles,
                           model_tag=args.model_tag)
            owner = router.owner_for(key) or next(iter(caches))
            target = caches[owner]
            per_replica[owner] += 1
        hits_before = target.stats.hits
        kwargs = {} if msa is None else {"msa": msa[None]}
        predict.fold_and_write(
            model, params, seq[None],
            os.path.join(args.out_dir, f"warm_{rank}.pdb"),
            cache=target, model_tag=args.model_tag,
            num_recycles=args.num_recycles, **kwargs)
        if target.stats.hits > hits_before:
            skipped += 1               # already warm: fold was elided
            skipped_freq += count
        else:
            warmed += 1
        warmed_freq += count
    elapsed = time.monotonic() - t0

    disk_bytes = 0
    disk_dirs = ([args.cache_dir] if args.cache_dir and cache is not None
                 else [c.disk_dir for c in caches.values() if c.disk_dir])
    for d in disk_dirs:
        for root, _, files in os.walk(d):
            disk_bytes += sum(
                os.path.getsize(os.path.join(root, f))
                for f in files if f.endswith(".npz"))
    report = {
        "metric": "cache_warm",
        "profile": args.freq or args.from_serve_log,
        "profile_source": ("serve_log" if args.from_serve_log
                           else "freq_file"),
        "serve_log_files": serve_log_files,
        "unique_in_profile": len(entries),
        "warmed": warmed,
        "skipped_already_cached": skipped,
        "bytes_resident": _resident_bytes(),
        "disk_bytes": disk_bytes,
        "cache_dir": args.cache_dir,
        "fleet": (None if router is None else {
            "replicas": list(caches),
            "warmed_per_replica": per_replica}),
        "model_tag": args.model_tag,
        # frequency mass covered by the (now-warm) head: the hit ratio
        # this warm predicts for traffic matching the profile
        "predicted_hit_ratio": round(
            warmed_freq / total_freq if total_freq else 0.0, 4),
        # mass that was ALREADY resident when probed — the hit ratio
        # previous warming/serving had realized; predicted - realized
        # is what this run bought
        "realized_hit_ratio": round(
            skipped_freq / total_freq if total_freq else 0.0, 4),
        "warm_wall_s": round(elapsed, 3),
    }
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
