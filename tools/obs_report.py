"""Render a per-stage latency waterfall + slowest traces from trace JSONL.

Input is the file `alphafold2_tpu.obs.Tracer(jsonl_path=...)` appends to
(one `"schema": 1` record per completed request trace; see README
"Observability"). The report answers the two questions stage-level
timing exists for:

- WHERE does a typical request spend its time? -> the waterfall:
  p50/p90/p99 per stage (submit / queue / parked / batch_form /
  compile / fold / writeback), with proportional bars;
- WHICH requests were pathological? -> top-K slowest traces with their
  span breakdown, terminal status, and leader links.

`--check` turns the report into a tripwire (tools/serve_smoke.sh's
observability phase): exit 1 when any record is missing its schema
version, any trace is incomplete (no terminal status), any span is an
orphan (negative timing or escaping its trace's window), any span name
is absent from STAGE_ORDER (the drift tripwire — a new serving stage
must be appended to the canonical order), or any accelerator-served
request (`source == "fold"`, status ok) lacks a non-zero `fold` span.
`--prom FILE` additionally validates that a Prometheus text exposition
(obs.export.prometheus_text / loadtest --prom-path) parses.

  python tools/obs_report.py /tmp/serve_traces.jsonl
  python tools/obs_report.py /tmp/serve_traces.jsonl --top 10
  python tools/obs_report.py traces.jsonl --check --prom metrics.prom
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from alphafold2_tpu.obs.export import SCHEMA_VERSION  # noqa: E402
from alphafold2_tpu.utils.profiling import percentile  # noqa: E402

# canonical stage order for the waterfall; unknown span names append.
# forward (fleet routing hop) and peer_fetch (peer cache tier) arrived
# with ISSUE 4; retry (backoff wait before a re-executed batch) and
# watchdog (the killed window of a hung execution) with ISSUE 5;
# rpc (one front-door HTTP hop, client-measured: submit POST or the
# whole forwarded exchange) and drain (time a request rode a graceful
# drain, from drain start to its terminal state) with ISSUE 6;
# shard (mesh serving: params/input placement onto the batch's device
# slice) with ISSUE 7 — fold spans additionally carry a `mesh` attr
# ("1x1", "2x4") the per-mesh latency section below groups by;
# recycle (one single-recycle step execution of the scheduler-owned
# recycle loop, tagged with its iteration index) with ISSUE 9 — the
# init pass stays a `fold` span so the accelerator-time rule below
# holds unchanged for step-scheduled requests;
# featurize (the CPU feature-pipeline stage of a RAW submission:
# feature-cache lookup, in-flight coalesce wait, pool queue + the
# tokenize/MSA-prep work itself) with ISSUE 10 — it precedes submit in
# the pipeline, so it leads the waterfall;
# admit (the continuous batcher's mid-recycle row admission: the
# row-masked init executable that restarts a freed row with a newly
# admitted request while survivor rows keep stepping) with ISSUE 11 —
# it is an admitted request's first accelerator pass, so the
# accelerator-time rule below accepts it alongside fold/compile, and
# its sibling recycle spans carry rows_live/rows_total attrs the
# occupancy line reads back;
# resume (carry-checkpoint recovery: re-uploading the last checkpoint
# after a transient mid-loop failure so survivors continue at their
# checkpointed ages, tagged with the resume-point recycle and the
# recycles lost) with ISSUE 14 — it sits between the watchdog window
# it recovers from and writeback;
# peer_serve (the serving side of a peer-cache fetch: the owner's
# continued trace record, stitched under the requester's peer_fetch
# hop by tools/obs_fleet.py) with ISSUE 15 — the rpc span now also
# covers the WHOLE forwarded exchange (submit POST through terminal
# pickup) and carries outcome/span_id attrs the fleet stitcher reads.
# --check's orphan-span rules apply to all of them unchanged, which is
# how the chaos smokes prove recovery cost is fully accounted.
#
# This tuple is LOAD-BEARING: check_stage_order() below hard-fails
# --check on any span name absent from it, so adding a span to the
# serving stack without appending it here trips the very next smoke
# phase instead of silently rendering at the bottom of the waterfall.
STAGE_ORDER = ("reconcile", "featurize", "submit", "forward", "rpc",
               "queue", "parked", "retry", "drain", "batch_form",
               "shard", "compile", "fold", "recycle", "admit",
               "watchdog", "resume", "writeback", "peer_fetch",
               "peer_serve", "cache_lookup", "write", "preempt",
               "adopt")

# span/trace boundary slack: start_s, dur_s, and duration_s are each
# INDEPENDENTLY rounded to 1e-6 when emitted, so a span auto-closed at
# finish time can legitimately show start+dur up to 1.5e-6 past the
# trace duration (three half-ulp roundings) before float noise — 1e-6
# exactly was a latent off-by-one-rounding flake
_EPS = 2e-6


def load_traces(path: str) -> Tuple[List[dict], List[str]]:
    """Parse a trace JSONL file. Returns (records, parse_errors)."""
    records, errors = [], []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as exc:
                errors.append(f"line {lineno}: unparseable JSON ({exc})")
    return records, errors


def check_traces(records: List[dict]) -> List[str]:
    """Structural tripwire. Returns a list of violations (empty = ok)."""
    problems = []
    for i, rec in enumerate(records):
        where = f"record {i} ({rec.get('trace_id', '?')})"
        if rec.get("schema") != SCHEMA_VERSION:
            problems.append(f"{where}: missing/unknown schema version "
                            f"{rec.get('schema')!r}")
            continue
        status = rec.get("status")
        if not status:
            problems.append(f"{where}: incomplete trace (no terminal "
                            "status)")
            continue
        duration = rec.get("duration_s", 0.0)
        if duration < 0:
            problems.append(f"{where}: negative duration {duration}")
        for span in rec.get("spans", ()):
            name = span.get("name", "?")
            t0, dur = span.get("start_s"), span.get("dur_s")
            if t0 is None or dur is None or t0 < -_EPS or dur < 0:
                problems.append(f"{where}: orphan span {name!r} "
                                f"(start={t0}, dur={dur})")
            elif t0 + dur > duration + _EPS:
                problems.append(f"{where}: span {name!r} escapes its "
                                f"trace window ({t0}+{dur} > {duration})")
        if status == "ok" and rec.get("source") == "fold":
            # admit counts as accelerator time: a row-admitted request
            # (continuous batching, ISSUE 11) gets its first pass via
            # the row-masked init executable under an `admit` span, not
            # the batch-level `fold` span its founders carry
            fold_time = sum(s.get("dur_s", 0.0)
                            for s in rec.get("spans", ())
                            if s.get("name") in ("fold", "compile",
                                                 "admit"))
            if fold_time <= 0:
                problems.append(f"{where}: served from the accelerator "
                                "but has no non-zero fold span")
    return problems


def check_stage_order(records: List[dict]) -> List[str]:
    """STAGE_ORDER drift tripwire (ISSUE 15): a span name present in
    the traces but absent from the canonical order is a HARD failure
    under --check. Every recent serving feature added a span and
    hand-appended it to STAGE_ORDER; this makes forgetting impossible
    — the new span's first smoke run fails here with the exact name to
    append instead of silently sorting to the waterfall's tail."""
    known = set(STAGE_ORDER)
    unknown = sorted({str(span.get("name", "?"))
                      for rec in records
                      for span in rec.get("spans", ())} - known)
    return [f"span name {name!r} is not in STAGE_ORDER — a new serving "
            f"stage must be appended to tools/obs_report.py's "
            f"canonical order (and documented there)"
            for name in unknown]


def stage_stats(records: List[dict]) -> dict:
    """{stage: {count, p50_s, p90_s, p99_s, total_s}} over all spans."""
    by_stage = {}
    for rec in records:
        for span in rec.get("spans", ()):
            by_stage.setdefault(span.get("name", "?"), []).append(
                float(span.get("dur_s", 0.0)))
    out = {}
    names = [s for s in STAGE_ORDER if s in by_stage]
    names += sorted(set(by_stage) - set(STAGE_ORDER))
    for name in names:
        durs = by_stage[name]
        out[name] = {"count": len(durs),
                     "p50_s": percentile(durs, 50),
                     "p90_s": percentile(durs, 90),
                     "p99_s": percentile(durs, 99),
                     "total_s": sum(durs)}
    return out


def mesh_fold_stats(records: List[dict]) -> dict:
    """Per-mesh-shape fold latency: {mesh_label: {count, p50_s, p99_s}}.
    Fold spans without a `mesh` attr (the classic single-chip executor)
    group under "1x1", so a mixed mesh-on/off trace file still separates
    1-chip from 8-chip folds. Empty when no fold spans exist."""
    by_mesh = {}
    for rec in records:
        for span in rec.get("spans", ()):
            if span.get("name") != "fold":
                continue
            mesh = (span.get("attrs") or {}).get("mesh", "1x1")
            by_mesh.setdefault(str(mesh), []).append(
                float(span.get("dur_s", 0.0)))
    return {mesh: {"count": len(durs),
                   "p50_s": percentile(durs, 50),
                   "p99_s": percentile(durs, 99)}
            for mesh, durs in sorted(by_mesh.items())}


def kernel_fold_stats(records: List[dict]) -> dict:
    """Per-attention-kernel accelerator-span latency (ISSUE 12):
    {kernel_label: {count, p50_s, p99_s}} over fold/recycle/admit spans
    (the three accelerator stages a kernel choice governs). Spans
    without a `kernel` attr (every pre-kernel-policy trace, and every
    dense fold under a policy-less scheduler) group under "dense" —
    mirrors mesh_fold_stats' "1x1" convention, so a mixed trace file
    still separates dense from block-sparse executions. Empty when no
    accelerator spans exist."""
    by_kernel = {}
    for rec in records:
        for span in rec.get("spans", ()):
            if span.get("name") not in ("fold", "recycle", "admit"):
                continue
            kern = (span.get("attrs") or {}).get("kernel", "dense")
            by_kernel.setdefault(str(kern), []).append(
                float(span.get("dur_s", 0.0)))
    return {kern: {"count": len(durs),
                   "p50_s": percentile(durs, 50),
                   "p99_s": percentile(durs, 99)}
            for kern, durs in sorted(by_kernel.items())}


def render_kernel_folds(stats: dict) -> str:
    lines = [f"{'kernel':>20}  {'spans':>6}  {'p50':>9}  {'p99':>9}"]
    for kern, s in stats.items():
        lines.append(f"{kern:>20}  {s['count']:>6}  {s['p50_s']:>9.4f}  "
                     f"{s['p99_s']:>9.4f}")
    return "\n".join(lines)


def rows_occupied_stats(records: List[dict]) -> Optional[dict]:
    """Row-occupancy read back from recycle spans' rows_live/rows_total
    attrs (the continuous batcher tags every step, ISSUE 11): the
    span-weighted mean occupancy plus the span count. None when no
    span carries the attrs (non-continuous runs). Span-weighted on
    purpose — each live element of a step carries the span, so busy
    steps weigh more; the scheduler-side
    serve_stats()["recycle"]["rows_occupied_fraction"] is the
    step-weighted truth the smoke gates on."""
    fracs = []
    for rec in records:
        for span in rec.get("spans", ()):
            if span.get("name") != "recycle":
                continue
            attrs = span.get("attrs") or {}
            live, total = attrs.get("rows_live"), attrs.get("rows_total")
            if live is not None and total:
                fracs.append(float(live) / float(total))
    if not fracs:
        return None
    return {"spans": len(fracs),
            "mean_fraction": sum(fracs) / len(fracs)}


def render_mesh_folds(stats: dict) -> str:
    lines = [f"{'mesh':>12}  {'folds':>6}  {'p50':>9}  {'p99':>9}"]
    for mesh, s in stats.items():
        lines.append(f"{mesh:>12}  {s['count']:>6}  {s['p50_s']:>9.4f}  "
                     f"{s['p99_s']:>9.4f}")
    return "\n".join(lines)


def render_waterfall(stats: dict, width: int = 40) -> str:
    """ASCII waterfall: one bar per stage, scaled to the largest p90."""
    if not stats:
        return "(no spans)"
    scale = max(s["p90_s"] for s in stats.values()) or 1.0
    lines = [f"{'stage':>12}  {'count':>6}  {'p50':>9}  {'p90':>9}  "
             f"{'p99':>9}  waterfall(p90)"]
    for name, s in stats.items():
        bar = "#" * max(1, int(round(s["p90_s"] / scale * width))) \
            if s["p90_s"] > 0 else ""
        lines.append(f"{name:>12}  {s['count']:>6}  {s['p50_s']:>9.4f}  "
                     f"{s['p90_s']:>9.4f}  {s['p99_s']:>9.4f}  {bar}")
    return "\n".join(lines)


def render_slowest(records: List[dict], top: int = 5) -> str:
    ranked = sorted(records, key=lambda r: -float(r.get("duration_s", 0)))
    lines = []
    for rec in ranked[:top]:
        spans = " ".join(
            f"{s.get('name')}={s.get('dur_s', 0.0):.4f}s"
            for s in rec.get("spans", ()))
        link = (f" leader={rec['leader_trace_id']}"
                if rec.get("leader_trace_id") else "")
        err = f" error={rec['error']!r}" if rec.get("error") else ""
        lines.append(
            f"{rec.get('duration_s', 0.0):9.4f}s  "
            f"{rec.get('trace_id', '?'):>6}  {rec.get('request_id', '?')} "
            f"[{rec.get('status')}/{rec.get('source')}]{link}  "
            f"{spans}{err}")
    return "\n".join(lines) if lines else "(no traces)"


# one sample line of Prometheus text exposition format 0.0.4
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                 # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"
    r" [-+]?(?:[0-9.eE+-]+|Inf|NaN)$")


def check_prometheus_text(text: str) -> List[str]:
    """Validate exposition text; returns violations (empty = parses)."""
    problems = []
    samples = 0
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if not re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ",
                            line):
                problems.append(f"prom line {lineno}: malformed comment "
                                f"{line!r}")
            continue
        if not _PROM_SAMPLE.match(line):
            problems.append(f"prom line {lineno}: unparseable sample "
                            f"{line!r}")
        else:
            samples += 1
    if samples == 0:
        problems.append("prom exposition has no samples")
    return problems


def summarize(records: List[dict]) -> dict:
    by_status, by_source = {}, {}
    for rec in records:
        by_status[rec.get("status")] = by_status.get(rec.get("status"),
                                                     0) + 1
        by_source[rec.get("source")] = by_source.get(rec.get("source"),
                                                     0) + 1
    durs = [float(r.get("duration_s", 0.0)) for r in records]
    return {"traces": len(records), "by_status": by_status,
            "by_source": by_source,
            "p50_s": percentile(durs, 50), "p99_s": percentile(durs, 99),
            "linked_followers": sum(1 for r in records
                                    if r.get("leader_trace_id"))}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace_jsonl", help="Tracer JSONL file")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest traces to list")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on schema/orphan-span/empty-fold "
                         "violations")
    ap.add_argument("--prom", default="",
                    help="also validate this Prometheus exposition file")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON summary line instead of the "
                         "human report")
    args = ap.parse_args(argv)

    records, parse_errors = load_traces(args.trace_jsonl)
    problems = list(parse_errors)
    if not records:
        problems.append(f"no trace records in {args.trace_jsonl}")
    problems += check_traces(records)
    problems += check_stage_order(records)
    if args.prom:
        try:
            with open(args.prom) as fh:
                problems += check_prometheus_text(fh.read())
        except OSError as exc:
            problems.append(f"prom file unreadable: {exc}")

    if args.json:
        out = summarize(records)
        out["stages"] = stage_stats(records)
        out["mesh_folds"] = mesh_fold_stats(records)
        out["kernel_folds"] = kernel_fold_stats(records)
        out["rows_occupied"] = rows_occupied_stats(records)
        out["problems"] = problems[:20]
        print(json.dumps(out))
    else:
        s = summarize(records)
        print(f"== {args.trace_jsonl}: {s['traces']} traces "
              f"(status {s['by_status']}, source {s['by_source']}, "
              f"{s['linked_followers']} linked followers) ==")
        print(render_waterfall(stage_stats(records)))
        mesh = mesh_fold_stats(records)
        if len(mesh) > 1 or any(m != "1x1" for m in mesh):
            print("\n-- fold latency by mesh shape --")
            print(render_mesh_folds(mesh))
        kern = kernel_fold_stats(records)
        if len(kern) > 1 or any(k != "dense" for k in kern):
            print("\n-- accelerator latency by attention kernel --")
            print(render_kernel_folds(kern))
        occ = rows_occupied_stats(records)
        if occ is not None:
            print(f"\nrows occupied (continuous batching): "
                  f"{occ['mean_fraction']:.3f} span-weighted mean over "
                  f"{occ['spans']} recycle spans")
        print(f"\n-- top {args.top} slowest --")
        print(render_slowest(records, args.top))
        if problems:
            print(f"\n-- {len(problems)} problems --")
            for p in problems[:20]:
                print(f"  {p}")

    if args.check and problems:
        print(f"OBS CHECK FAIL: {len(problems)} violations "
              f"({problems[0]})", file=sys.stderr)
        return 1
    if args.check:
        print(f"OBS CHECK OK: {len(records)} complete traces, "
              "0 orphan spans", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
