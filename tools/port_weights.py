"""Whole-model torch -> flax weight porting for reference checkpoints.

Promotes the per-module translation rules proven by tests/test_parity.py
to the full `Alphafold2` tree (VERDICT round-1 item #5), so a checkpoint
trained with the reference implementation
(/root/reference/alphafold2_pytorch/alphafold2.py:469-905) runs in this
framework. The Evoformer stacks are scanned here (params carry a leading
depth axis), so per-layer torch trees are stacked along axis 0.

Usage (API):

    from tools.port_weights import port_alphafold2
    params = flax_model.init(...)                 # template tree
    params, unported = port_alphafold2(torch_model, params)

Usage (CLI): convert a saved reference state into an orbax/msgpack blob:

    python tools/port_weights.py --torch-ckpt ref.pt \
        --model-kwargs '{"dim": 256, "depth": 6}' --out params.msgpack

Known limits (each documented where it bites):
- the IPA structure module is NOT ported: the reference outsources it to
  the external `invariant-point-attention` package (alphafold2.py:608),
  which is not installed here (tools/_reference_stubs.py substitutes a
  dummy), so there is no ground truth to translate; our from-scratch IPA
  (model/structure.py) keeps its init. The surrounding projections
  (msa_to_single_repr_dim, trunk_to_pairwise_repr_dim,
  to_quaternion_update, to_points, lddt_linear) ARE ported.
- build the flax model with `outer_mean_reference_scale=True` when
  running ported reference checkpoints: the reference synthesizes an
  all-ones msa_mask (alphafold2.py:703) and its masked OuterMean
  double-divides (alphafold2.py:347), so that flag is required for exact
  output parity (TestWholeModelParity exercises it). Without the flag the
  model uses the corrected masked mean and pair activations differ by a
  factor of the MSA row count per OuterMean.
- framework-only leaves (seq/msa embed projection banks used by
  embeds.py) have no reference counterpart and keep their init.
"""

from __future__ import annotations

import json
from typing import Tuple

import numpy as np


# --------------------------------------------------------------------------
# leaf-level translators (the rules from tests/test_parity.py:44-63)
# --------------------------------------------------------------------------


def t2n(t) -> np.ndarray:
    return np.asarray(t.detach().cpu().numpy())


def linear(tl) -> dict:
    """torch nn.Linear -> flax Dense params (weight is transposed)."""
    out = {"kernel": t2n(tl.weight).T}
    if tl.bias is not None:
        out["bias"] = t2n(tl.bias)
    return out


def embedding(te) -> dict:
    return {"embedding": t2n(te.weight)}


def layernorm(tln) -> dict:
    """torch nn.LayerNorm -> our LayerNorm wrapper (model/primitives.py
    LayerNorm nests a flax LayerNorm under 'LayerNorm_0')."""
    return {"LayerNorm_0": {"scale": t2n(tln.weight), "bias": t2n(tln.bias)}}


# --------------------------------------------------------------------------
# module-level translators (reference module attrs -> our param subtrees)
# --------------------------------------------------------------------------


def attention(ta) -> dict:
    """reference Attention (alphafold2.py:98-123)."""
    return {
        "to_q": linear(ta.to_q),
        "to_kv": linear(ta.to_kv),
        "to_out": linear(ta.to_out),
        "gating": linear(ta.gating),
    }


def axial_attention(ta) -> dict:
    """reference AxialAttention (alphafold2.py:192-217)."""
    out = {
        "LayerNorm_0": layernorm(ta.norm),
        "attn": attention(ta.attn),
    }
    # accept_edges=True -> nn.Sequential(Linear, Rearrange); otherwise an
    # Always(None) placeholder (alphafold2.py:214-217)
    ebias = getattr(ta, "edges_to_attn_bias", None)
    try:
        first = ebias[0]
    except (TypeError, IndexError, KeyError):
        first = None
    if first is not None and hasattr(first, "weight"):
        out["edges_to_attn_bias"] = linear(first)
    return out


def triangle_multiplicative(tm) -> dict:
    """reference TriangleMultiplicativeModule (alphafold2.py:257-317)."""
    return {
        "LayerNorm_0": layernorm(tm.norm),
        "left_proj": linear(tm.left_proj),
        "right_proj": linear(tm.right_proj),
        "left_gate": linear(tm.left_gate),
        "right_gate": linear(tm.right_gate),
        "out_gate": linear(tm.out_gate),
        "LayerNorm_1": layernorm(tm.to_out_norm),
        "to_out": linear(tm.to_out),
    }


def outer_mean(to) -> dict:
    """reference OuterMean (alphafold2.py:321-351)."""
    return {
        "LayerNorm_0": layernorm(to.norm),
        "left_proj": linear(to.left_proj),
        "right_proj": linear(to.right_proj),
        "proj_out": linear(to.proj_out),
    }


def feed_forward(tf) -> dict:
    """reference FeedForward (alphafold2.py:74-94): net[0]/net[3] are the
    two Linears around GEGLU/Dropout."""
    return {
        "LayerNorm_0": layernorm(tf.norm),
        "Dense_0": linear(tf.net[0]),
        "Dense_1": linear(tf.net[3]),
    }


def pairwise_block(tb, include_outer_mean: bool = True) -> dict:
    """reference PairwiseAttentionBlock (alphafold2.py:353-385).

    `include_outer_mean=False` for the template embedder: the reference
    calls it without msa_repr (alphafold2.py:755), so our lazily-built
    tree has no outer_mean there while torch carries unused weights.
    """
    out = {
        "triangle_attention_outgoing":
            axial_attention(tb.triangle_attention_outgoing),
        "triangle_attention_ingoing":
            axial_attention(tb.triangle_attention_ingoing),
        "triangle_multiply_outgoing":
            triangle_multiplicative(tb.triangle_multiply_outgoing),
        "triangle_multiply_ingoing":
            triangle_multiplicative(tb.triangle_multiply_ingoing),
    }
    if include_outer_mean:
        out["outer_mean"] = outer_mean(tb.outer_mean)
    return out


def msa_block(tb) -> dict:
    """reference MsaAttentionBlock (alphafold2.py:387-408)."""
    return {
        "row_attn": axial_attention(tb.row_attn),
        "col_attn": axial_attention(tb.col_attn),
    }


def evoformer_block(teb) -> dict:
    """reference EvoformerBlock (alphafold2.py:412-446): layer ModuleList
    order is [pairwise, pair-ff, msa-attn, msa-ff]."""
    pair, ff, msa_attn, msa_ff = teb.layer
    return {
        "attn": pairwise_block(pair),
        "ff": feed_forward(ff),
        "msa_attn": msa_block(msa_attn),
        "msa_ff": feed_forward(msa_ff),
    }


def _stack_trees(trees):
    """Stack a list of identical-structure trees along a new leading axis
    (the scanned-depth axis of our Evoformer params)."""
    if isinstance(trees[0], dict):
        return {k: _stack_trees([t[k] for t in trees]) for k in trees[0]}
    return np.stack(trees, axis=0)


def evoformer(tev, scanned: bool) -> dict:
    """reference Evoformer (alphafold2.py:448-467) -> our scan layout
    ('layers/block' with a leading depth axis, model/evoformer.py) or the
    unrolled 'layers_i' layout for depth-1 / use_scan=False models."""
    blocks = [evoformer_block(b) for b in tev.layers]
    if scanned and len(blocks) > 1:
        return {"layers": {"block": _stack_trees(blocks)}}
    return {f"layers_{i}": b for i, b in enumerate(blocks)}


# --------------------------------------------------------------------------
# whole model
# --------------------------------------------------------------------------


def port_alphafold2(tmodel, template_params) -> Tuple[dict, list]:
    """Port a reference `Alphafold2` torch module into a flax params tree.

    `template_params` must come from our `Alphafold2.init(...)` at the
    matching configuration; ported subtrees replace the template's leaves
    (with shape checks), everything else keeps its init. Returns
    (params, unported_top_level_keys).
    """
    ported = {
        "token_emb": embedding(tmodel.token_emb),
        "to_pairwise_repr": linear(tmodel.to_pairwise_repr),
        "pos_emb": embedding(tmodel.pos_emb),
        "embedd_project": linear(tmodel.embedd_project),
        "extra_msa_evoformer": evoformer(tmodel.extra_msa_evoformer,
                                         scanned=True),
        "net": evoformer(tmodel.net, scanned=True),
        "mlm": {"to_logits": linear(tmodel.mlm.to_logits)},
        "template_pairwise_embedder":
            pairwise_block(tmodel.template_pairwise_embedder,
                           include_outer_mean=False),
        "template_pointwise_attn":
            attention(tmodel.template_pointwise_attn),
        "to_template_embed": linear(tmodel.to_template_embed),
        "template_angle_mlp_in": linear(tmodel.template_angle_mlp[0]),
        "template_angle_mlp_out": linear(tmodel.template_angle_mlp[2]),
        "distogram_norm": {"LayerNorm_0": layernorm(
            tmodel.to_distogram_logits[0])["LayerNorm_0"]},
        "to_distogram_logits": linear(tmodel.to_distogram_logits[1]),
        "msa_to_single_repr_dim": linear(tmodel.msa_to_single_repr_dim),
        "trunk_to_pairwise_repr_dim":
            linear(tmodel.trunk_to_pairwise_repr_dim),
        "lddt_linear": linear(tmodel.lddt_linear),
        "recycling_msa_norm": {"LayerNorm_0": layernorm(
            tmodel.recycling_msa_norm)["LayerNorm_0"]},
        "recycling_pairwise_norm": {"LayerNorm_0": layernorm(
            tmodel.recycling_pairwise_norm)["LayerNorm_0"]},
        "recycling_distance_embed":
            embedding(tmodel.recycling_distance_embed),
    }
    if getattr(tmodel, "predict_angles", False):
        ported["to_prob_theta"] = linear(tmodel.to_prob_theta)
        ported["to_prob_phi"] = linear(tmodel.to_prob_phi)
        ported["to_prob_omega"] = linear(tmodel.to_prob_omega)
    if hasattr(tmodel, "to_quaternion_update"):
        # structure-module surroundings (the IPA block itself is not
        # portable — see module docstring)
        ported["structure_module"] = {
            "to_quaternion_update": linear(tmodel.to_quaternion_update),
            "to_points": linear(tmodel.to_points),
        }

    def merge(template, new, path=""):
        if not isinstance(template, dict):
            arr = np.asarray(new)
            t_arr = np.asarray(template)
            if arr.shape != t_arr.shape:
                raise ValueError(
                    f"shape mismatch at {path}: ported {arr.shape} vs "
                    f"template {t_arr.shape}")
            return arr.astype(t_arr.dtype)
        out = dict(template)
        for k, v in new.items():
            if k not in template:
                raise KeyError(f"ported key {path}/{k} not in template — "
                               "config mismatch?")
            out[k] = merge(template[k], v, f"{path}/{k}")
        return out

    # present in the torch model regardless of config (torch builds every
    # module in __init__) but present in our lazily-built tree only when
    # the config exercises them
    config_dependent = {
        "msa_to_single_repr_dim", "trunk_to_pairwise_repr_dim",
        "lddt_linear", "structure_module",
        "to_prob_theta", "to_prob_phi", "to_prob_omega",
    }

    params = dict(template_params)
    top = dict(params["params"])
    unported = [k for k in top if k not in ported]
    for k, sub in ported.items():
        if k not in top:
            if k in config_dependent:
                continue
            raise KeyError(
                f"ported top-level {k!r} missing from template; build the "
                "template with the matching Alphafold2 configuration")
        top[k] = merge(top[k], sub, k)
    params["params"] = top
    return params, unported


def main():  # pragma: no cover - thin CLI around port_alphafold2
    import argparse
    import os
    import sys

    # same import surface as tests/test_parity.py: the repo root (for
    # alphafold2_tpu), this dir (for _reference_stubs) and the reference
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(here))
    sys.path.insert(0, here)
    if os.path.isdir("/root/reference"):
        sys.path.insert(0, "/root/reference")

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--torch-ckpt", required=True,
                        help="torch .pt file with a reference state_dict")
    parser.add_argument("--model-kwargs", default="{}",
                        help="JSON kwargs shared by both model constructors")
    parser.add_argument("--out", required=True,
                        help="output .msgpack of the flax params")
    args = parser.parse_args()

    import torch

    import _reference_stubs  # noqa: F401 (fills reference native deps)
    from alphafold2_pytorch import Alphafold2 as RefAlphafold2

    import jax
    from flax import serialization

    from alphafold2_tpu import Alphafold2

    kwargs = json.loads(args.model_kwargs)
    tmodel = RefAlphafold2(**kwargs)
    tmodel.load_state_dict(torch.load(args.torch_ckpt, map_location="cpu"))
    tmodel.eval()

    model = Alphafold2(**kwargs)
    seq = jax.numpy.zeros((1, 8), dtype=jax.numpy.int32)
    template = model.init(jax.random.PRNGKey(0), seq)
    params, unported = port_alphafold2(tmodel, template)
    with open(args.out, "wb") as f:
        f.write(serialization.to_bytes(params))
    print(f"wrote {args.out}; unported top-level keys: {unported}")


if __name__ == "__main__":
    main()
