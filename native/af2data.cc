// Native host-side data loader for alphafold2-tpu.
//
// The reference's data path leans on native cores hidden inside Python
// dependencies (BioPython/proDy/mdtraj/sidechainnet — SURVEY.md §2.4);
// this library is the framework's own native equivalent for the hot
// host-side work that feeds the TPU: MSA (a3m/FASTA) parsing +
// tokenization and PDB parsing into the 14-slot sidechainnet atom layout.
// Exposed as a C ABI consumed via ctypes (alphafold2_tpu/data/native.py);
// no Python objects cross the boundary — only flat buffers.
//
// Build: see native/Makefile (g++ -O3 -shared -fPIC).

#include <cctype>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// Tokenization must match alphafold2_tpu.constants.AA_ALPHABET
// ("ARNDCQEGHILKMFPSTWYV_"): index 20 ('_') is padding/unknown.
constexpr char kAlphabet[] = "ARNDCQEGHILKMFPSTWYV_";
constexpr int kPad = 20;
constexpr int kSlots = 14;  // NUM_COORDS_PER_RES

int8_t TokenTable(unsigned char c) {
  static int8_t table[256];
  static bool init = false;
  if (!init) {
    memset(table, kPad, sizeof(table));
    for (int i = 0; kAlphabet[i]; ++i) {
      table[static_cast<unsigned char>(kAlphabet[i])] = i;
      table[static_cast<unsigned char>(tolower(kAlphabet[i]))] = i;
    }
    init = true;
  }
  return table[c];
}

// sidechainnet slot order per residue: N CA C O then sidechain atoms.
const std::unordered_map<std::string, std::unordered_map<std::string, int>>&
SlotMap() {
  static const auto* m = [] {
    auto* mp = new std::unordered_map<std::string,
                                      std::unordered_map<std::string, int>>;
    struct Row { const char* res; const char* atoms; };
    // atoms beyond the backbone, space separated, slot 4 onwards
    static const Row rows[] = {
        {"ALA", "CB"},
        {"ARG", "CB CG CD NE CZ NH1 NH2"},
        {"ASN", "CB CG OD1 ND2"},
        {"ASP", "CB CG OD1 OD2"},
        {"CYS", "CB SG"},
        {"GLN", "CB CG CD OE1 NE2"},
        {"GLU", "CB CG CD OE1 OE2"},
        {"GLY", ""},
        {"HIS", "CB CG ND1 CD2 CE1 NE2"},
        {"ILE", "CB CG1 CG2 CD1"},
        {"LEU", "CB CG CD1 CD2"},
        {"LYS", "CB CG CD CE NZ"},
        {"MET", "CB CG SD CE"},
        {"PHE", "CB CG CD1 CD2 CE1 CE2 CZ"},
        {"PRO", "CB CG CD"},
        {"SER", "CB OG"},
        {"THR", "CB OG1 CG2"},
        {"TRP", "CB CG CD1 CD2 NE1 CE2 CE3 CZ2 CZ3 CH2"},
        {"TYR", "CB CG CD1 CD2 CE1 CE2 CZ OH"},
        {"VAL", "CB CG1 CG2"},
    };
    for (const auto& row : rows) {
      auto& slots = (*mp)[row.res];
      slots["N"] = 0;
      slots["CA"] = 1;
      slots["C"] = 2;
      slots["O"] = 3;
      int slot = 4;
      std::string atoms(row.atoms);
      size_t pos = 0;
      while (pos < atoms.size()) {
        size_t next = atoms.find(' ', pos);
        if (next == std::string::npos) next = atoms.size();
        if (next > pos) slots[atoms.substr(pos, next - pos)] = slot++;
        pos = next + 1;
      }
    }
    return mp;
  }();
  return *m;
}

const std::unordered_map<std::string, char>& ThreeToOne() {
  static const auto* m = [] {
    auto* mp = new std::unordered_map<std::string, char>{
        {"ALA", 'A'}, {"ARG", 'R'}, {"ASN", 'N'}, {"ASP", 'D'},
        {"CYS", 'C'}, {"GLN", 'Q'}, {"GLU", 'E'}, {"GLY", 'G'},
        {"HIS", 'H'}, {"ILE", 'I'}, {"LEU", 'L'}, {"LYS", 'K'},
        {"MET", 'M'}, {"PHE", 'F'}, {"PRO", 'P'}, {"SER", 'S'},
        {"THR", 'T'}, {"TRP", 'W'}, {"TYR", 'Y'}, {"VAL", 'V'}};
    return mp;
  }();
  return *m;
}

std::string Strip(const std::string& s) {
  size_t a = s.find_first_not_of(" \t\r\n");
  if (a == std::string::npos) return "";
  size_t b = s.find_last_not_of(" \t\r\n");
  return s.substr(a, b - a + 1);
}

}  // namespace

extern "C" {

// --- a3m / FASTA MSA parsing ---------------------------------------------
//
// Two-pass C ABI: msa_parse_a3m_size() reports (rows, cols) for the given
// text; msa_parse_a3m() fills a preallocated int8 row-major (rows, cols)
// token buffer. Insertions (lowercase letters and '.') are removed — the
// ESM-style convention (reference utils.py:241-252); '-' maps to padding.
// Returns 0 on success, negative on malformed input or width mismatch.

int msa_parse_a3m_size(const char* text, int64_t len, int64_t* rows,
                       int64_t* cols) {
  *rows = 0;
  *cols = 0;
  std::string cur;
  bool in_seq = false;
  auto flush = [&]() -> int {
    if (!in_seq) return 0;
    int64_t width = 0;
    for (char c : cur) {
      if (c == '.' || (isalpha(static_cast<unsigned char>(c)) &&
                       islower(static_cast<unsigned char>(c)))) {
        continue;  // insertion
      }
      ++width;
    }
    if (*rows == 0) {
      *cols = width;
    } else if (width != *cols) {
      return -2;  // ragged alignment
    }
    ++(*rows);
    cur.clear();
    return 0;
  };

  std::string line;
  for (int64_t i = 0; i <= len; ++i) {
    if (i == len || text[i] == '\n') {
      std::string s = Strip(line);
      line.clear();
      if (!s.empty() && s[0] == '>') {
        int rc = flush();
        if (rc) return rc;
        in_seq = true;
        cur.clear();
      } else if (!s.empty()) {
        if (!in_seq && *rows == 0 && cur.empty()) in_seq = true;  // raw seqs
        cur += s;
      }
    } else {
      line += text[i];
    }
  }
  return flush();
}

int msa_parse_a3m(const char* text, int64_t len, int8_t* out, int64_t rows,
                  int64_t cols) {
  int64_t row = 0;
  std::string cur;
  bool in_seq = false;
  auto flush = [&]() -> int {
    if (!in_seq) return 0;
    if (row >= rows) return -3;
    int64_t col = 0;
    for (char c : cur) {
      unsigned char u = static_cast<unsigned char>(c);
      if (c == '.' || (isalpha(u) && islower(u))) continue;
      if (col >= cols) return -2;
      out[row * cols + col] = (c == '-') ? kPad : TokenTable(u);
      ++col;
    }
    if (col != cols) return -2;
    ++row;
    cur.clear();
    return 0;
  };

  std::string line;
  for (int64_t i = 0; i <= len; ++i) {
    if (i == len || text[i] == '\n') {
      std::string s = Strip(line);
      line.clear();
      if (!s.empty() && s[0] == '>') {
        int rc = flush();
        if (rc) return rc;
        in_seq = true;
        cur.clear();
      } else if (!s.empty()) {
        if (!in_seq && row == 0 && cur.empty()) in_seq = true;
        cur += s;
      }
    } else {
      line += text[i];
    }
  }
  int rc = flush();
  if (rc) return rc;
  return row == rows ? 0 : -3;
}

// --- PDB parsing into the 14-slot layout ---------------------------------
//
// pdb_parse_size(): number of residues (chain-filtered, first model).
// pdb_parse(): fills seq tokens (int8, L), coords (float32, L*14*3) and
// atom mask (int8, L*14). chain = '\0' accepts the first chain found.

int pdb_parse_size(const char* text, int64_t len, char chain,
                   int64_t* n_res) {
  *n_res = 0;
  char active_chain = chain;
  int last_res = INT32_MIN;
  char last_icode = 0;
  std::string line;
  for (int64_t i = 0; i <= len; ++i) {
    if (i != len && text[i] != '\n') {
      line += text[i];
      continue;
    }
    if (line.rfind("ENDMDL", 0) == 0) break;
    if (line.rfind("ATOM", 0) == 0 && line.size() >= 54) {
      char ch = line[21];
      if (active_chain == '\0') active_chain = ch;
      // altloc filter must match pdb_parse or sizes diverge
      char altloc = line[16];
      if (ch == active_chain && (altloc == ' ' || altloc == 'A')) {
        int resseq = atoi(line.substr(22, 4).c_str());
        char icode = line[26];
        if (resseq != last_res || icode != last_icode) {
          ++(*n_res);
          last_res = resseq;
          last_icode = icode;
        }
      }
    }
    line.clear();
  }
  return 0;
}

int pdb_parse(const char* text, int64_t len, char chain, int8_t* seq,
              float* coords, int8_t* mask, int64_t n_res) {
  const auto& slot_map = SlotMap();
  const auto& three_to_one = ThreeToOne();
  char active_chain = chain;
  int last_res = INT32_MIN;
  char last_icode = 0;
  int64_t idx = -1;
  std::string line;
  memset(mask, 0, n_res * kSlots);
  memset(seq, kPad, n_res);

  for (int64_t i = 0; i <= len; ++i) {
    if (i != len && text[i] != '\n') {
      line += text[i];
      continue;
    }
    if (line.rfind("ENDMDL", 0) == 0) break;
    if (line.rfind("ATOM", 0) == 0 && line.size() >= 54) {
      char ch = line[21];
      if (active_chain == '\0') active_chain = ch;
      if (ch == active_chain) {
        // altloc: accept ' ' or 'A' only
        char altloc = line[16];
        if (altloc == ' ' || altloc == 'A') {
          int resseq = atoi(line.substr(22, 4).c_str());
          char icode = line[26];
          if (resseq != last_res || icode != last_icode) {
            ++idx;
            if (idx >= n_res) return -3;
            last_res = resseq;
            last_icode = icode;
            std::string resname = Strip(line.substr(17, 3));
            auto it = three_to_one.find(resname);
            if (it != three_to_one.end()) {
              seq[idx] = TokenTable(
                  static_cast<unsigned char>(it->second));
            }
          }
          std::string resname = Strip(line.substr(17, 3));
          std::string atom = Strip(line.substr(12, 4));
          auto res_it = slot_map.find(resname);
          if (res_it != slot_map.end()) {
            auto at_it = res_it->second.find(atom);
            if (at_it != res_it->second.end()) {
              int slot = at_it->second;
              float x = atof(line.substr(30, 8).c_str());
              float y = atof(line.substr(38, 8).c_str());
              float z = atof(line.substr(46, 8).c_str());
              float* dst = coords + (idx * kSlots + slot) * 3;
              dst[0] = x;
              dst[1] = y;
              dst[2] = z;
              mask[idx * kSlots + slot] = 1;
            }
          }
        }
      }
    }
    line.clear();
  }
  return 0;
}

// --- tokenization --------------------------------------------------------

void tokenize_seq(const char* seq, int64_t len, int8_t* out) {
  for (int64_t i = 0; i < len; ++i) {
    char c = seq[i];
    out[i] = (c == '-' || c == '.')
                 ? kPad
                 : TokenTable(static_cast<unsigned char>(c));
  }
}

}  // extern "C"
