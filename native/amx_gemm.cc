// AMX bf16 GEMM for the XLA:CPU host-fallback path.
//
// The framework's compute path is XLA:TPU; when a training step has to run
// on the host instead (driver fallback, tests, CI), XLA:CPU's dot emitter
// peaks at ~100 GFLOP/s on one core of this class of machine while the
// core's AMX tiles do >600 GFLOP/s in bf16. This file provides a
// single-threaded AMX GEMM exposed as an XLA FFI custom call
// ("af2_amx_gemm"), used by alphafold2_tpu/ops/cpu_gemm.py to route the
// model's Dense-layer contractions (f32 in/out, bf16 tile compute with f32
// accumulate — the same precision story as the TPU bf16 path, where the
// MXU also accumulates bf16 products into f32).
//
// Layout notes:
//   C[M,N] f32 = A[M,K] f32 x B[K,N] f32
//   - A rows are converted to bf16 into 32-wide K panels per 32-row block.
//   - B is converted/packed once per call into VNNI tiles: for tile row r
//     and output column c, bpack[r][2c+j] = B[32*kb + 2r + j][n0 + c] —
//     the operand layout _tile_dpbf16ps contracts over.
//   - C accumulates in f32 tile registers (2x2 tiles = 32x32 per block).
// Constraints: K % 32 == 0, N % 16 == 0; any M (tail rows masked on the
// C store). The Python wrapper falls back to XLA for other shapes.
//
// No counterpart in the reference (its CPU path is torch/ATen's oneDNN;
// this is the from-scratch equivalent for the JAX runtime).

#include <immintrin.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

#include "xla/ffi/api/ffi.h"

#define ARCH_REQ_XCOMP_PERM 0x1023
#define XFEATURE_XTILEDATA 18

namespace {

using bf16 = uint16_t;

bool amx_request_permission() {
  static const bool ok =
      syscall(SYS_arch_prctl, ARCH_REQ_XCOMP_PERM, XFEATURE_XTILEDATA) == 0;
  return ok;
}

void cfg_tiles() {
  // ldtilecfg layout: byte 0 palette, byte 1 start_row, 2-15 reserved
  // (must be zero), 16-47 colsb (16 x u16), 48-63 rows (16 x u8).
  // Explicit zeroed buffer + memcpy keeps the compiler from eliding the
  // zero-init of the reserved bytes (a GP fault otherwise).
  alignas(64) uint8_t cfg[64];
  std::memset(cfg, 0, sizeof(cfg));
  cfg[0] = 1;
  for (int i = 0; i < 8; i++) {
    uint16_t colsb = 64;
    std::memcpy(cfg + 16 + 2 * i, &colsb, 2);
    cfg[48 + i] = 16;
  }
  _tile_loadconfig(cfg);
}

// A block rows [m0, m0+rows) -> bf16 panels apack[kb][r][0..31].
void pack_a(const float* A, int lda, int m0, int rows, int K, bf16* out) {
  const int kb_n = K / 32;
  for (int kb = 0; kb < kb_n; kb++)
    for (int r = 0; r < rows; r++) {
      const float* src = A + (m0 + r) * (size_t)lda + kb * 32;
      __m512 lo = _mm512_loadu_ps(src);
      __m512 hi = _mm512_loadu_ps(src + 16);
      __m512bh packed = _mm512_cvtne2ps_pbh(hi, lo);
      _mm512_storeu_si512(out + ((size_t)kb * rows + r) * 32,
                          (__m512i)packed);
    }
}

// Bt[N, K] (B stored transposed) -> the same VNNI tile layout as pack_b:
// bpack[kb][r][2c+j] = B[kb*32+2r+j][n0+c] = Bt[n0+c][kb*32+2r+j].
// Per tile this is a 16x16 dword transpose of the bf16-pair columns;
// gathers keep it simple (pack is O(KN), the GEMM is O(MKN)).
void pack_b_trans(const float* Bt, int ldb, int K, int n0, bf16* out) {
  const int kb_n = K / 32;
  const __m512i vidx = _mm512_mullo_epi32(
      _mm512_set_epi32(15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1,
                       0),
      _mm512_set1_epi32(ldb));
  for (int kb = 0; kb < kb_n; kb++)
    for (int r = 0; r < 16; r++) {
      // column pair (2r, 2r+1) of rows n0..n0+15
      const float* base0 = Bt + (size_t)n0 * ldb + kb * 32 + 2 * r;
      __m512 c0 = _mm512_i32gather_ps(vidx, base0, 4);
      __m512 c1 = _mm512_i32gather_ps(vidx, base0 + 1, 4);
      __m512bh bh = _mm512_cvtne2ps_pbh(c1, c0);  // low 16 = c0, high = c1
      __m512i x = (__m512i)bh;
      __m256i lo = _mm512_castsi512_si256(x);
      __m256i hi = _mm512_extracti64x4_epi64(x, 1);
      __m512i lo512 = _mm512_cvtepu16_epi32(lo);
      __m512i hi512 = _mm512_slli_epi32(_mm512_cvtepu16_epi32(hi), 16);
      _mm512_storeu_si512(out + ((size_t)kb * 16 + r) * 32,
                          _mm512_or_si512(lo512, hi512));
    }
}

// B[K, n0:n0+16] -> VNNI tiles bpack[kb][r][2c+j] = B[kb*32+2r+j][n0+c].
void pack_b(const float* B, int ldb, int K, int n0, bf16* out) {
  const int kb_n = K / 32;
  for (int kb = 0; kb < kb_n; kb++)
    for (int r = 0; r < 16; r++) {
      const float* row0 = B + (size_t)(kb * 32 + 2 * r) * ldb + n0;
      const float* row1 = row0 + ldb;
      __m512 v0 = _mm512_loadu_ps(row0);
      __m512 v1 = _mm512_loadu_ps(row1);
      __m512bh bh = _mm512_cvtne2ps_pbh(v1, v0);
      __m512i x = (__m512i)bh;
      __m256i lo = _mm512_castsi512_si256(x);
      __m256i hi = _mm512_extracti64x4_epi64(x, 1);
      __m512i lo512 = _mm512_cvtepu16_epi32(lo);
      __m512i hi512 = _mm512_slli_epi32(_mm512_cvtepu16_epi32(hi), 16);
      _mm512_storeu_si512(out + ((size_t)kb * 16 + r) * 32,
                          _mm512_or_si512(lo512, hi512));
    }
}

// One (m0, n0) block: C[m0:m0+rows, n0:n0+ncols] via 2x2 (or 2x1) C tiles.
// bnext: start of the NEXT 32-column B panel pair (or nullptr) — software
// prefetch overlaps its L2->L1 fill with this block's tile math (worth
// ~15-25% measured; the 1KB tile loads otherwise stall on L2 latency).
void block_2x2(const bf16* apack, const bf16* bp0, const bf16* bp1, float* C,
               int ldc, int m0, int rows, int n0, int kb_n,
               const bf16* bnext, size_t bnext_stride) {
  const int r0 = std::min(16, rows), r1 = rows - r0;
  float cbuf[16 * 16] __attribute__((aligned(64)));
  _tile_zero(0);
  _tile_zero(1);
  _tile_zero(2);
  _tile_zero(3);
  for (int kb = 0; kb < kb_n; kb++) {
    _tile_loadd(4, apack + (size_t)kb * rows * 32, 64);
    _tile_loadd(6, bp0 + (size_t)kb * 16 * 32, 64);
    _tile_dpbf16ps(0, 4, 6);
    if (bp1) {
      _tile_loadd(7, bp1 + (size_t)kb * 16 * 32, 64);
      _tile_dpbf16ps(1, 4, 7);
    }
    if (bnext) {
      // one prefetch per 64-byte line: 16 lines cover the full 1KB tile
      const char* pf = (const char*)(bnext + (size_t)kb * 16 * 32);
      for (int l = 0; l < 1024; l += 64) _mm_prefetch(pf + l, _MM_HINT_T0);
      pf = (const char*)(bnext + bnext_stride + (size_t)kb * 16 * 32);
      for (int l = 0; l < 1024; l += 64) _mm_prefetch(pf + l, _MM_HINT_T0);
    }
    if (r1 > 0) {
      _tile_loadd(5, apack + ((size_t)kb * rows + 16) * 32, 64);
      _tile_dpbf16ps(2, 5, 6);
      if (bp1) _tile_dpbf16ps(3, 5, 7);
    }
  }
  auto spill = [&](int mrow, int ncol, int nrows) {
    for (int r = 0; r < nrows; r++)
      std::memcpy(C + (size_t)(mrow + r) * ldc + ncol, cbuf + r * 16, 64);
  };
  _tile_stored(0, cbuf, 64);
  spill(m0, n0, r0);
  if (bp1) {
    _tile_stored(1, cbuf, 64);
    spill(m0, n0 + 16, r0);
  }
  if (r1 > 0) {
    _tile_stored(2, cbuf, 64);
    spill(m0 + 16, n0, r1);
    if (bp1) {
      _tile_stored(3, cbuf, 64);
      spill(m0 + 16, n0 + 16, r1);
    }
  }
}

// Full GEMM with explicit leading dimensions (strided rows let callers
// hand in interior slices of rank-4 tensors, e.g. one attention head of
// a [b, n, heads, d] block without any transpose). K % 32 == 0,
// N % 16 == 0, any M. trans_b: B passed [N, K] with row stride ldb.
void gemm_ld(const float* A, int lda, const float* B, int ldb, float* C,
             int ldc, int64_t M, int64_t N, int64_t K, bool trans_b) {
  const int kb_n = (int)(K / 32);
  static thread_local std::vector<bf16> bpack;
  static thread_local std::vector<bf16> apack;
  bpack.resize((size_t)K * N);
  apack.resize((size_t)32 * K);
  for (int64_t n0 = 0; n0 < N; n0 += 16) {
    if (trans_b)
      pack_b_trans(B, ldb, (int)K, (int)n0, bpack.data() + (size_t)n0 * K);
    else
      pack_b(B, ldb, (int)K, (int)n0, bpack.data() + (size_t)n0 * K);
  }
  for (int64_t m0 = 0; m0 < M; m0 += 32) {
    const int rows = (int)std::min<int64_t>(32, M - m0);
    pack_a(A, lda, (int)m0, rows, (int)K, apack.data());
    int64_t n0 = 0;
    for (; n0 + 32 <= N; n0 += 32) {
      const bf16* bnext = (n0 + 64 <= N)
          ? bpack.data() + (size_t)(n0 + 32) * K : nullptr;
      block_2x2(apack.data(), bpack.data() + (size_t)n0 * K,
                bpack.data() + (size_t)(n0 + 16) * K, C, ldc, (int)m0,
                rows, (int)n0, kb_n, bnext, (size_t)K * 16);
    }
    if (n0 < N)  // odd 16-column tail
      block_2x2(apack.data(), bpack.data() + (size_t)n0 * K, nullptr, C,
                ldc, (int)m0, rows, (int)n0, kb_n, nullptr, 0);
  }
}

void gemm(const float* A, const float* B, float* C, int64_t M, int64_t N,
          int64_t K, bool trans_b = false) {
  gemm_ld(A, (int)K, B, trans_b ? (int)K : (int)N, C, (int)N, M, N, K,
          trans_b);
}

namespace ffi = xla::ffi;

// a: [M, K] or [G, M, K]; b: [K, N] or [G, K, N] (G = batch of GEMMs);
// trans_b: b is [N, K] / [G, N, K] instead.
ffi::Error GemmRun(ffi::Buffer<ffi::F32>& a, ffi::Buffer<ffi::F32>& b,
                   ffi::ResultBuffer<ffi::F32>& c, bool trans_b) {
  if (!amx_request_permission())
    return ffi::Error(ffi::ErrorCode::kFailedPrecondition,
                      "AMX tile permission unavailable");
  auto adims = a.dimensions();
  auto bdims = b.dimensions();
  if ((adims.size() != 2 && adims.size() != 3) ||
      bdims.size() != adims.size())
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "af2_amx_gemm expects rank-2 or rank-3 operands");
  const bool batched = adims.size() == 3;
  const int64_t G = batched ? adims[0] : 1;
  const int64_t M = adims[batched ? 1 : 0];
  const int64_t K = adims[batched ? 2 : 1];
  const int64_t bd0 = bdims[batched ? 1 : 0];
  const int64_t bd1 = bdims[batched ? 2 : 1];
  const int64_t N = trans_b ? bd0 : bd1;
  if ((trans_b ? bd1 : bd0) != K || (batched && bdims[0] != G))
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "af2_amx_gemm operand shape mismatch");
  if (K % 32 || N % 16)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "af2_amx_gemm requires K % 32 == 0 and N % 16 == 0");
  cfg_tiles();
  for (int64_t g = 0; g < G; g++)
    gemm(a.typed_data() + g * M * K, b.typed_data() + g * K * N,
         c->typed_data() + g * M * N, M, N, K, trans_b);
  _tile_release();
  return ffi::Error::Success();
}

// q [B,N,H,D] x k [B,M,H,D] -> logits [B,H,N,M]: per-(batch, head) GEMM
// over interior slices — heads stay minor to tokens, so the caller never
// materializes a [B,H,N,D] transpose (the attention layout the model
// actually carries).
ffi::Error AttnQkImpl(ffi::Buffer<ffi::F32> q, ffi::Buffer<ffi::F32> k,
                      ffi::ResultBuffer<ffi::F32> c) {
  if (!amx_request_permission())
    return ffi::Error(ffi::ErrorCode::kFailedPrecondition,
                      "AMX tile permission unavailable");
  auto qd = q.dimensions();
  auto kd = k.dimensions();
  if (qd.size() != 4 || kd.size() != 4)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "af2_amx_attn_qk expects rank-4 [B,N,H,D] operands");
  const int64_t B = qd[0], N = qd[1], H = qd[2], D = qd[3];
  const int64_t M = kd[1];
  if (kd[0] != B || kd[2] != H || kd[3] != D)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "af2_amx_attn_qk operand shape mismatch");
  if (D % 32 || M % 16)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "af2_amx_attn_qk requires D % 32 == 0, M % 16 == 0");
  cfg_tiles();
  const int ld = (int)(H * D);
  for (int64_t ib = 0; ib < B; ib++)
    for (int64_t ih = 0; ih < H; ih++)
      gemm_ld(q.typed_data() + ib * N * H * D + ih * D, ld,
              k.typed_data() + ib * M * H * D + ih * D, ld,
              c->typed_data() + (ib * H + ih) * N * M, (int)M,
              N, M, D, /*trans_b=*/true);
  _tile_release();
  return ffi::Error::Success();
}

// probs [B,H,N,M] x v [B,M,H,D] -> out [B,N,H,D]: the dual of AttnQk —
// the output lands directly in the model's token-major layout (C rows
// strided by H*D), so no un-transpose follows the attention either.
ffi::Error AttnAvImpl(ffi::Buffer<ffi::F32> p, ffi::Buffer<ffi::F32> v,
                      ffi::ResultBuffer<ffi::F32> c) {
  if (!amx_request_permission())
    return ffi::Error(ffi::ErrorCode::kFailedPrecondition,
                      "AMX tile permission unavailable");
  auto pd = p.dimensions();
  auto vd = v.dimensions();
  if (pd.size() != 4 || vd.size() != 4)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "af2_amx_attn_av expects rank-4 operands");
  const int64_t B = pd[0], H = pd[1], N = pd[2], M = pd[3];
  const int64_t D = vd[3];
  if (vd[0] != B || vd[1] != M || vd[2] != H)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "af2_amx_attn_av operand shape mismatch");
  if (M % 32 || D % 16)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "af2_amx_attn_av requires M % 32 == 0, D % 16 == 0");
  cfg_tiles();
  const int ld = (int)(H * D);
  for (int64_t ib = 0; ib < B; ib++)
    for (int64_t ih = 0; ih < H; ih++)
      gemm_ld(p.typed_data() + (ib * H + ih) * N * M, (int)M,
              v.typed_data() + ib * M * H * D + ih * D, ld,
              c->typed_data() + ib * N * H * D + ih * D, ld,
              N, D, M, /*trans_b=*/false);
  _tile_release();
  return ffi::Error::Success();
}

ffi::Error GemmImpl(ffi::Buffer<ffi::F32> a, ffi::Buffer<ffi::F32> b,
                    ffi::ResultBuffer<ffi::F32> c) {
  return GemmRun(a, b, c, /*trans_b=*/false);
}

ffi::Error GemmTbImpl(ffi::Buffer<ffi::F32> a, ffi::Buffer<ffi::F32> b,
                      ffi::ResultBuffer<ffi::F32> c) {
  return GemmRun(a, b, c, /*trans_b=*/true);
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(Af2AmxGemm, GemmImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(Af2AmxGemmTb, GemmTbImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(Af2AmxAttnQk, AttnQkImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(Af2AmxAttnAv, AttnAvImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

extern "C" int af2_amx_available() {
  if (!amx_request_permission()) return 0;
  // trap-check: configure and immediately release a tile state
  cfg_tiles();
  _tile_release();
  return 1;
}
