"""Multi-host (pod-scale) initialization and data placement.

The distributed communication backend of this framework is XLA's GSPMD
collectives over ICI within a slice and DCN across slices — the TPU-native
replacement for the reference's aspirational NCCL-through-DeepSpeed path
(SURVEY.md §5.8, reference training_scripts/*.py are empty stubs). This
module holds the host-side glue:

- `initialize()`: `jax.distributed.initialize` wrapper (no-op when
  single-process, e.g. local runs and tests);
- `global_mesh()`: build the (pipe, data, i, j) mesh over ALL processes'
  devices;
- `host_local_batch_to_global()`: assemble a globally-sharded array from
  per-host shards (`jax.make_array_from_process_local_data`) so each host
  feeds only its slice of the batch.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alphafold2_tpu.parallel.mesh import AXIS_NAMES, DATA_AXIS


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Initialize the multi-process runtime; returns True if distributed.
    Safe to call unconditionally — single-process runs skip it."""
    if num_processes is None or num_processes <= 1:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    return True


def global_mesh(data: int = 1, i: int = 1, j: int = 1,
                pipe: int = 1) -> Mesh:
    """Mesh over all processes' devices (jax.devices() is global)."""
    devices = jax.devices()
    need = pipe * data * i * j
    if need != len(devices):
        raise ValueError(f"mesh {pipe}x{data}x{i}x{j}={need} != global "
                         f"device count {len(devices)}")
    return Mesh(np.asarray(devices).reshape(pipe, data, i, j), AXIS_NAMES)


def host_local_batch_to_global(batch, mesh: Mesh):
    """Per-host batch shards -> one global jax.Array per leaf, sharded on
    the data axis. Each process passes only its local portion."""

    def place(x):
        spec = [None] * x.ndim
        if x.ndim >= 1:
            spec[0] = DATA_AXIS
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(*spec)), np.asarray(x))

    return jax.tree.map(place, batch)
