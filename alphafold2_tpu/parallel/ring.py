"""Ring attention: exact attention over a sequence axis sharded across
devices, with K/V shards rotated around the mesh ring via `ppermute`.

This is the long-context strategy the reference lacks entirely (SURVEY.md
§5.7 — its sequence scaling is all single-device tricks: axial
factorization, sparse/linear attention, checkpointing). On TPU the ring
maps 1:1 onto ICI neighbors: each step overlaps a blockwise flash-style
attention update with the neighbor exchange, so memory per device is
O(L/n_shards) for K/V while the math stays exactly softmax attention
(online log-sum-exp accumulation, Liu et al. 2023 "Ring Attention with
Blockwise Transformers").

Use inside `shard_map` over a mesh axis; `ring_attention_sharded` wraps
that for (b, n, h, d) inputs sharded on n.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from alphafold2_tpu.parallel.sharding import shard_map_compat


def _axis_size(axis_name) -> jnp.ndarray:
    """jax.lax.axis_size where it exists (jax >= 0.8); the classic
    psum-of-ones identity on older jax."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def _block_attend(q, k, v, bias, acc, row_max, row_sum):
    """One blockwise online-softmax update.

    q: (b, h, nq, d); k/v: (b, h, nk, d); bias: (b, h, nq, nk) or None;
    acc: (b, h, nq, d) running weighted sum; row_max/row_sum: (b, h, nq).
    Returns updated (acc, row_max, row_sum).
    """
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    if bias is not None:
        logits = logits + bias

    new_max = jnp.maximum(row_max, logits.max(-1))
    correction = jnp.exp(row_max - new_max)
    p = jnp.exp(logits - new_max[..., None])

    acc = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    row_sum = row_sum * correction + p.sum(-1)
    return acc, new_max, row_sum


def ring_attention(
    q: jnp.ndarray,      # (b, h, nq_local, d), pre-scaled
    k: jnp.ndarray,      # (b, h, nk_local, d)
    v: jnp.ndarray,      # (b, h, nk_local, d)
    axis_name: str,
    bias: Optional[jnp.ndarray] = None,   # (b, h, nq_local, nk_GLOBAL)
    mask: Optional[jnp.ndarray] = None,   # (b, nk_GLOBAL) key validity
) -> jnp.ndarray:
    """Exact attention where each device holds one K/V shard; runs inside
    shard_map/pmap over `axis_name`. bias/mask carry the GLOBAL key axis
    (every device already holds its full rows of pair bias)."""
    n_shards = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    nk = k.shape[-2]

    b, h, nq, d = q.shape
    acc = jnp.zeros((b, h, nq, d), jnp.float32)
    row_max = jnp.full((b, h, nq), -jnp.inf, jnp.float32)
    row_sum = jnp.zeros((b, h, nq), jnp.float32)

    def slice_global(x, shard):
        start = shard * nk
        return jax.lax.dynamic_slice_in_dim(x, start, nk, axis=-1)

    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def body(step, carry):
        acc, row_max, row_sum, k_cur, v_cur = carry
        # which global shard the current K/V block came from
        shard = (my_idx - step) % n_shards

        blk_bias = None
        if bias is not None:
            blk_bias = slice_global(bias, shard).astype(jnp.float32)
        if mask is not None:
            key_ok = slice_global(mask, shard)
            mbias = jnp.where(key_ok[:, None, None, :], 0.0, -1e9)
            blk_bias = mbias if blk_bias is None else blk_bias + mbias

        acc, row_max, row_sum = _block_attend(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), blk_bias, acc, row_max, row_sum)

        # rotate K/V to the next device (skippable on the last step, but a
        # uniform loop keeps the collective schedule static)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, row_max, row_sum, k_nxt, v_nxt

    acc, row_max, row_sum, _, _ = jax.lax.fori_loop(
        0, n_shards, body, (acc, row_max, row_sum, k, v))

    out = acc / jnp.maximum(row_sum[..., None], 1e-30)
    return out.astype(q.dtype)


def _as_key_data(key) -> jnp.ndarray:
    """PRNG key -> raw uint32 data (shard_map-friendly replicated operand)."""
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        return jax.random.key_data(key)
    return key


def _device_dropout_key(key_data, coords):
    """Per-device base dropout key: fold each mesh coordinate of the
    device into the replicated base key. `coords` are traced axis_index
    values (skipping axes the operand is not sharded over), so every
    device derives an independent mask stream — the reversible trunk's
    fold_in recipe (model/reversible.py) applied to the mesh."""
    k = jax.random.wrap_key_data(key_data)
    for c in coords:
        k = jax.random.fold_in(k, c)
    return k


def pair_row_dropout_mask(
    key, rate: float, *, b: int, h: int, j_blocks: int,
    il: int, jl: int, i_blocks: int | None = 1,
    data_coord: int | None = None,
):
    """Dense replay of the ring kernel's dropout mask derivation, for
    parity tests: returns the full (b, h, I, J_q, J_k) keep mask that a
    mesh run of `pair_row_attention_sharded` with the same `key`
    realizes. `i_blocks=None` mirrors an unsharded row axis (i coord not
    folded); an int mirrors an i mesh axis of that size (folded even at
    size 1, matching the kernel). Shares `_device_dropout_key` with the
    kernel so the derivation cannot drift; what the parity test then
    checks independently is the ring's *distribution* semantics
    (undropped row_sum normalization, 1/(1-rate) scaling, gradient
    flow)."""
    kd = _as_key_data(key)
    rows = []
    for ic in range(i_blocks or 1):
        cols = []
        for jc in range(j_blocks):
            coords = [] if data_coord is None else [data_coord]
            coords += ([] if i_blocks is None else [ic]) + [jc]
            dev = _device_dropout_key(kd, coords)
            blocks = [
                jax.random.bernoulli(
                    jax.random.fold_in(dev, ks), 1.0 - rate,
                    (b, h, il, jl, jl))
                for ks in range(j_blocks)
            ]
            cols.append(jnp.concatenate(blocks, axis=-1))
        rows.append(jnp.concatenate(cols, axis=-2))
    return jnp.concatenate(rows, axis=2)


def pair_row_attention_sharded(
    q: jnp.ndarray,      # (b, h, I, J, d) global, pre-scaled
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray],  # (b, h, J, J) edge bias between column
    mesh: Mesh,                   # positions, or None
    i_axis: Optional[str] = "i",
    j_axis: str = "j",
    mask: Optional[jnp.ndarray] = None,   # (b, I, J) per-row key validity
    data_axis: Optional[str] = "data",
    dropout_rate: float = 0.0,
    dropout_key=None,             # PRNG key; required when rate > 0
) -> jnp.ndarray:
    """Row attention over the J axis of a sharded 2-D map, ring-parallel
    (SURVEY.md §5.7 hard-part #1).

    Layout: q/k/v are per-cell projections of the map, sharded
    P(data, -, i, j, -); within each row i, cells attend along J with the
    edge bias bias[j_query, j_key] (the reference's edges_to_attn_bias
    semantics, alphafold2.py:214-217, :246-248 — the same (J, J) bias for
    every row). The bias enters the shard_map sharded over its QUERY axis
    by the j mesh axis with the key axis kept whole (one J_local x J
    panel per device — a 1/n_j slice, resharded from the pair layout by
    one GSPMD all-to-all at the boundary); the ring then slices the
    matching key block each step. Output returns with the input sharding.

    `i_axis=None` means the row axis is unsharded (the MSA track: rows
    are alignments, only the attended residue axis is sharded).
    `mask` is per-row key validity (b, I, J) — the full pair/MSA mask —
    sliced along the key axis each ring step, so arbitrary non-separable
    masks are honored EXACTLY (round-2 VERDICT weak #5: the old (b, J)
    vector contract silently relaxed them). `data_axis` keeps the batch
    dim sharded inside the shard_map; without it the data-parallel batch
    would be all-gathered (and redundantly computed) across the data
    axis for the duration of the ring.

    Training-time attention-prob dropout runs INSIDE the ring (round-4
    VERDICT #5 — it used to silently disable the ring): each device
    folds its mesh coordinates into `dropout_key`, then folds the global
    key-shard index per ring step, and Bernoulli-drops the unnormalized
    softmax numerator while `row_sum` accumulates UNDROPPED — exactly
    the dense semantics `out = dropout(softmax(logits)) @ v` with
    1/(1-rate) scaling, since the softmax normalizer is independent of
    which post-softmax terms dropout zeroes.
    """
    if dropout_rate > 0.0 and dropout_key is None:
        raise ValueError("pair_row_attention_sharded: dropout_rate > 0 "
                         "requires dropout_key")

    def ax(name, dim=None):
        if name is None or name not in mesh.axis_names:
            return None
        if dim is not None and dim % mesh.shape[name] != 0:
            return None  # e.g. batch=1 on a data=2 training mesh
        return name

    da, ia = ax(data_axis, q.shape[0]), ax(i_axis)
    spec = P(da, None, ia, j_axis, None)
    bias_spec = P(da, None, j_axis, None)     # query rows local, keys whole
    mask_spec = P(da, ia, None)               # rows local, key axis whole
    has_bias = bias is not None

    has_mask = mask is not None
    has_drop = dropout_rate > 0.0

    args = [q, k, v]
    in_specs = [spec, spec, spec]
    if has_bias:
        args.append(bias)
        in_specs.append(bias_spec)
    if has_mask:
        args.append(mask)
        in_specs.append(mask_spec)
    if has_drop:
        args.append(_as_key_data(dropout_key))
        in_specs.append(P(None))              # replicated; devices fold
                                              # their own mesh coords in

    def kernel(qi, ki, vi, *rest):
        rest = list(rest)
        bi = rest.pop(0) if has_bias else None
        mi = rest.pop(0) if has_mask else None
        dev_key = None
        if has_drop:
            coords = [jax.lax.axis_index(a) for a in (da, ia) if a]
            coords.append(jax.lax.axis_index(j_axis))
            dev_key = _device_dropout_key(rest.pop(0), coords)
        b, h, il, jl, d = qi.shape
        n_shards = _axis_size(j_axis)
        my_idx = jax.lax.axis_index(j_axis)
        perm = [(s, (s + 1) % n_shards) for s in range(n_shards)]

        qf = qi.astype(jnp.float32)
        acc = jnp.zeros((b, h, il, jl, d), jnp.float32)
        row_max = jnp.full((b, h, il, jl), -jnp.inf, jnp.float32)
        row_sum = jnp.zeros((b, h, il, jl), jnp.float32)

        # bias stays ONE (b, h, jl, J) panel; the per-step (jl, jl) slice
        # broadcasts over the il row axis inside the logits add
        def body(step, carry):
            acc, row_max, row_sum, k_cur, v_cur = carry
            shard = (my_idx - step) % n_shards
            logits = jnp.einsum(
                "bhiqd,bhikd->bhiqk", qf, k_cur.astype(jnp.float32))
            if bi is not None:
                blk_bias = jax.lax.dynamic_slice_in_dim(
                    bi, shard * jl, jl, axis=-1).astype(jnp.float32)
                logits = logits + blk_bias[:, :, None]
            if mi is not None:
                key_ok = jax.lax.dynamic_slice_in_dim(
                    mi, shard * jl, jl, axis=-1)     # (b, il, jl_k)
                logits = jnp.where(key_ok[:, None, :, None, :],
                                   logits, -1e9)

            new_max = jnp.maximum(row_max, logits.max(-1))
            corr = jnp.exp(row_max - new_max)
            p = jnp.exp(logits - new_max[..., None])
            p_av = p
            if dev_key is not None:
                # drop the numerator only; row_sum stays undropped so the
                # final acc/row_sum equals dense dropout(softmax(..)) @ v
                keep = jax.random.bernoulli(
                    jax.random.fold_in(dev_key, shard),
                    1.0 - dropout_rate, p.shape)
                p_av = p * keep / (1.0 - dropout_rate)
            acc2 = acc * corr[..., None] + jnp.einsum(
                "bhiqk,bhikd->bhiqd", p_av, v_cur.astype(jnp.float32))
            sum2 = row_sum * corr + p.sum(-1)
            return (acc2, new_max, sum2,
                    jax.lax.ppermute(k_cur, j_axis, perm),
                    jax.lax.ppermute(v_cur, j_axis, perm))

        acc, row_max, row_sum, _, _ = jax.lax.fori_loop(
            0, n_shards, body, (acc, row_max, row_sum, ki, vi))
        out = acc / jnp.maximum(row_sum[..., None], 1e-30)
        return out.astype(qi.dtype)

    fn = shard_map_compat(kernel, mesh, tuple(in_specs), spec,
                          check=False)
    return fn(*args)


def ring_attention_sharded(
    q: jnp.ndarray,      # (b, h, n, d) global
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str,
    bias: Optional[jnp.ndarray] = None,   # (b, h, n, n) global
    mask: Optional[jnp.ndarray] = None,   # (b, n) global
) -> jnp.ndarray:
    """shard_map wrapper: shards q/k/v (and bias rows) over `axis` on the
    sequence dim and runs the ring. Result comes back sharded the same way.
    """
    seq_spec = P(None, None, axis, None)
    bias_spec = P(None, None, axis, None)

    in_specs = [seq_spec, seq_spec, seq_spec]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(bias_spec)
        args.append(bias)
    if mask is not None:
        in_specs.append(P(None, None))
        args.append(mask)

    def kernel(*xs):
        qi, ki, vi = xs[0], xs[1], xs[2]
        rest = list(xs[3:])
        bi = rest.pop(0) if bias is not None else None
        mi = rest.pop(0) if mask is not None else None
        return ring_attention(qi, ki, vi, axis, bias=bi, mask=mi)

    fn = shard_map_compat(kernel, mesh, tuple(in_specs), seq_spec,
                          check=False)
    return fn(*args)
