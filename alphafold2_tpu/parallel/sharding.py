"""Sharding rules and in-model constraint helpers.

The model calls `shard_pair` / `shard_msa` / `shard_seq` at block boundaries;
under an active mesh these lower to `with_sharding_constraint`
(GSPMD placement hints), outside a mesh they are no-ops — the same model
code runs single-chip and multi-chip. This replaces the reference's absent
distributed layer (SURVEY.md §2.5, §5.8) without invading model code.

Tensor contracts (axes -> PartitionSpec):
- pair  (b, i, j, d)      -> P(data, i, j, None)
- msa   (b, m, n, d)      -> P(data, None, i, None)
- seq   (b, n, d)         -> P(data, None, None)
- coords(b, n, 3)         -> P(data, None, None)
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alphafold2_tpu.parallel.mesh import DATA_AXIS, PAIR_I_AXIS, PAIR_J_AXIS

_state = threading.local()


def active_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs,
                     manual_axes: Optional[frozenset] = None,
                     check: Optional[bool] = None):
    """Version-tolerant shard_map: `jax.shard_map` (jax >= 0.8 — manual
    axes via `axis_names`, replication typing via `check_vma`) or
    `jax.experimental.shard_map.shard_map` (jax 0.4.x — the complement
    `auto=` set and `check_rep`). `manual_axes=None` means fully manual;
    `check=None` keeps each API's default."""
    kw = {}
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        if manual_axes is not None:
            kw["axis_names"] = frozenset(manual_axes)
        if check is not None:
            kw["check_vma"] = check
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as legacy_sm
    if manual_axes is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(manual_axes)
    if check is not None:
        kw["check_rep"] = check
    return legacy_sm(f, mesh, in_specs, out_specs, **kw)


def _enter_mesh(mesh: Mesh):
    """The version-tolerant ambient-mesh context: `jax.set_mesh` where
    it exists (jax >= 0.5), else the Mesh's own resource-env context
    manager (jax 0.4.x — `with mesh:`). Constraints here always name
    their mesh explicitly via NamedSharding, so the ambient context
    only matters for closures traced under jit."""
    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh], manual_axes: frozenset = frozenset()):
    """Activate a mesh for model-internal sharding constraints.

    Also enters `jax.set_mesh` so closures under jit see the mesh.

    `manual_axes`: axis names the caller has already made manual via
    `shard_map` (e.g. the pipeline's `pipe`/`data` axes). Constraints
    inside the mapped body may only mention the remaining auto axes, so
    `_constraint` drops manual names from its specs — this is how the
    2-D pair sharding stays live INSIDE a pipeline stage (VERDICT r4 #4).
    """
    prev = getattr(_state, "mesh", None)
    prev_manual = getattr(_state, "manual", frozenset())
    _state.mesh = mesh
    _state.manual = frozenset(manual_axes)
    try:
        if mesh is not None and not manual_axes:
            with _enter_mesh(mesh):
                yield mesh
        else:
            # inside a shard_map body the ambient mesh is already manual;
            # entering jax.set_mesh again is neither needed (constraints
            # name their mesh explicitly) nor allowed mid-trace
            yield mesh
    finally:
        _state.mesh = prev
        _state.manual = prev_manual


def _constraint(x, spec: P):
    mesh = active_mesh()
    if mesh is None:
        return x
    manual = getattr(_state, "manual", frozenset())
    # drop axis names the mesh doesn't have, can't divide the dim, or
    # that are manual in the enclosing shard_map
    cleaned = []
    for dim, axis in zip(x.shape, spec):
        if axis is None or axis not in mesh.axis_names or axis in manual:
            cleaned.append(None)
        elif dim % mesh.shape[axis] != 0:
            cleaned.append(None)
        else:
            cleaned.append(axis)
    if all(a is None for a in cleaned):
        return x
    # pad spec to rank
    cleaned += [None] * (x.ndim - len(cleaned))
    if manual:
        # inside a shard_map body the constraint must name the mesh view
        # whose axis types carry the enclosing Manual axes — that is the
        # trace-time abstract mesh, not the concrete one we stored
        # (jax < 0.5 has no abstract-mesh API; the concrete-mesh
        # fallback below is what those versions expect)
        get_amesh = getattr(jax.sharding, "get_abstract_mesh", None)
        amesh = get_amesh() if get_amesh is not None else None
        if amesh is not None and amesh.axis_names:
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(amesh, P(*cleaned)))
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*cleaned)))


def pair_spec() -> P:
    return P(DATA_AXIS, PAIR_I_AXIS, PAIR_J_AXIS, None)


def msa_spec() -> P:
    return P(DATA_AXIS, None, PAIR_I_AXIS, None)


def seq_spec() -> P:
    return P(DATA_AXIS, None, None)


def shard_pair(x):
    """(b, i, j, d) pair activations: 2-D shard the residue axes."""
    return _constraint(x, pair_spec())


def shard_msa(x):
    """(b, m, n, d) MSA activations: shard the sequence axis."""
    return _constraint(x, msa_spec())


def shard_seq(x):
    """(b, n, d) single-track activations: data-parallel only."""
    return _constraint(x, seq_spec())


def fold_input_specs() -> dict:
    """PartitionSpecs for the serving executor's fold INPUTS (the
    inference-side seam `serve.FoldExecutor` lowers under — training
    goes through `shard_*` constraints instead).

    Token inputs are tiny next to the in-model pair tensor, so seq/mask
    replicate; the MSA tokens shard their sequence axis over `i` (same
    contract as `msa_spec`, one rank lower — no feature dim yet) so the
    msa embedding materializes already distributed:

    - seq      (b, n)    -> P()
    - mask     (b, n)    -> P()
    - msa      (b, m, n) -> P(None, None, i)
    - msa_mask (b, m, n) -> P(None, None, i)
    """
    return {"seq": P(), "mask": P(),
            "msa": P(None, None, PAIR_I_AXIS),
            "msa_mask": P(None, None, PAIR_I_AXIS)}


def fold_input_shardings(mesh: Mesh, batch: dict) -> dict:
    """NamedShardings for one assembled serving batch on `mesh`.
    A spec axis that cannot divide the actual dim (or is missing from
    the mesh) degrades to replication for that tensor — placement is a
    performance hint, never a shape constraint."""
    out = {}
    for name, spec in fold_input_specs().items():
        x = batch.get(name)
        if x is None:
            out[name] = None
            continue
        cleaned = []
        for dim, axis in zip(x.shape, spec):
            if axis is None or axis not in mesh.axis_names \
                    or dim % mesh.shape[axis] != 0:
                cleaned.append(None)
            else:
                cleaned.append(axis)
        out[name] = NamedSharding(mesh, P(*cleaned))
    return out


# ---------------------------------------------------------------------------
# ZeRO-style parameter / optimizer-state sharding
# ---------------------------------------------------------------------------
#
# The reference gestures at this with an empty DeepSpeed stub
# (training_scripts/deepspeed.py, 0 LoC). The GSPMD equivalent needs no
# runtime machinery: give each parameter leaf a sharded placement over the
# data axis and the optimizer state (same-shaped moments) inherits it, so
# per-device optimizer bytes drop ~n_data-fold. XLA re-gathers shards where
# the computation needs full parameters.


def zero_param_specs(params, mesh: Mesh, axis: str = DATA_AXIS):
    """PartitionSpec tree for ZeRO-style sharding: each leaf's largest
    mesh-divisible dimension is sharded over `axis`; leaves with no
    divisible dimension (scalars, odd shapes) stay replicated."""
    n = mesh.shape[axis]

    def spec_for(leaf):
        shape = getattr(leaf, "shape", ())
        best = None
        for d, s in enumerate(shape):
            if s % n == 0 and s >= n and (best is None or s > shape[best]):
                best = d
        if best is None or n <= 1:
            return P()
        spec = [None] * len(shape)
        spec[best] = axis
        return P(*spec)

    return jax.tree.map(spec_for, params)


def shard_pytree_zero(tree, mesh: Mesh, axis: str = DATA_AXIS):
    """Place a pytree (params, opt_state, or a whole TrainState) with
    ZeRO sharding: array leaves get `zero_param_specs` placements. The
    shape-based rule lands optimizer moments on exactly their parameter's
    sharding (same shapes -> same spec). One batched device_put for the
    whole tree, not a transfer per leaf."""
    shardings = jax.tree.map(
        lambda leaf: NamedSharding(mesh, zero_param_specs(leaf, mesh, axis))
        if hasattr(leaf, "shape") else None,
        tree)
    placed = jax.device_put(
        [l for l in jax.tree.leaves(tree) if hasattr(l, "shape")],
        [s for s in jax.tree.leaves(shardings) if s is not None])
    it = iter(placed)
    return jax.tree.map(
        lambda leaf: next(it) if hasattr(leaf, "shape") else leaf, tree)


def tp_param_specs(params, mesh: Mesh, axis: str = PAIR_J_AXIS):
    """Megatron-style tensor-parallel PartitionSpecs for the model's
    param tree, keyed by layer-name suffix (SURVEY §2.5 "tensor/model
    parallel"; the reference has no TP at all).

    Column-parallel (shard the output features): attention to_q/to_kv/
    gating, the first FF projection, triangle left/right projections —
    each head's / hidden unit's compute lands whole on one device.
    Row-parallel (shard the input features): to_out, the second FF
    projection, triangle proj_out — XLA inserts the one all-reduce at the
    block boundary. Under GSPMD these specs are placement policy only;
    outputs are bit-identical to the replicated run (tests/
    test_sharding.py::TestTensorParallel asserts both).
    """
    n = mesh.shape[axis]

    # The FF entries are anchored to the FeedForward module scope
    # ("ff/", "msa_ff/" — primitives.py FeedForward's flax auto-named
    # Dense_0/Dense_1) so unrelated Dense_0/Dense_1 elsewhere in the tree
    # (head MLPs, structure module) stay replicated by intent, not luck.
    COL = ("to_q/kernel", "to_kv/kernel", "gating/kernel",
           "left_proj/kernel", "right_proj/kernel", "ff/Dense_0/kernel")
    ROW = ("to_out/kernel", "proj_out/kernel", "ff/Dense_1/kernel")
    COL_BIAS = ("gating/bias", "left_proj/bias", "right_proj/bias",
                "ff/Dense_0/bias")

    def spec_for(path, leaf):
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        shape = getattr(leaf, "shape", ())
        if n > 1 and shape:
            if name.endswith(COL) and shape[-1] % n == 0:
                return P(*([None] * (len(shape) - 1) + [axis]))
            if name.endswith(ROW) and len(shape) >= 2 and \
                    shape[-2] % n == 0:
                return P(*([None] * (len(shape) - 2) + [axis, None]))
            if name.endswith(COL_BIAS) and shape[-1] % n == 0:
                return P(*([None] * (len(shape) - 1) + [axis]))
        return P()

    specs = jax.tree_util.tree_map_with_path(spec_for, params)
    if n > 1:
        matched = sum(s != P() for s in jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)))
        if matched == 0:
            import warnings
            warnings.warn(
                "tp_param_specs matched no parameters — the suffix table "
                "no longer lines up with the model's module names, so "
                "tensor parallelism silently degrades to replication",
                stacklevel=2)
    return specs


def shard_pytree_tp(params, mesh: Mesh, axis: str = PAIR_J_AXIS):
    """device_put the param tree with `tp_param_specs` placements."""
    specs = tp_param_specs(params, mesh, axis)
    return jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P)))


def shard_pytree_tp_zero(tree, mesh: Mesh, tp_axis: str = PAIR_J_AXIS,
                         zero_axis: str = DATA_AXIS):
    """Combined placement: tensor-parallel specs where they apply (the
    attention/FF/triangle projection kernels and, via shape-matched
    suffixes, their optimizer moments), ZeRO over the data axis for every
    other array leaf. One batched device_put; non-array leaves pass
    through untouched."""
    tp = tp_param_specs(tree, mesh, tp_axis)
    zero = zero_param_specs(tree, mesh, zero_axis)
    merged = jax.tree.map(
        lambda t, z: t if t != P() else z, tp, zero,
        is_leaf=lambda x: isinstance(x, P))
    specs = jax.tree.leaves(merged, is_leaf=lambda x: isinstance(x, P))
    leaves = jax.tree.leaves(tree)
    assert len(leaves) == len(specs)
    arr = [(l, s) for l, s in zip(leaves, specs) if hasattr(l, "shape")]
    placed = jax.device_put([l for l, _ in arr],
                            [NamedSharding(mesh, s) for _, s in arr])
    it = iter(placed)
    return jax.tree.map(
        lambda leaf: next(it) if hasattr(leaf, "shape") else leaf, tree)


def pytree_bytes_per_device(tree) -> int:
    """Max per-device bytes across the addressable shards of `tree`'s
    array leaves (replicated leaves count fully on every device)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        if not hasattr(leaf, "sharding"):
            continue
        shard_shape = leaf.sharding.shard_shape(leaf.shape)
        n = 1
        for s in shard_shape:
            n *= s
        total += n * leaf.dtype.itemsize
    return total
