"""Sharding rules and in-model constraint helpers.

The model calls `shard_pair` / `shard_msa` / `shard_seq` at block boundaries;
under an active mesh these lower to `with_sharding_constraint`
(GSPMD placement hints), outside a mesh they are no-ops — the same model
code runs single-chip and multi-chip. This replaces the reference's absent
distributed layer (SURVEY.md §2.5, §5.8) without invading model code.

Tensor contracts (axes -> PartitionSpec):
- pair  (b, i, j, d)      -> P(data, i, j, None)
- msa   (b, m, n, d)      -> P(data, None, i, None)
- seq   (b, n, d)         -> P(data, None, None)
- coords(b, n, 3)         -> P(data, None, None)
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alphafold2_tpu.parallel.mesh import DATA_AXIS, PAIR_I_AXIS, PAIR_J_AXIS

_state = threading.local()


def active_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh]):
    """Activate a mesh for model-internal sharding constraints.

    Also enters `jax.set_mesh` so closures under jit see the mesh.
    """
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    try:
        if mesh is not None:
            with jax.set_mesh(mesh):
                yield mesh
        else:
            yield None
    finally:
        _state.mesh = prev


def _constraint(x, spec: P):
    mesh = active_mesh()
    if mesh is None:
        return x
    # drop axis names the mesh doesn't have or can't divide the dim
    cleaned = []
    for dim, axis in zip(x.shape, spec):
        if axis is None or axis not in mesh.axis_names:
            cleaned.append(None)
        elif dim % mesh.shape[axis] != 0:
            cleaned.append(None)
        else:
            cleaned.append(axis)
    # pad spec to rank
    cleaned += [None] * (x.ndim - len(cleaned))
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*cleaned)))


def pair_spec() -> P:
    return P(DATA_AXIS, PAIR_I_AXIS, PAIR_J_AXIS, None)


def msa_spec() -> P:
    return P(DATA_AXIS, None, PAIR_I_AXIS, None)


def seq_spec() -> P:
    return P(DATA_AXIS, None, None)


def shard_pair(x):
    """(b, i, j, d) pair activations: 2-D shard the residue axes."""
    return _constraint(x, pair_spec())


def shard_msa(x):
    """(b, m, n, d) MSA activations: shard the sequence axis."""
    return _constraint(x, msa_spec())


def shard_seq(x):
    """(b, n, d) single-track activations: data-parallel only."""
    return _constraint(x, seq_spec())
