"""Device-mesh construction for multi-chip execution.

The reference's "distributed backend" is empty DeepSpeed/Lightning stubs
implying NCCL (/root/reference/training_scripts/deepspeed.py,
lightning.py — both 0 LoC; install_deepspeed.sh). The TPU-native replacement
is GSPMD: a named `jax.sharding.Mesh` whose collectives XLA emits over
ICI/DCN. No NCCL, no process groups — sharding annotations only.

Axis vocabulary:
- ``data``: batch-parallel axis (DDP analog / ZeRO via sharded opt state);
- ``i``, ``j``: the two residue axes of the O(L^2) pair representation —
  2-D sharding of the pair tensor is the long-context strategy (SURVEY.md
  §5.7): row attention runs local over j-shards, column attention local over
  i-shards, triangle contractions become sharded matmuls XLA partitions with
  all-gathers over the contracting axis.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
PAIR_I_AXIS = "i"
PAIR_J_AXIS = "j"

AXIS_NAMES = (DATA_AXIS, PAIR_I_AXIS, PAIR_J_AXIS)


def make_mesh(
    data: int = 1,
    i: int = 1,
    j: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, i, j) mesh over the given (or all) devices.

    On real hardware, prefer factorizations where `i` x `j` maps to an ICI
    torus face so ring collectives over the sharded pair axes ride ICI.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = data * i * j
    if need != len(devices):
        raise ValueError(
            f"mesh {data}x{i}x{j}={need} != #devices {len(devices)}")
    arr = np.asarray(devices).reshape(data, i, j)
    return Mesh(arr, AXIS_NAMES)


def single_device_mesh() -> Mesh:
    return make_mesh(1, 1, 1, devices=jax.devices()[:1])
