"""Device-mesh construction for multi-chip execution.

The reference's "distributed backend" is empty DeepSpeed/Lightning stubs
implying NCCL (/root/reference/training_scripts/deepspeed.py,
lightning.py — both 0 LoC; install_deepspeed.sh). The TPU-native replacement
is GSPMD: a named `jax.sharding.Mesh` whose collectives XLA emits over
ICI/DCN. No NCCL, no process groups — sharding annotations only.

Axis vocabulary:
- ``data``: batch-parallel axis (DDP analog / ZeRO via sharded opt state);
- ``i``, ``j``: the two residue axes of the O(L^2) pair representation —
  2-D sharding of the pair tensor is the long-context strategy (SURVEY.md
  §5.7): row attention runs local over j-shards, column attention local over
  i-shards, triangle contractions become sharded matmuls XLA partitions with
  all-gathers over the contracting axis.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
PAIR_I_AXIS = "i"
PAIR_J_AXIS = "j"
PIPE_AXIS = "pipe"

AXIS_NAMES = (PIPE_AXIS, DATA_AXIS, PAIR_I_AXIS, PAIR_J_AXIS)


def make_mesh(
    data: int = 1,
    i: int = 1,
    j: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    pipe: int = 1,
) -> Mesh:
    """Build a (pipe, data, i, j) mesh over the given (or all) devices.

    `pipe` is the pipeline-parallel stage axis (parallel/pipeline.py);
    size 1 (the default) makes it inert — every GSPMD spec addresses
    axes by name, so existing (data, i, j) placements are unaffected.
    On real hardware, prefer factorizations where `i` x `j` maps to an
    ICI torus face so ring collectives over the sharded pair axes ride
    ICI, and lay `pipe` along an ICI ring so stage hops are single-hop
    neighbor exchanges.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = pipe * data * i * j
    if need != len(devices):
        raise ValueError(
            f"mesh {pipe}x{data}x{i}x{j}={need} != #devices "
            f"{len(devices)}")
    arr = np.asarray(devices).reshape(pipe, data, i, j)
    return Mesh(arr, AXIS_NAMES)


def single_device_mesh() -> Mesh:
    return make_mesh(1, 1, 1, devices=jax.devices()[:1])
