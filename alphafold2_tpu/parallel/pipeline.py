"""GPipe-style pipeline parallelism over a `pipe` mesh axis.

The last §2.5 parallelism family (SURVEY.md): the reference gestures at
DeepSpeed pipeline stages through its empty `training_scripts/` stubs;
the TPU-native equivalent is a static skew schedule compiled into one
XLA program — no runtime scheduler, no NCCL send/recv threads. Layers
are grouped into S stages; stage s's params live only on mesh ring
position s (1/S of layer memory per device); activations hop stage to
stage over ICI via `ppermute`.

Schedule (classic GPipe, M microbatches, S stages, T = M + S - 1 ticks):

  tick t: every device runs its stage on the activation it holds —
          device s legitimately holds microbatch m = t - s; bubble
          slots compute on zeros and their results are never read —
          then shifts its output to device s+1; device 0 ingests
          microbatch t+1; device S-1 banks microbatch t - (S-1).

All control flow is a `lax.scan` over ticks with `jnp.where` selects —
static shapes, no data-dependent branching, exactly what Mosaic/XLA
want. `ppermute`'s transpose is `ppermute` with the inverse ring, so
the whole pipeline is differentiable and trains under `jax.grad`.

Helpers:
- `stack_stage_params`: S per-stage param trees -> one tree with a
  leading stage axis (shard it P('pipe') so each device keeps 1/S);
- `microbatch` / `unmicrobatch`: split a batch axis into (M, b/M, ...).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from alphafold2_tpu.parallel.mesh import PIPE_AXIS
from alphafold2_tpu.parallel.sharding import shard_map_compat


def make_pipeline_mesh(pipe: int, data: int = 1, devices=None) -> Mesh:
    """A (pipe, data) mesh. On hardware, lay `pipe` along an ICI ring so
    the per-tick `ppermute` is a single-hop neighbor exchange."""
    import numpy as np
    devices = list(devices if devices is not None else jax.devices())
    if pipe * data != len(devices):
        raise ValueError(f"mesh {pipe}x{data} != #devices {len(devices)}")
    return Mesh(np.asarray(devices).reshape(pipe, data),
                (PIPE_AXIS, "data"))


def stack_stage_params(param_trees: Sequence[Any]):
    """[tree_0, ..., tree_{S-1}] (same structure) -> one tree whose
    leaves have a leading stage axis of size S."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *param_trees)


def microbatch(x: jnp.ndarray, n: int) -> jnp.ndarray:
    b = x.shape[0]
    assert b % n == 0, f"batch {b} not divisible into {n} microbatches"
    return x.reshape(n, b // n, *x.shape[1:])


def unmicrobatch(x: jnp.ndarray) -> jnp.ndarray:
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def _tree_where(pred, a, b):
    return jax.tree.map(
        lambda x, y: jnp.where(pred, x, y), a, b)


def pipeline_apply(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    xs: Any,
    mesh: Mesh,
    *,
    axis_name: str = PIPE_AXIS,
    data_axis: Optional[str] = None,
) -> Any:
    """Run `stage_fn` as an S-stage pipeline over microbatched inputs.

    stage_fn: (stage_params, activation_tree) -> activation_tree, the
      SAME function for every stage (stage identity lives in the params,
      e.g. a scanned-layer slice). Activations must keep one shape/dtype
      across stages (true for Evoformer blocks: (x, m) in -> (x, m) out).
    stacked_params: tree with leading stage axis S == mesh.shape[axis].
    xs: activation tree with leading microbatch axis M (every leaf
      (M, ...)).
    data_axis: optional mesh axis to shard the per-microbatch batch dim
      (leaf axis 1) over — composes pp x dp in one shard_map; without it
      every pipe position computes the full microbatch. Falls back to
      replication for leaves whose batch dim does not tile.
    Returns the output tree (M, ...), sharded like the inputs.
    """
    s_count = mesh.shape[axis_name]
    m_count = jax.tree.leaves(xs)[0].shape[0]
    ticks = m_count + s_count - 1

    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)

    def x_spec(leaf):
        if data_axis is not None and data_axis in mesh.axis_names and \
                leaf.ndim >= 2 and leaf.shape[1] % mesh.shape[data_axis] == 0:
            return P(None, data_axis)
        return P()

    x_specs = jax.tree.map(x_spec, xs)

    def spmd(params_local, xs):
        # shard_map hands each device its (1, ...) stage slice
        params_local = jax.tree.map(lambda p: p[0], params_local)
        idx = jax.lax.axis_index(axis_name)
        zero = jax.tree.map(lambda x: jnp.zeros_like(x[0]), xs)
        state0 = _tree_where(idx == 0,
                             jax.tree.map(lambda x: x[0], xs), zero)
        # the carry becomes device-varying after the first tick; mark the
        # init values as varying over the pipe axis so scan's carry types
        # line up (jax>=0.8 shard_map vma typing; older jax has no vma
        # types — and no pcast — so the marking is a no-op there)
        pcast = getattr(jax.lax, "pcast", None)
        mark = (lambda x: pcast(jnp.zeros_like(x), (axis_name,),
                                to="varying")) if pcast is not None \
            else jnp.zeros_like
        outputs0 = jax.tree.map(mark, xs)
        ring = [(s, (s + 1) % s_count) for s in range(s_count)]

        def tick(carry, t):
            state, outputs = carry
            y = stage_fn(params_local, state)
            # bank the finished microbatch (last stage only)
            out_t = t - (s_count - 1)
            safe = jnp.clip(out_t, 0, m_count - 1)
            write = (idx == s_count - 1) & (out_t >= 0)
            outputs = jax.tree.map(
                lambda o, v: o.at[safe].set(
                    jnp.where(write, v, o[safe])), outputs, y)
            # hop to the next stage; stage 0 ingests the next microbatch
            shifted = jax.tree.map(
                lambda v: jax.lax.ppermute(v, axis_name, ring), y)
            nxt = jnp.clip(t + 1, 0, m_count - 1)
            state = _tree_where(
                idx == 0, jax.tree.map(lambda x: x[nxt], xs), shifted)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (state0, outputs0), jnp.arange(ticks))
        # results live on the last ring position; replicate them. psum in
        # f32: XLA:CPU's AllReducePromotion pass crashes cloning bf16
        # all-reduces that reach it from the partial-auto lowering
        # ("Invalid binary instruction opcode copy", observed r05), and
        # a bf16 sum-of-one-nonzero loses nothing by running wider.
        def _replicate(o):
            of = o.astype(jnp.float32) if o.dtype == jnp.bfloat16 else o
            r = jax.lax.psum(
                jnp.where(idx == s_count - 1, of, jnp.zeros_like(of)),
                axis_name)
            return r.astype(o.dtype)

        outputs = jax.tree.map(_replicate, outputs)
        return outputs

    # Manual only over the pipe (and data) axes: any other mesh axes
    # (the pair tensor's `i`/`j`) stay AUTO, so GSPMD keeps honoring
    # in-stage `with_sharding_constraint`s — pipeline parallelism
    # composes with the 2-D pair sharding instead of collapsing it
    # (VERDICT r4 #4).
    manual = {axis_name}
    if data_axis is not None and data_axis in mesh.axis_names:
        manual.add(data_axis)
    fn = shard_map_compat(spmd, mesh, (param_specs, x_specs), x_specs,
                          manual_axes=frozenset(manual))
    return fn(stacked_params, xs)
