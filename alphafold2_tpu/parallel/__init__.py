from alphafold2_tpu.parallel.mesh import (  # noqa: F401
    AXIS_NAMES,
    DATA_AXIS,
    PAIR_I_AXIS,
    PAIR_J_AXIS,
    make_mesh,
    single_device_mesh,
)
from alphafold2_tpu.parallel.sharding import (  # noqa: F401
    active_mesh,
    fold_input_shardings,
    fold_input_specs,
    msa_spec,
    pair_spec,
    pytree_bytes_per_device,
    seq_spec,
    shard_msa,
    shard_pair,
    shard_pytree_tp_zero,
    shard_pytree_zero,
    shard_seq,
    tp_param_specs,
    use_mesh,
    zero_param_specs,
)
from alphafold2_tpu.parallel.pipeline import (  # noqa: F401
    make_pipeline_mesh,
    microbatch,
    pipeline_apply,
    stack_stage_params,
    unmicrobatch,
)
