"""Pretrained-embedding wrappers: ESM-1b, MSA-Transformer, ProtTrans.

Parity with the reference wrapper layer
(/root/reference/alphafold2_pytorch/embeds.py:10-103) and its extractor
helpers (utils.py:255-390): wrap an Alphafold2 model so sequences/MSAs are
first embedded by a frozen pretrained protein LM, the embeddings projected
to model dim and injected as `seq_embed` / `msa_embed`.

Host/TPU split (TPU-first design): the frozen torch LMs run host-side on
CPU out of the XLA graph (they are preprocessing, not training state);
only the resulting arrays cross to the device. All hub/HF loads are lazy
and gated — in an offline container construction raises a clear error
instead of failing at import time.
"""

from __future__ import annotations


import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.data.featurize import detokenize


def _lazy_torch():
    import torch  # local import: torch is host-side only here
    return torch


class _PretrainedWrapper:
    """Common scaffolding: wraps (model, params) of an Alphafold2 and adds
    embed injection. Subclasses define _load() and _embed()."""

    def __init__(self, alphafold2, params=None):
        self.alphafold2 = alphafold2
        self.params = params
        self._backend = None

    def _ensure_loaded(self):
        if self._backend is None:
            try:
                self._backend = self._load()
            except Exception as exc:  # pragma: no cover - env dependent
                raise RuntimeError(
                    f"{type(self).__name__} needs its pretrained weights "
                    f"(download failed or package missing): {exc}") from exc
        return self._backend

    def _embed_tokens(self, tokens_2d) -> np.ndarray:
        """(rows, L) int tokens -> (rows, L, lm_dim) embeddings."""
        raise NotImplementedError

    def embed_batch(self, seq, msa=None):
        """Returns (seq_embed, msa_embed) numpy arrays at LM dims.

        Default: embed the sequence directly and the MSA row-by-row
        (flattened through `_embed_tokens`); MSAEmbedWrapper overrides
        this wholesale because the MSA transformer embeds the whole
        alignment jointly."""
        seq_embed = self._embed_tokens(np.asarray(seq))
        msa_embed = None
        if msa is not None:
            m = np.asarray(msa)
            flat = m.reshape(-1, m.shape[-1])
            msa_embed = self._embed_tokens(flat).reshape(*m.shape, -1)
        return seq_embed, msa_embed

    def __call__(self, params=None, seq=None, msa=None, **kwargs):
        if params is None:
            params = self.params
        seq_embed, msa_embed = self.embed_batch(seq, msa)
        return self.alphafold2.apply(params, seq, msa=msa,
                                     seq_embed=seq_embed,
                                     msa_embed=msa_embed, **kwargs)


class ESMEmbedWrapper(_PretrainedWrapper):
    """ESM-1b per-token embeddings (reference embeds.py:77-103,
    utils.py:331-352; layer-33 representations, 1280-d)."""

    REPR_LAYER = 33

    def _load(self):
        torch = _lazy_torch()
        model, alphabet = torch.hub.load(*constants.ESM_MODEL_PATH)
        batch_converter = alphabet.get_batch_converter()
        model.eval()
        return model, batch_converter

    def _embed_tokens(self, tokens_2d) -> np.ndarray:
        torch = _lazy_torch()
        model, batch_converter = self._ensure_loaded()
        data = [(f"s{i}", detokenize(row).replace("_", "<pad>"))
                for i, row in enumerate(np.asarray(tokens_2d))]
        _, _, toks = batch_converter(data)
        with torch.no_grad():
            out = model(toks, repr_layers=[self.REPR_LAYER],
                        return_contacts=False)
        reps = out["representations"][self.REPR_LAYER]
        return reps[:, 1:1 + tokens_2d.shape[-1]].cpu().numpy()


class MSAEmbedWrapper(_PretrainedWrapper):
    """MSA-Transformer row embeddings (reference embeds.py:33-75,
    utils.py:308-329; esm_msa1 layer-12, 768-d)."""

    REPR_LAYER = 12

    def _load(self):
        torch = _lazy_torch()
        model, alphabet = torch.hub.load(*constants.MSA_MODEL_PATH)
        model.eval()
        return model, alphabet.get_batch_converter()

    def embed_batch(self, seq, msa=None):
        torch = _lazy_torch()
        model, batch_converter = self._ensure_loaded()
        assert msa is not None, "MSAEmbedWrapper needs an MSA"
        m = np.asarray(msa)
        embeds = []
        for b in range(m.shape[0]):
            data = [(f"r{r}", detokenize(m[b, r]).replace("_", "-"))
                    for r in range(m.shape[1])]
            # esm_msa1's MSABatchConverter already returns (1, R, L+1)
            _, _, toks = batch_converter(data)
            with torch.no_grad():
                out = model(toks, repr_layers=[self.REPR_LAYER])
            reps = out["representations"][self.REPR_LAYER]
            embeds.append(reps[0, :, 1:1 + m.shape[-1]].cpu().numpy())
        msa_embed = np.stack(embeds)
        # first MSA row doubles as the sequence embedding (reference
        # embeds.py:70-73 passes msa_embed and the model adds the seq row)
        return msa_embed[:, 0], msa_embed


class ProtT5EmbedWrapper(_PretrainedWrapper):
    """ProtT5-XL-U50 embeddings via HuggingFace (reference
    utils.py:355-390 get_t5_embedd; 1024-d = constants.NUM_EMBEDDS_T5).

    Unlike BERT-style models there is no leading CLS token: the encoder
    output aligns with residue 0 directly and only the trailing ``</s>``
    must be dropped (the reference's ``shift_left, shift_right = 0, -1``).
    """

    def _load(self):
        from transformers import T5EncoderModel, T5Tokenizer
        name = "Rostlab/prot_t5_xl_uniref50"
        return (T5EncoderModel.from_pretrained(name),
                T5Tokenizer.from_pretrained(name, do_lower_case=False))

    def _embed_tokens(self, tokens_2d) -> np.ndarray:
        torch = _lazy_torch()
        model, tokenizer = self._ensure_loaded()
        texts = [" ".join(detokenize(row).replace("_", "X"))
                 for row in np.asarray(tokens_2d)]
        enc = tokenizer.batch_encode_plus(texts, add_special_tokens=True,
                                          padding=True, return_tensors="pt")
        with torch.no_grad():
            out = model(input_ids=enc["input_ids"],
                        attention_mask=enc["attention_mask"])
        reps = out.last_hidden_state
        return reps[:, :tokens_2d.shape[-1]].float().cpu().numpy()


class ProtTranEmbedWrapper(_PretrainedWrapper):
    """ProtBERT embeddings via HuggingFace (reference embeds.py:10-31,
    utils.py:295-306; 1024-d)."""

    def _load(self):
        from transformers import AutoModel, AutoTokenizer
        name = "Rostlab/prot_bert"
        return (AutoModel.from_pretrained(name),
                AutoTokenizer.from_pretrained(name))

    def _embed_tokens(self, tokens_2d) -> np.ndarray:
        torch = _lazy_torch()
        model, tokenizer = self._ensure_loaded()
        texts = [" ".join(detokenize(row).replace("_", "X"))
                 for row in np.asarray(tokens_2d)]
        enc = tokenizer(texts, return_tensors="pt", padding=True)
        with torch.no_grad():
            out = model(**enc).last_hidden_state
        return out[:, 1:1 + tokens_2d.shape[-1]].cpu().numpy()
