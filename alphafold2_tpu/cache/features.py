"""Feature store: the cache tier UPSTREAM of the fold cache.

At serving scale the CPU-side feature work (tokenize, MSA prep,
feature construction — in a real deployment, the MSA search itself) is
the dominant cost (ParaFold), and it is pure in the raw input: the same
sequence + raw MSA featurizes to the same arrays no matter which fold
config, model tag, or recycle count consumes them. So features get
their own content-addressed tier keyed by `cache.keys.feature_key` —
one entry serves every downstream fold variant, and feature traffic
dedups independently of fold traffic.

Same architecture and trust model as the fold-result store
(`cache/store.py`) — literally: both re-base on the ONE generic
byte-budgeted store (`cache.bytestore.ByteStore`, ISSUE 13),
parameterized here on `encode_features`/`decode_features`; anything
wrong with a disk entry is a MISS and the file is quarantined
(`*.quarantined`), never raised into the serving path. No peer tier —
features are cheap to recompute relative to a network hop for token
arrays (revisit when real MSA search lands; the seam is
`FeatureCache.get/put`, same as FoldCache's — and the shared store
means spill tiers land in ONE place when they do).

`serve.features.FeaturePool` wires this into the serving path; it is
equally usable standalone for offline featurize memoization.
"""

from __future__ import annotations

import io
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from alphafold2_tpu.cache.bytestore import ByteStore
from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE


@dataclass
class FeaturizedInput:
    """One featurized raw job: the arrays `serve.FoldRequest` consumes.
    Always exact-length (unpadded) copies — padding/bucketing stays the
    fold scheduler's job."""

    seq: np.ndarray                       # (n,) int32 tokens
    msa: Optional[np.ndarray] = None      # (m, n) int32 tokens

    @property
    def nbytes(self) -> int:
        return int(self.seq.nbytes
                   + (0 if self.msa is None else self.msa.nbytes))


def encode_features(key: str, value: FeaturizedInput) -> bytes:
    """One featurized input as self-identifying npz bytes — the disk
    format, validated on read with the same `decode_features` every
    tier shares (mirrors cache.store.encode_fold)."""
    buf = io.BytesIO()
    arrays = {"seq": np.asarray(value.seq, np.int32),
              "key": np.frombuffer(key.encode("utf-8"), np.uint8)}
    if value.msa is not None:
        arrays["msa"] = np.asarray(value.msa, np.int32)
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_features(key: str, data: bytes) -> FeaturizedInput:
    """Parse + validate `encode_features` bytes. Raises on anything
    wrong (unreadable, key mismatch, shape nonsense); callers translate
    that into miss/quarantine semantics."""
    with np.load(io.BytesIO(data)) as z:
        stored_key = bytes(z["key"]).decode("utf-8")
        value = FeaturizedInput(
            seq=np.asarray(z["seq"], np.int32),
            msa=(np.asarray(z["msa"], np.int32)
                 if "msa" in z.files else None))
    if (stored_key != key or value.seq.ndim != 1
            or value.seq.shape[0] == 0
            or (value.msa is not None
                and (value.msa.ndim != 2
                     or value.msa.shape[1] != value.seq.shape[0]))):
        raise ValueError(f"feature entry {key} fails validation")
    return value


class FeatureCache:
    """Content-addressed featurized-input cache (memory LRU + disk).

    The memory/disk/quarantine machinery is `cache.bytestore.ByteStore`
    parameterized on `encode_features`/`decode_features` (ISSUE 13:
    ONE copy, shared with `cache.store.FoldCache`); this class owns the
    feature-specific counters and trace events.

    max_bytes / max_entries bound the memory tier; the disk tier is
    bounded by TTL (and the directory's owner). ttl_s=None disables
    expiry. `clock` is injectable for tests. Outcome counters mirror
    into the process registry as `feature_cache_events_total{event=}` —
    a distinct series from the fold store's `fold_cache_events_total`,
    because the two tiers' hit ratios answer different capacity
    questions (feature-pool sizing vs accelerator sizing).
    """

    def __init__(self, max_bytes: int = 64 << 20, max_entries: int = 8192,
                 ttl_s: Optional[float] = None,
                 disk_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.time,
                 registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.expirations = 0
        self.disk_hits = 0
        self.disk_errors = 0
        self._m_events = (registry or get_registry()).counter(
            "feature_cache_events_total",
            "feature-store outcomes across all FeatureCache instances",
            ("event",))
        self._store = ByteStore(
            encode=encode_features, decode=decode_features,
            max_bytes=max_bytes, max_entries=max_entries, ttl_s=ttl_s,
            disk_dir=disk_dir, clock=clock, on_event=self._bump,
            quarantine_event="feature_quarantine")

    def _bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
        self._m_events.inc(n, event=field)

    @property
    def max_bytes(self) -> int:
        return self._store.max_bytes

    @property
    def max_entries(self) -> int:
        return self._store.max_entries

    @property
    def ttl_s(self) -> Optional[float]:
        return self._store.ttl_s

    @property
    def disk_dir(self) -> Optional[str]:
        return self._store.disk_dir

    # -- tier internals (delegated; names kept for tests/tooling) --------

    def _mem_get(self, key: str) -> Optional[FeaturizedInput]:
        return self._store.mem_get(key)

    def _mem_put(self, key: str, value: FeaturizedInput,
                 expires_at: Optional[float] = None):
        self._store.mem_put(key, value, expires_at=expires_at)

    def _path(self, key: str) -> str:
        return self._store.path(key)

    def _quarantine(self, path: str, key: str, trace=NULL_TRACE):
        self._store.quarantine(path, key, trace)

    def _disk_get(self, key: str, trace=NULL_TRACE):
        """Returns (value, expires_at) or None."""
        return self._store.disk_get(key, trace)

    def _disk_put(self, key: str, value: FeaturizedInput):
        self._store.disk_put(key, value)

    # -- public API ------------------------------------------------------

    def get(self, key: str, trace=NULL_TRACE) -> Optional[FeaturizedInput]:
        """Lookup; never raises. memory -> disk, disk hits promoted."""
        hit = self._store.lookup(key, trace)
        if hit is None:
            self._bump("misses")
            trace.event("feature_miss")
            return None
        value, tier = hit
        if tier == "disk":
            self._bump("disk_hits")
        self._bump("hits")
        trace.event("feature_hit", tier=tier)
        return value

    def put(self, key: str, seq, msa=None) -> FeaturizedInput:
        """Store one featurized input (copies taken; never raises past
        the disk-error counter)."""
        value = FeaturizedInput(
            seq=np.array(seq, np.int32, copy=True),
            msa=None if msa is None else np.array(msa, np.int32,
                                                  copy=True))
        self._bump("puts")
        self._mem_put(key, value)
        if self.disk_dir:
            self._disk_put(key, value)
        return value

    # -- views -----------------------------------------------------------

    @property
    def bytes_resident(self) -> int:
        return self._store.bytes_resident

    def __len__(self) -> int:
        return len(self._store)

    @property
    def hit_ratio(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            out = {f: getattr(self, f)
                   for f in ("hits", "misses", "puts", "evictions",
                             "expirations", "disk_hits", "disk_errors")}
        out["entries_resident"] = len(self._store)
        out["bytes_resident"] = self._store.bytes_resident
        total = out["hits"] + out["misses"]
        out["hit_ratio"] = out["hits"] / total if total else 0.0
        out["max_bytes"] = self.max_bytes
        out["max_entries"] = self.max_entries
        out["ttl_s"] = self.ttl_s
        out["disk_dir"] = self.disk_dir
        return out
