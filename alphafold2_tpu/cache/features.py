"""Feature store: the cache tier UPSTREAM of the fold cache.

At serving scale the CPU-side feature work (tokenize, MSA prep,
feature construction — in a real deployment, the MSA search itself) is
the dominant cost (ParaFold), and it is pure in the raw input: the same
sequence + raw MSA featurizes to the same arrays no matter which fold
config, model tag, or recycle count consumes them. So features get
their own content-addressed tier keyed by `cache.keys.feature_key` —
one entry serves every downstream fold variant, and feature traffic
dedups independently of fold traffic.

Same architecture and trust model as the fold-result store
(`cache/store.py`): byte-budgeted memory LRU over an optional
atomic-write on-disk `.npz` tier; anything wrong with a disk entry is a
MISS and the file is quarantined (`*.quarantined`), never raised into
the serving path. No peer tier — features are cheap to recompute
relative to a network hop for token arrays (revisit when real MSA
search lands; the seam is `FeatureCache.get/put`, same as FoldCache's).

`serve.features.FeaturePool` wires this into the serving path; it is
equally usable standalone for offline featurize memoization.
"""

from __future__ import annotations

import io
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE

_QUARANTINE_SUFFIX = ".quarantined"


@dataclass
class FeaturizedInput:
    """One featurized raw job: the arrays `serve.FoldRequest` consumes.
    Always exact-length (unpadded) copies — padding/bucketing stays the
    fold scheduler's job."""

    seq: np.ndarray                       # (n,) int32 tokens
    msa: Optional[np.ndarray] = None      # (m, n) int32 tokens

    @property
    def nbytes(self) -> int:
        return int(self.seq.nbytes
                   + (0 if self.msa is None else self.msa.nbytes))


def encode_features(key: str, value: FeaturizedInput) -> bytes:
    """One featurized input as self-identifying npz bytes — the disk
    format, validated on read with the same `decode_features` every
    tier shares (mirrors cache.store.encode_fold)."""
    buf = io.BytesIO()
    arrays = {"seq": np.asarray(value.seq, np.int32),
              "key": np.frombuffer(key.encode("utf-8"), np.uint8)}
    if value.msa is not None:
        arrays["msa"] = np.asarray(value.msa, np.int32)
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_features(key: str, data: bytes) -> FeaturizedInput:
    """Parse + validate `encode_features` bytes. Raises on anything
    wrong (unreadable, key mismatch, shape nonsense); callers translate
    that into miss/quarantine semantics."""
    with np.load(io.BytesIO(data)) as z:
        stored_key = bytes(z["key"]).decode("utf-8")
        value = FeaturizedInput(
            seq=np.asarray(z["seq"], np.int32),
            msa=(np.asarray(z["msa"], np.int32)
                 if "msa" in z.files else None))
    if (stored_key != key or value.seq.ndim != 1
            or value.seq.shape[0] == 0
            or (value.msa is not None
                and (value.msa.ndim != 2
                     or value.msa.shape[1] != value.seq.shape[0]))):
        raise ValueError(f"feature entry {key} fails validation")
    return value


class _Entry:
    __slots__ = ("value", "expires_at")

    def __init__(self, value: FeaturizedInput,
                 expires_at: Optional[float]):
        self.value = value
        self.expires_at = expires_at


class FeatureCache:
    """Content-addressed featurized-input cache (memory LRU + disk).

    max_bytes / max_entries bound the memory tier; the disk tier is
    bounded by TTL (and the directory's owner). ttl_s=None disables
    expiry. `clock` is injectable for tests. Outcome counters mirror
    into the process registry as `feature_cache_events_total{event=}` —
    a distinct series from the fold store's `fold_cache_events_total`,
    because the two tiers' hit ratios answer different capacity
    questions (feature-pool sizing vs accelerator sizing).
    """

    def __init__(self, max_bytes: int = 64 << 20, max_entries: int = 8192,
                 ttl_s: Optional[float] = None,
                 disk_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.time,
                 registry: Optional[MetricsRegistry] = None):
        if max_bytes < 0 or max_entries < 0:
            raise ValueError("max_bytes and max_entries must be >= 0")
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self.ttl_s = ttl_s
        self.disk_dir = disk_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._mem: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.expirations = 0
        self.disk_hits = 0
        self.disk_errors = 0
        self._m_events = (registry or get_registry()).counter(
            "feature_cache_events_total",
            "feature-store outcomes across all FeatureCache instances",
            ("event",))
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)

    def _bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
        self._m_events.inc(n, event=field)

    # -- memory tier -----------------------------------------------------

    def _mem_get(self, key: str) -> Optional[FeaturizedInput]:
        now = self._clock()
        with self._lock:
            entry = self._mem.get(key)
            if entry is None:
                return None
            if entry.expires_at is not None and now >= entry.expires_at:
                del self._mem[key]
                self._bytes -= entry.value.nbytes
                self.expirations += 1
                return None
            self._mem.move_to_end(key)
            return entry.value

    def _mem_put(self, key: str, value: FeaturizedInput,
                 expires_at: Optional[float] = None):
        """expires_at overrides the fresh-write TTL — disk promotions
        pass the ORIGINAL write time's expiry (same tier-bounce rule as
        FoldCache._mem_put)."""
        if self.max_entries == 0 or self.max_bytes == 0:
            return
        if expires_at is None:
            expires_at = (None if self.ttl_s is None
                          else self._clock() + self.ttl_s)
        with self._lock:
            old = self._mem.pop(key, None)
            if old is not None:
                self._bytes -= old.value.nbytes
            self._mem[key] = _Entry(value, expires_at)
            self._bytes += value.nbytes
            while self._mem and (len(self._mem) > self.max_entries
                                 or self._bytes > self.max_bytes):
                _, evicted = self._mem.popitem(last=False)
                self._bytes -= evicted.value.nbytes
                self.evictions += 1

    # -- disk tier -------------------------------------------------------

    def _path(self, key: str) -> str:
        return os.path.join(self.disk_dir, key[:2], f"{key}.npz")

    def _quarantine(self, path: str, key: str, trace=NULL_TRACE):
        self._bump("disk_errors")
        trace.event("feature_quarantine")
        with self._lock:
            entry = self._mem.pop(key, None)
            if entry is not None:
                self._bytes -= entry.value.nbytes
        try:
            os.replace(path, path + _QUARANTINE_SUFFIX)
        except OSError:
            pass                       # racing quarantiners: either wins

    def _disk_get(self, key: str, trace=NULL_TRACE):
        """Returns (value, expires_at) or None."""
        path = self._path(key)
        try:
            if not os.path.exists(path):
                return None
            expires_at = None
            if self.ttl_s is not None:
                expires_at = os.path.getmtime(path) + self.ttl_s
                if self._clock() >= expires_at:
                    self._bump("expirations")
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    return None
        except OSError:
            return None
        try:
            with open(path, "rb") as fh:
                data = fh.read()
            value = decode_features(key, data)
        except Exception:              # unreadable/garbage/wrong entry
            self._quarantine(path, key, trace)
            return None
        return value, expires_at

    def _disk_put(self, key: str, value: FeaturizedInput):
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as fh:
                fh.write(encode_features(key, value))
            os.replace(tmp, path)      # atomic: readers see old or new
        except Exception:
            self._bump("disk_errors")
            try:
                os.remove(tmp)
            except OSError:
                pass

    # -- public API ------------------------------------------------------

    def get(self, key: str, trace=NULL_TRACE) -> Optional[FeaturizedInput]:
        """Lookup; never raises. memory -> disk, disk hits promoted."""
        value = self._mem_get(key)
        tier = "memory"
        if value is None and self.disk_dir:
            hit = self._disk_get(key, trace)
            if hit is not None:
                value, expires_at = hit
                tier = "disk"
                self._bump("disk_hits")
                self._mem_put(key, value, expires_at=expires_at)
        if value is None:
            self._bump("misses")
            trace.event("feature_miss")
            return None
        self._bump("hits")
        trace.event("feature_hit", tier=tier)
        return value

    def put(self, key: str, seq, msa=None) -> FeaturizedInput:
        """Store one featurized input (copies taken; never raises past
        the disk-error counter)."""
        value = FeaturizedInput(
            seq=np.array(seq, np.int32, copy=True),
            msa=None if msa is None else np.array(msa, np.int32,
                                                  copy=True))
        self._bump("puts")
        self._mem_put(key, value)
        if self.disk_dir:
            self._disk_put(key, value)
        return value

    # -- views -----------------------------------------------------------

    @property
    def bytes_resident(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    @property
    def hit_ratio(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            out = {f: getattr(self, f)
                   for f in ("hits", "misses", "puts", "evictions",
                             "expirations", "disk_hits", "disk_errors")}
            out["entries_resident"] = len(self._mem)
            out["bytes_resident"] = self._bytes
        total = out["hits"] + out["misses"]
        out["hit_ratio"] = out["hits"] / total if total else 0.0
        out["max_bytes"] = self.max_bytes
        out["max_entries"] = self.max_entries
        out["ttl_s"] = self.ttl_s
        out["disk_dir"] = self.disk_dir
        return out
