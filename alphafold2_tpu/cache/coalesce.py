"""In-flight request coalescing: one computation, N waiters.

A cache only deduplicates work that has *finished*; under a batch
window the duplicates arrive while the first copy is still queued or on
the accelerator, and a plain cache would fold all of them. The registry
closes that gap: the first submission of a key becomes the LEADER (it
enqueues and folds normally), every later submission of the same key
while the leader is outstanding becomes a FOLLOWER — recorded here,
never enqueued, resolved when the leader settles.

Settlement is unconditional: whatever happens to the leader (result,
executor error, deadline shed, cancellation, worker crash) the owner
MUST call `settle(key)` exactly once and resolve every returned
follower — including failure propagation, because a follower that
attached to a leader that then errored must see that error, not hang.
The registry stores opaque follower objects and never touches them;
policy (what response a follower gets) stays with the owner — including
follower-deadline policy: `evict_followers(predicate)` lets the owner
pull out parked followers whose own deadline expired and shed them with
their own terminal state instead of inheriting the leader's timing.

Settlement is not the only exit for a leader: a leader that is SHED
(deadline expired while queued) or rejected at submit never produced a
result, but its followers may still be viable — error-resolving the
whole group would turn one dead request into N. `promote(key, pick)`
instead crowns a surviving follower (the owner's `pick` chooses; the
scheduler picks the tightest deadline — it has the least slack to
re-queue) as the new leader: it leaves the parked set, the remaining
followers stay attached under it, and a later settle() of the key fans
out from the new leader. Promotions are counted (`leader_promotions`
in `snapshot()`, `coalesce_leader_promotions_total` in the metrics
registry).

`attach` also records the leader object, so a follower's request trace
can link to the leader's trace (`attach_with_leader`). Lifetime
counters mirror into the process metrics registry
(`coalesce_leaders_total` / `coalesce_followers_total`).

Thread-safe; attach/settle are O(1) dict ops under one lock, safe on
the submit hot path.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry


class InflightRegistry:
    """Tracks keys with work in flight and the followers awaiting them."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self._followers: Dict[str, List[Any]] = {}
        self._leader_objs: Dict[str, Any] = {}
        self.leaders = 0               # lifetime counters, lock-guarded
        self.coalesced = 0
        self.leader_promotions = 0
        reg = registry or get_registry()
        self._m_leaders = reg.counter(
            "coalesce_leaders_total", "keys that started an in-flight fold")
        self._m_followers = reg.counter(
            "coalesce_followers_total",
            "submissions parked behind an in-flight leader")
        self._m_promotions = reg.counter(
            "coalesce_leader_promotions_total",
            "followers promoted to leader after their leader was shed "
            "or rejected")

    def attach(self, key: str, follower: Any) -> bool:
        """Returns True if the caller is the leader for `key` (it must do
        the work and later settle); False if `follower` was recorded
        behind an existing leader."""
        return self.attach_with_leader(key, follower)[0]

    def attach_with_leader(self, key: str, follower: Any,
                           on_follower: Optional[
                               Callable[[Any], None]] = None,
                           ) -> Tuple[bool, Optional[Any]]:
        """attach(), but also returns the current leader object (None
        when the caller just became it). `on_follower(leader)` runs
        UNDER the registry lock when the caller was recorded as a
        follower — settle()/evict_followers() cannot interleave, so
        follower bookkeeping (e.g. linking its trace to the leader's)
        is guaranteed to land before any settlement can resolve it.
        Keep the callback O(1); it sits on the submit hot path."""
        with self._lock:
            waiting = self._followers.get(key)
            if waiting is None:
                self._followers[key] = []
                self._leader_objs[key] = follower
                self.leaders += 1
                leader = None
                is_leader = True
            else:
                leader = self._record_follower_locked(key, waiting,
                                                      follower,
                                                      on_follower)
                is_leader = False
        if is_leader:
            self._m_leaders.inc()
        else:
            self._m_followers.inc()
        return is_leader, leader

    def _record_follower_locked(self, key, waiting, follower,
                                on_follower):
        """Caller holds self._lock and verified `waiting` exists: the
        ONE copy of follower-attach bookkeeping, shared by
        attach_with_leader and attach_follower so their accounting
        cannot drift."""
        leader = self._leader_objs.get(key)
        if on_follower is not None:
            on_follower(leader)
        waiting.append(follower)
        self.coalesced += 1
        return leader

    def attach_follower(self, key: str, follower: Any,
                        on_follower: Optional[
                            Callable[[Any], None]] = None) -> bool:
        """Attach ONLY when `key` already has an in-flight leader:
        True = recorded as a follower (on_follower ran under the lock,
        same contract as attach_with_leader), False = no leader, the
        follower was NOT recorded and the caller keeps full ownership.
        This is the cache-aware admission primitive (ISSUE 9): a
        duplicate of in-flight work costs ~0 to serve, so the scheduler
        admits it past a "full" queue — but only as a follower; it must
        never become a leader that enqueues real work the queue bound
        just refused."""
        with self._lock:
            waiting = self._followers.get(key)
            if waiting is None:
                return False
            self._record_follower_locked(key, waiting, follower,
                                         on_follower)
        self._m_followers.inc()
        return True

    def settle(self, key: str) -> List[Any]:
        """Close out `key`: the leader's work reached a terminal state
        (success OR failure). Returns the followers to resolve; after
        this, the next attach of `key` starts a fresh leader."""
        with self._lock:
            self._leader_objs.pop(key, None)
            return self._followers.pop(key, [])

    def promote(self, key: str,
                pick: Callable[[List[Any]], Any]) -> Optional[Any]:
        """The leader of `key` dropped out WITHOUT reaching a terminal
        result (shed while queued, rejected at submit): crown one of
        its parked followers instead of dissolving the group.

        `pick(followers)` chooses from the non-empty parked list (the
        scheduler picks the tightest deadline) and must return one of
        its elements. The chosen follower is removed from the parked
        set, recorded as the key's leader object (later attachers link
        to ITS trace), and returned — the caller owns re-enqueueing it.
        Returns None when no followers are parked; the key is then
        fully cleared (equivalent to settle() of an empty group) and
        the next attach starts fresh."""
        with self._lock:
            waiting = self._followers.get(key)
            if not waiting:
                self._followers.pop(key, None)
                self._leader_objs.pop(key, None)
                return None
            new_leader = pick(waiting)
            waiting.remove(new_leader)
            self._leader_objs[key] = new_leader
            self.leader_promotions += 1
        self._m_promotions.inc()
        return new_leader

    def evict_followers(self,
                        predicate: Callable[[Any], bool]) -> List[Any]:
        """Remove and return every parked follower matching `predicate`
        (e.g. its own deadline expired while the leader is still in
        flight). The evicted followers no longer count in `waiting()`
        and will NOT be returned by a later settle() — the caller owns
        resolving them."""
        evicted: List[Any] = []
        with self._lock:
            for key, waiting in self._followers.items():
                if not waiting:
                    continue
                keep = []
                for f in waiting:
                    (evicted if predicate(f) else keep).append(f)
                self._followers[key] = keep
        return evicted

    def inflight(self) -> int:
        with self._lock:
            return len(self._followers)

    def waiting(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._followers.values())

    def snapshot(self) -> dict:
        with self._lock:
            return {"inflight_keys": len(self._followers),
                    "waiting_followers":
                        sum(len(v) for v in self._followers.values()),
                    "leaders": self.leaders,
                    "coalesced": self.coalesced,
                    "leader_promotions": self.leader_promotions}
