"""In-flight request coalescing: one computation, N waiters.

A cache only deduplicates work that has *finished*; under a batch
window the duplicates arrive while the first copy is still queued or on
the accelerator, and a plain cache would fold all of them. The registry
closes that gap: the first submission of a key becomes the LEADER (it
enqueues and folds normally), every later submission of the same key
while the leader is outstanding becomes a FOLLOWER — recorded here,
never enqueued, resolved when the leader settles.

Settlement is unconditional: whatever happens to the leader (result,
executor error, deadline shed, cancellation, worker crash) the owner
MUST call `settle(key)` exactly once and resolve every returned
follower — including failure propagation, because a follower that
attached to a leader that then errored must see that error, not hang.
The registry stores opaque follower objects and never touches them;
policy (what response a follower gets) stays with the owner.

Thread-safe; attach/settle are O(1) dict ops under one lock, safe on
the submit hot path.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List


class InflightRegistry:
    """Tracks keys with work in flight and the followers awaiting them."""

    def __init__(self):
        self._lock = threading.Lock()
        self._followers: Dict[str, List[Any]] = {}
        self.leaders = 0               # lifetime counters, lock-guarded
        self.coalesced = 0

    def attach(self, key: str, follower: Any) -> bool:
        """Returns True if the caller is the leader for `key` (it must do
        the work and later settle); False if `follower` was recorded
        behind an existing leader."""
        with self._lock:
            waiting = self._followers.get(key)
            if waiting is None:
                self._followers[key] = []
                self.leaders += 1
                return True
            waiting.append(follower)
            self.coalesced += 1
            return False

    def settle(self, key: str) -> List[Any]:
        """Close out `key`: the leader's work reached a terminal state
        (success OR failure). Returns the followers to resolve; after
        this, the next attach of `key` starts a fresh leader."""
        with self._lock:
            return self._followers.pop(key, [])

    def inflight(self) -> int:
        with self._lock:
            return len(self._followers)

    def waiting(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._followers.values())

    def snapshot(self) -> dict:
        with self._lock:
            return {"inflight_keys": len(self._followers),
                    "waiting_followers":
                        sum(len(v) for v in self._followers.values()),
                    "leaders": self.leaders,
                    "coalesced": self.coalesced}
