"""Content-addressed keys for fold results.

A fold is a pure function of (sequence tokens, the MSA the server will
actually feed the model, fold configuration, model identity), so the
cache key is a stable digest over exactly those — not the request id,
not arrival time, not the bucket (padding is masked out; two lengths
sharing a bucket must NOT share a key, and the same sequence folded
through different bucket layouts SHOULD).

The MSA contributes its *effective* content: the serving scheduler pins
`msa_depth` and keeps only the first `msa_depth` rows of deeper MSAs
(bucketing.assemble's query-first convention), so two requests whose
MSAs agree on those rows are the same work and hash the same. The
pinned depth itself is part of the key — a depth-3 and depth-8 serving
config pad/mask differently and trace different programs.

`model_tag` folds model identity in. Callers own its meaning: a params
checksum, a release string ("af2_tpu_v3@step120k"), anything that
changes when the weights or architecture do. The empty default is fine
for a single-model process but unsafe for a shared on-disk store —
README "Result cache & deduplication" spells this out.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from alphafold2_tpu.utils.hashing import stable_digest

# bump when the semantics of the cached value change (e.g. stored
# fields, confidence definition): old disk entries silently miss
# instead of deserializing into the wrong meaning
KEY_SCHEMA = "fold-v1"

# feature-tier analog (cache/features.py): bump when the featurized
# representation changes shape/meaning so stale entries miss cleanly
FEATURE_KEY_SCHEMA = "feat-v1"


def feature_key(seq, msa=None, *, config_digest: str = "") -> str:
    """Digest identifying one RAW input's featurized form — keyed
    UPSTREAM of `fold_key`: two raw submissions with the same sequence
    and raw MSA are the same featurize work regardless of fold config
    (num_recycles, model_tag, msa_depth all live downstream in
    `fold_key`), so feature traffic dedups independently of fold
    traffic.

    seq: an AA string (canonicalized to upper case — the tokenizer
    upcases, so "mkv" and "MKV" are the same work) or an already-
    tokenized 1-D int array. msa: None, a sequence of aligned AA
    strings, or a 2-D int token array. String and token forms key
    DIFFERENTLY on purpose: the digest covers the raw content the
    featurizer will read, and pre-tokenized input skips the tokenize
    step (the downstream fold_key over the resulting tokens still
    unifies them for fold-level dedup).

    config_digest: the featurizer configuration's own digest
    (serve.features.featurizer_config_digest) — a changed tokenizer
    alphabet or featurize version must MISS cleanly, never serve a
    stale representation. Raises TypeError on un-hashable content;
    callers then skip caching.
    """
    if isinstance(seq, str):
        seq_part = seq.strip().upper()
        if not seq_part:
            raise ValueError("feature_key seq string is empty")
    else:
        seq_part = np.asarray(seq, dtype=np.int32)
        if seq_part.ndim != 1:
            raise ValueError(
                f"feature_key seq must be 1-D, got {seq_part.shape}")
    msa_part = None
    if msa is not None:
        if isinstance(msa, np.ndarray) or (
                hasattr(msa, "ndim") and not isinstance(msa, (list, tuple))):
            msa_part = np.asarray(msa, dtype=np.int32)
            if msa_part.ndim != 2:
                raise ValueError(
                    f"feature_key msa array must be 2-D, got "
                    f"{msa_part.shape}")
        else:
            rows = list(msa)
            if rows and all(isinstance(r, str) for r in rows):
                msa_part = tuple(r.strip().upper() for r in rows)
            else:
                msa_part = np.asarray(msa, dtype=np.int32)
                if msa_part.ndim != 2:
                    raise ValueError(
                        f"feature_key msa must be 2-D tokens or aligned "
                        f"strings, got shape {msa_part.shape}")
    return stable_digest(FEATURE_KEY_SCHEMA, config_digest, seq_part,
                         msa_part)


def fold_key(
    seq,
    msa=None,
    *,
    msa_depth: Optional[int] = None,
    num_recycles: int = 0,
    model_tag: str = "",
    extras=None,
) -> str:
    """Digest identifying one fold's result.

    seq: (n,) int tokens. msa: optional (m, n) int tokens. msa_depth
    mirrors SchedulerConfig.msa_depth: None = serve the MSA as-is,
    0 = MSA-free signature (the MSA is ignored entirely, so it does
    not contribute), k = first k rows contribute (deeper rows are
    truncated by the server and must not split the key).

    extras: any additional result-determining inputs (stable_digest
    types: arrays/scalars/strings/nested tuples). None — the serving
    scheduler's case — keys identically to omitting it, so offline
    callers that pass no extras share entries with the server when the
    rest of the config matches. Raises TypeError on un-hashable
    content; callers should then skip caching, never guess.
    """
    # canonical token dtype: FoldRequest coerces to int32 before the
    # scheduler keys, so offline callers passing default-int (int64)
    # tokens must land on the SAME key — dtype is part of the digest
    seq = np.asarray(seq, dtype=np.int32)
    if seq.ndim != 1:
        raise ValueError(f"fold_key seq must be 1-D, got {seq.shape}")
    if msa is not None and msa_depth == 0:
        msa = None                     # served MSA-free: content irrelevant
    if msa is not None:
        msa = np.asarray(msa, dtype=np.int32)
        if msa.ndim != 2 or msa.shape[1] != seq.shape[0]:
            raise ValueError(
                f"fold_key msa must be (m, {seq.shape[0]}), got "
                f"{None if msa is None else msa.shape}")
        if msa_depth is not None:
            msa = msa[:msa_depth]
    return stable_digest(KEY_SCHEMA, model_tag, seq, msa,
                         msa_depth, int(num_recycles), extras)
