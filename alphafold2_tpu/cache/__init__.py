"""alphafold2_tpu.cache — content-addressed fold results + coalescing.

At serving scale the request stream is massively redundant (ParaFold's
workload analysis), so the cheapest fold is the one never run. Three
pieces, each usable alone:

- keys:     fold_key — canonical digest of (seq, effective MSA, fold
            config, model tag) via utils.hashing.stable_digest;
            feature_key — the UPSTREAM digest of one raw input's
            featurize work (no fold config: feature traffic dedups
            independently of fold traffic)
- bytestore: ByteStore — THE one generic byte-budgeted store (memory
            LRU + TTL over an atomic-write disk tier with quarantine),
            parameterized on encode/decode; both stores below re-base
            on it (ISSUE 13)
- checkpoints: CheckpointStore — durable per-row MID-LOOP carry
            spills keyed by (fold_key, model_tag, age), rebased on
            ByteStore's disk tier with optional object-store backend
            and peer tiers, so an interrupted step-loop fold resumes
            at its checkpointed age on any replica (ISSUE 18;
            `serve.RetryPolicy(checkpoint_spill=...)`)
- store:    FoldCache — ByteStore over encode_fold/decode_fold plus
            the fold-specific stats, gauges, and peer tier;
            corruption == miss
- features: FeatureCache — the same store one stage upstream, holding
            featurized inputs (serve.features.FeaturePool)
- coalesce: InflightRegistry — duplicate submissions attach to the
            in-flight leader instead of folding twice

`serve.Scheduler(..., cache=FoldCache(...))` wires all three into the
serving path (submit: cache -> coalesce -> enqueue; completion
populates the store and fans out to followers). `predict.fold_and_write`
takes the same cache for offline batch memoization. Caching is OFF by
default everywhere — results are only reusable when the model+params
are fixed and identified by `model_tag` (README "Result cache &
deduplication").
"""

from alphafold2_tpu.cache.bytestore import ByteStore  # noqa: F401
from alphafold2_tpu.cache.checkpoints import (CheckpointStore,  # noqa: F401
                                              RowCheckpoint,
                                              checkpoint_group,
                                              decode_checkpoint,
                                              encode_checkpoint)
from alphafold2_tpu.cache.coalesce import InflightRegistry  # noqa: F401
from alphafold2_tpu.cache.features import (FeatureCache,  # noqa: F401
                                           FeaturizedInput,
                                           decode_features,
                                           encode_features)
from alphafold2_tpu.cache.keys import (FEATURE_KEY_SCHEMA,  # noqa: F401
                                       KEY_SCHEMA, feature_key, fold_key)
from alphafold2_tpu.cache.store import (CachedFold, CacheStats,  # noqa: F401
                                        FoldCache, decode_fold,
                                        encode_fold)
