"""Fold result store: in-memory LRU over optional disk and peer tiers.

The memory tier is a byte-budgeted LRU (coords for a 512-residue fold
are ~6 KB; a default 256 MB budget holds tens of thousands of results —
but budgets are enforced, not assumed). The disk tier is one `.npz`
per key under a 2-hex-char fan-out, written atomically (tmp file +
`os.replace`) so a crashed writer can never leave a half-entry a later
reader trusts. Anything wrong with a disk entry — unreadable npz,
missing fields, key mismatch, shape nonsense — is treated as a MISS and
the file is quarantined (renamed `*.quarantined`), never re-read and
never raised to the serving path: a corrupt cache must cost a
recompute, not an outage. Quarantine also reconciles the memory tier:
any resident copy of the poisoned key is dropped WITH its
`bytes_resident` accounting (a quarantine that left the bytes counted
would drift the budget until restart).

`peer` mounts a third tier below disk (memory -> disk -> peer): any
object with `get(key, trace=) -> Optional[CachedFold]` — a
`fleet.PeerCacheClient` fetching npz-over-HTTP from the key's ring
owner, or a `fleet.ObjectStorePeer` over a shared volume. Peer lookups
share the disk tier's trust model (validated via `decode_fold`, any
trouble degrades to a miss) and a peer hit is promoted into the local
memory AND disk tiers so the fleet converges instead of re-fetching.
`peer_write_through=True` additionally pushes local puts to
`peer.put()` (object-store deployments; the HTTP client is read-only —
the owner already holds what it folded). Off by default.

Expiry is TTL-based (wall clock at put time, both tiers) plus
max-entries / max-bytes LRU eviction in memory. `CacheStats` counts
every outcome; `snapshot()` is the JSON-ready health view the serve
stats embed.
"""

from __future__ import annotations

import io
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE

_QUARANTINE_SUFFIX = ".quarantined"


@dataclass
class CachedFold:
    """One stored result: exact-length (unpadded) arrays, always copies."""

    coords: np.ndarray       # (n, 3) float32
    confidence: np.ndarray   # (n,) float32

    @property
    def nbytes(self) -> int:
        return int(self.coords.nbytes + self.confidence.nbytes)


def encode_fold(key: str, value: CachedFold) -> bytes:
    """One cached fold as self-identifying npz bytes — THE wire/disk
    format: the disk tier, the peer HTTP protocol, and object-store
    backends all carry exactly these bytes, so every tier validates
    with the same `decode_fold`."""
    buf = io.BytesIO()
    np.savez(buf, coords=value.coords, confidence=value.confidence,
             key=np.frombuffer(key.encode("utf-8"), np.uint8))
    return buf.getvalue()


def decode_fold(key: str, data: bytes) -> CachedFold:
    """Parse + validate `encode_fold` bytes. Raises on anything wrong
    (unreadable, key mismatch, shape nonsense); callers translate that
    into their tier's miss/quarantine semantics."""
    with np.load(io.BytesIO(data)) as z:
        stored_key = bytes(z["key"]).decode("utf-8")
        value = CachedFold(
            coords=np.asarray(z["coords"], np.float32),
            confidence=np.asarray(z["confidence"], np.float32))
    if (stored_key != key or value.coords.ndim != 2
            or value.coords.shape[1] != 3
            or value.confidence.shape != (value.coords.shape[0],)):
        raise ValueError(f"cache entry {key} fails validation")
    return value


class CacheStats:
    """Thread-safe counters for every cache outcome.

    Every bump is mirrored into the process-wide metrics registry
    (`fold_cache_events_total{event=...}`), so all FoldCache instances
    in a process add up under one Prometheus series while each
    instance's `snapshot()` stays its own."""

    FIELDS = ("hits", "misses", "puts", "evictions", "expirations",
              "disk_hits", "disk_errors", "peer_hits", "peer_errors")

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        self._m_events = (registry or get_registry()).counter(
            "fold_cache_events_total",
            "result-store outcomes across all FoldCache instances",
            ("event",))

    def bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
        self._m_events.inc(n, event=field)

    @property
    def hit_ratio(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            out = {f: getattr(self, f) for f in self.FIELDS}
        total = out["hits"] + out["misses"]
        out["hit_ratio"] = out["hits"] / total if total else 0.0
        return out


class _Entry:
    __slots__ = ("value", "expires_at")

    def __init__(self, value: CachedFold, expires_at: Optional[float]):
        self.value = value
        self.expires_at = expires_at


class FoldCache:
    """Content-addressed fold cache (memory LRU + optional disk + peer).

    max_bytes / max_entries bound the memory tier only; the disk tier
    is bounded by TTL (and by whoever owns the directory). ttl_s=None
    disables expiry. `clock` is injectable for tests.

    peer: optional third tier consulted after a disk miss — any object
        with `get(key, trace=) -> Optional[CachedFold]` that never lets
        an exception escape as anything but a miss (fleet.PeerCacheClient,
        fleet.ObjectStorePeer). A peer hit is promoted into memory and
        disk with a fresh TTL (the peer already refuses entries expired
        on ITS clock, so a value's total lifetime is bounded by one TTL
        per tier hop, not unbounded bouncing).
    peer_write_through: also push local puts to `peer.put(key, value)`
        when the peer supports it (shared-volume object stores).
    """

    def __init__(self, max_bytes: int = 256 << 20, max_entries: int = 4096,
                 ttl_s: Optional[float] = None,
                 disk_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.time,
                 registry: Optional[MetricsRegistry] = None,
                 peer=None, peer_write_through: bool = False,
                 faults=None):
        if max_bytes < 0 or max_entries < 0:
            raise ValueError("max_bytes and max_entries must be >= 0")
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self.ttl_s = ttl_s
        self.disk_dir = disk_dir
        self.peer = peer
        self.peer_write_through = bool(peer_write_through)
        # optional serve.faults.FaultPlan: chaos-corrupts disk bytes
        # BEFORE validation, so injected corruption exercises exactly
        # the quarantine path a real bit-rotted entry would
        self.faults = faults
        self._clock = clock
        self._lock = threading.Lock()
        self._mem: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        reg = registry or get_registry()
        self.stats = CacheStats(registry=reg)
        self._m_bytes = reg.gauge(
            "fold_cache_bytes_resident",
            "memory-tier resident bytes (last-reporting store)")
        self._m_entries = reg.gauge(
            "fold_cache_entries_resident",
            "memory-tier resident entries (last-reporting store)")
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)

    # -- memory tier -----------------------------------------------------

    def _mem_get(self, key: str) -> Optional[CachedFold]:
        now = self._clock()
        with self._lock:
            entry = self._mem.get(key)
            if entry is None:
                return None
            if entry.expires_at is not None and now >= entry.expires_at:
                del self._mem[key]
                self._bytes -= entry.value.nbytes
                self.stats.bump("expirations")
                self._m_bytes.set(self._bytes)
                self._m_entries.set(len(self._mem))
                return None
            self._mem.move_to_end(key)
            return entry.value

    def _mem_put(self, key: str, value: CachedFold,
                 expires_at: Optional[float] = None):
        """expires_at overrides the fresh-write TTL — disk promotions
        pass the ORIGINAL write time's expiry so a value can never live
        past write_time + ttl_s by bouncing between tiers."""
        if self.max_entries == 0 or self.max_bytes == 0:
            return
        if expires_at is not None:
            expires = expires_at
        else:
            expires = (None if self.ttl_s is None
                       else self._clock() + self.ttl_s)
        with self._lock:
            old = self._mem.pop(key, None)
            if old is not None:
                self._bytes -= old.value.nbytes
            self._mem[key] = _Entry(value, expires)
            self._bytes += value.nbytes
            while self._mem and (len(self._mem) > self.max_entries
                                 or self._bytes > self.max_bytes):
                _, evicted = self._mem.popitem(last=False)
                self._bytes -= evicted.value.nbytes
                self.stats.bump("evictions")
            self._m_bytes.set(self._bytes)
            self._m_entries.set(len(self._mem))

    def _mem_drop(self, key: str) -> bool:
        """Remove a memory-resident entry WITH its byte accounting.
        Every invalidation path (quarantine, explicit invalidate) must
        come through here: popping from `_mem` without the `_bytes`
        decrement leaks resident-byte accounting until restart."""
        with self._lock:
            entry = self._mem.pop(key, None)
            if entry is None:
                return False
            self._bytes -= entry.value.nbytes
            self._m_bytes.set(self._bytes)
            self._m_entries.set(len(self._mem))
            return True

    # -- disk tier -------------------------------------------------------

    def _path(self, key: str) -> str:
        return os.path.join(self.disk_dir, key[:2], f"{key}.npz")

    def _quarantine(self, path: str, key: Optional[str] = None,
                    trace=NULL_TRACE):
        self.stats.bump("disk_errors")
        trace.event("cache_quarantine")
        if key is not None:
            # the durable copy of `key` failed validation: drop any
            # memory-resident copy too (reconciling bytes_resident) so
            # a poisoned key costs one clean recompute, not a tier that
            # keeps serving while its backing entry is quarantined
            self._mem_drop(key)
        try:
            os.replace(path, path + _QUARANTINE_SUFFIX)
        except OSError:
            pass                       # racing quarantiners: either wins

    def _disk_get(self, key: str, trace=NULL_TRACE):
        """Returns (value, expires_at) or None."""
        path = self._path(key)
        try:
            if not os.path.exists(path):
                return None
            expires_at = None
            if self.ttl_s is not None:
                expires_at = os.path.getmtime(path) + self.ttl_s
                if self._clock() >= expires_at:
                    self.stats.bump("expirations")
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    return None
        except OSError:
            return None
        try:
            with open(path, "rb") as fh:
                data = fh.read()
            if self.faults is not None:
                data = self.faults.corrupt_cache_bytes(key, data)
            value = decode_fold(key, data)
        except Exception:              # unreadable/garbage/wrong entry
            self._quarantine(path, key, trace)
            return None
        return value, expires_at

    def _disk_put(self, key: str, value: CachedFold):
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as fh:
                fh.write(encode_fold(key, value))
            os.replace(tmp, path)      # atomic: readers see old or new
        except Exception:
            self.stats.bump("disk_errors")
            try:
                os.remove(tmp)
            except OSError:
                pass

    # -- public API ------------------------------------------------------

    def get(self, key: str, trace=NULL_TRACE,
            peer: bool = True) -> Optional[CachedFold]:
        """Lookup; never raises. Tier order memory -> disk -> peer;
        lower-tier hits are promoted upward (a peer hit lands in memory
        AND disk, so the fleet converges instead of re-fetching).
        `peer=False` skips the network tier — the scheduler passes it
        for keys it is about to FORWARD to their owner (the owner's
        cache answers at the forwarded submit; a guaranteed-miss HTTP
        round trip first, worst case a full peer timeout when the
        owner is down, would only delay the hop). `trace` (obs.Trace;
        zero-cost NULL_TRACE default) receives cache_hit / cache_miss /
        cache_quarantine events plus a `peer_fetch` span so a request
        trace shows where its result came from."""
        value = self._mem_get(key)
        tier = "memory"
        if value is None and self.disk_dir:
            hit = self._disk_get(key, trace)
            if hit is not None:
                value, expires_at = hit
                tier = "disk"
                self.stats.bump("disk_hits")
                self._mem_put(key, value, expires_at=expires_at)
        if value is None and peer and self.peer is not None:
            value = self._peer_get(key, trace)
            if value is not None:
                tier = "peer"
        if value is None:
            self.stats.bump("misses")
            trace.event("cache_miss")
            return None
        self.stats.bump("hits")
        trace.event("cache_hit", tier=tier)
        return value

    def _peer_get(self, key: str, trace=NULL_TRACE) -> Optional[CachedFold]:
        """Consult the peer tier; any trouble degrades to a miss (a
        partitioned fleet must cost recomputes, never outages)."""
        try:
            with trace.span("peer_fetch"):
                value = self.peer.get(key, trace=trace)
        except Exception:
            self.stats.bump("peer_errors")
            return None
        if value is None:
            return None
        self.stats.bump("peer_hits")
        self._mem_put(key, value)
        if self.disk_dir:
            self._disk_put(key, value)
        return value

    def put(self, key: str, coords, confidence) -> CachedFold:
        """Store one result (copies taken; never raises past stats)."""
        value = CachedFold(
            coords=np.array(coords, np.float32, copy=True),
            confidence=np.array(confidence, np.float32, copy=True))
        self.stats.bump("puts")
        self._mem_put(key, value)
        if self.disk_dir:
            self._disk_put(key, value)
        if self.peer_write_through and self.peer is not None \
                and hasattr(self.peer, "put"):
            try:
                self.peer.put(key, value)
            except Exception:
                self.stats.bump("peer_errors")
        return value

    def read_raw(self, key: str) -> Optional[bytes]:
        """The key's entry as `encode_fold` bytes, or None — what a
        `fleet.PeerCacheServer` sends to a fetching peer. Serves from
        memory when resident (no disk round-trip on the hot set);
        otherwise reads and VALIDATES the disk file before shipping it
        (a corrupt entry is quarantined — including dropping any
        memory-resident copy with its bytes accounting — never sent:
        the peer protocol's trust model starts at the sender). Does not
        consult this cache's own peer tier (peers answer for what THEY
        hold; fan-out chains would re-introduce unbounded forwarding).
        TTL semantics match `get`."""
        value = self._mem_get(key)
        if value is not None:
            return encode_fold(key, value)
        if not self.disk_dir:
            return None
        hit = self._disk_get(key)
        if hit is None:
            return None
        value, expires_at = hit
        self._mem_put(key, value, expires_at=expires_at)
        return encode_fold(key, value)

    def invalidate(self, key: str) -> bool:
        """Drop `key` from the local tiers (memory accounting included;
        the disk file is removed, not quarantined — invalidation is
        policy, not corruption). Returns True when anything was held."""
        dropped = self._mem_drop(key)
        if self.disk_dir:
            try:
                os.remove(self._path(key))
                dropped = True
            except OSError:
                pass
        return dropped

    # -- views -----------------------------------------------------------

    @property
    def bytes_resident(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    def snapshot(self) -> dict:
        out = self.stats.snapshot()
        with self._lock:
            out["entries_resident"] = len(self._mem)
            out["bytes_resident"] = self._bytes
        out["max_bytes"] = self.max_bytes
        out["max_entries"] = self.max_entries
        out["ttl_s"] = self.ttl_s
        out["disk_dir"] = self.disk_dir
        out["peer"] = (None if self.peer is None
                       else type(self.peer).__name__)
        return out
