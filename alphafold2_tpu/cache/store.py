"""Fold result store: in-memory LRU over optional disk and peer tiers.

The memory tier is a byte-budgeted LRU (coords for a 512-residue fold
are ~6 KB; a default 256 MB budget holds tens of thousands of results —
but budgets are enforced, not assumed). The disk tier is one `.npz`
per key under a 2-hex-char fan-out, written atomically (tmp file +
`os.replace`) so a crashed writer can never leave a half-entry a later
reader trusts. Anything wrong with a disk entry — unreadable npz,
missing fields, key mismatch, shape nonsense — is treated as a MISS and
the file is quarantined (renamed `*.quarantined`), never re-read and
never raised to the serving path: a corrupt cache must cost a
recompute, not an outage. Quarantine also reconciles the memory tier:
any resident copy of the poisoned key is dropped WITH its
`bytes_resident` accounting (a quarantine that left the bytes counted
would drift the budget until restart).

`peer` mounts a third tier below disk (memory -> disk -> peer): any
object with `get(key, trace=) -> Optional[CachedFold]` — a
`fleet.PeerCacheClient` fetching npz-over-HTTP from the key's ring
owner, or a `fleet.ObjectStorePeer` over a shared volume. Peer lookups
share the disk tier's trust model (validated via `decode_fold`, any
trouble degrades to a miss) and a peer hit is promoted into the local
memory AND disk tiers so the fleet converges instead of re-fetching.
`peer_write_through=True` additionally pushes local puts to
`peer.put()` (object-store deployments; the HTTP client is read-only —
the owner already holds what it folded). Off by default.

Expiry is TTL-based (wall clock at put time, both tiers) plus
max-entries / max-bytes LRU eviction in memory. `CacheStats` counts
every outcome; `snapshot()` is the JSON-ready health view the serve
stats embed.
"""

from __future__ import annotations

import io
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from alphafold2_tpu.cache.bytestore import ByteStore
from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE


@dataclass
class CachedFold:
    """One stored result: exact-length (unpadded) arrays, always copies."""

    coords: np.ndarray       # (n, 3) float32
    confidence: np.ndarray   # (n,) float32

    @property
    def nbytes(self) -> int:
        return int(self.coords.nbytes + self.confidence.nbytes)


def encode_fold(key: str, value: CachedFold) -> bytes:
    """One cached fold as self-identifying npz bytes — THE wire/disk
    format: the disk tier, the peer HTTP protocol, and object-store
    backends all carry exactly these bytes, so every tier validates
    with the same `decode_fold`."""
    buf = io.BytesIO()
    np.savez(buf, coords=value.coords, confidence=value.confidence,
             key=np.frombuffer(key.encode("utf-8"), np.uint8))
    return buf.getvalue()


def decode_fold(key: str, data: bytes) -> CachedFold:
    """Parse + validate `encode_fold` bytes. Raises on anything wrong
    (unreadable, key mismatch, shape nonsense); callers translate that
    into their tier's miss/quarantine semantics."""
    with np.load(io.BytesIO(data)) as z:
        stored_key = bytes(z["key"]).decode("utf-8")
        value = CachedFold(
            coords=np.asarray(z["coords"], np.float32),
            confidence=np.asarray(z["confidence"], np.float32))
    if (stored_key != key or value.coords.ndim != 2
            or value.coords.shape[1] != 3
            or value.confidence.shape != (value.coords.shape[0],)):
        raise ValueError(f"cache entry {key} fails validation")
    return value


class CacheStats:
    """Thread-safe counters for every cache outcome.

    Every bump is mirrored into the process-wide metrics registry
    (`fold_cache_events_total{event=...}`), so all FoldCache instances
    in a process add up under one Prometheus series while each
    instance's `snapshot()` stays its own."""

    FIELDS = ("hits", "misses", "puts", "evictions", "expirations",
              "disk_hits", "disk_errors", "peer_hits", "peer_errors")

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        self._m_events = (registry or get_registry()).counter(
            "fold_cache_events_total",
            "result-store outcomes across all FoldCache instances",
            ("event",))

    def bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
        self._m_events.inc(n, event=field)

    @property
    def hit_ratio(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            out = {f: getattr(self, f) for f in self.FIELDS}
        total = out["hits"] + out["misses"]
        out["hit_ratio"] = out["hits"] / total if total else 0.0
        return out


class FoldCache:
    """Content-addressed fold cache (memory LRU + optional disk + peer).

    The memory/disk/quarantine machinery is `cache.bytestore.ByteStore`
    parameterized on `encode_fold`/`decode_fold` (ISSUE 13: ONE copy,
    shared with `cache.features.FeatureCache`); this class owns what a
    FOLD store adds — hit/miss stats into `fold_cache_events_total`,
    registry residency gauges, the peer tier, the fault-injection hook,
    and the peer-serving `read_raw`.

    max_bytes / max_entries bound the memory tier only; the disk tier
    is bounded by TTL (and by whoever owns the directory). ttl_s=None
    disables expiry. `clock` is injectable for tests.

    peer: optional third tier consulted after a disk miss — any object
        with `get(key, trace=) -> Optional[CachedFold]` that never lets
        an exception escape as anything but a miss (fleet.PeerCacheClient,
        fleet.ObjectStorePeer). A peer hit is promoted into memory and
        disk with a fresh TTL (the peer already refuses entries expired
        on ITS clock, so a value's total lifetime is bounded by one TTL
        per tier hop, not unbounded bouncing).
    peer_write_through: also push local puts to `peer.put(key, value)`
        when the peer supports it (shared-volume object stores).
    """

    def __init__(self, max_bytes: int = 256 << 20, max_entries: int = 4096,
                 ttl_s: Optional[float] = None,
                 disk_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.time,
                 registry: Optional[MetricsRegistry] = None,
                 peer=None, peer_write_through: bool = False,
                 faults=None):
        self.peer = peer
        self.peer_write_through = bool(peer_write_through)
        # optional serve.faults.FaultPlan: chaos-corrupts disk bytes
        # BEFORE validation, so injected corruption exercises exactly
        # the quarantine path a real bit-rotted entry would
        self.faults = faults
        reg = registry or get_registry()
        self.stats = CacheStats(registry=reg)
        self._m_bytes = reg.gauge(
            "fold_cache_bytes_resident",
            "memory-tier resident bytes (last-reporting store)")
        self._m_entries = reg.gauge(
            "fold_cache_entries_resident",
            "memory-tier resident entries (last-reporting store)")

        def _resize(nbytes, entries):
            self._m_bytes.set(nbytes)
            self._m_entries.set(entries)

        self._store = ByteStore(
            encode=encode_fold, decode=decode_fold,
            max_bytes=max_bytes, max_entries=max_entries, ttl_s=ttl_s,
            disk_dir=disk_dir, clock=clock,
            on_event=self.stats.bump, on_resize=_resize,
            # read self.faults at call time: the plan may be armed or
            # swapped after construction
            corrupt=lambda key, data: (
                data if self.faults is None
                else self.faults.corrupt_cache_bytes(key, data)),
            quarantine_event="cache_quarantine")

    # sizing/config views delegate to the one store (ISSUE 13: the
    # machinery lives in cache.bytestore; these stay part of the
    # public surface snapshot()/tests read)
    @property
    def max_bytes(self) -> int:
        return self._store.max_bytes

    @property
    def max_entries(self) -> int:
        return self._store.max_entries

    @property
    def ttl_s(self) -> Optional[float]:
        return self._store.ttl_s

    @property
    def disk_dir(self) -> Optional[str]:
        return self._store.disk_dir

    # -- tier internals (delegated; the names remain because tests and
    # -- operational tooling reach for them directly) ---------------------

    def _mem_get(self, key: str) -> Optional[CachedFold]:
        return self._store.mem_get(key)

    def _mem_put(self, key: str, value: CachedFold,
                 expires_at: Optional[float] = None):
        self._store.mem_put(key, value, expires_at=expires_at)

    def _mem_drop(self, key: str) -> bool:
        return self._store.mem_drop(key)

    def _path(self, key: str) -> str:
        return self._store.path(key)

    def _quarantine(self, path: str, key: Optional[str] = None,
                    trace=NULL_TRACE):
        self._store.quarantine(path, key, trace)

    def _disk_get(self, key: str, trace=NULL_TRACE):
        """Returns (value, expires_at) or None."""
        return self._store.disk_get(key, trace)

    def _disk_put(self, key: str, value: CachedFold):
        self._store.disk_put(key, value)

    # -- public API ------------------------------------------------------

    def get(self, key: str, trace=NULL_TRACE,
            peer: bool = True) -> Optional[CachedFold]:
        """Lookup; never raises. Tier order memory -> disk -> peer;
        lower-tier hits are promoted upward (a peer hit lands in memory
        AND disk, so the fleet converges instead of re-fetching).
        `peer=False` skips the network tier — the scheduler passes it
        for keys it is about to FORWARD to their owner (the owner's
        cache answers at the forwarded submit; a guaranteed-miss HTTP
        round trip first, worst case a full peer timeout when the
        owner is down, would only delay the hop). `trace` (obs.Trace;
        zero-cost NULL_TRACE default) receives cache_hit / cache_miss /
        cache_quarantine events plus a `peer_fetch` span so a request
        trace shows where its result came from."""
        hit = self._store.lookup(key, trace)
        value = tier = None
        if hit is not None:
            value, tier = hit
            if tier == "disk":
                self.stats.bump("disk_hits")
        if value is None and peer and self.peer is not None:
            value = self._peer_get(key, trace)
            if value is not None:
                tier = "peer"
        if value is None:
            self.stats.bump("misses")
            trace.event("cache_miss")
            return None
        self.stats.bump("hits")
        trace.event("cache_hit", tier=tier)
        return value

    def _peer_get(self, key: str, trace=NULL_TRACE) -> Optional[CachedFold]:
        """Consult the peer tier; any trouble degrades to a miss (a
        partitioned fleet must cost recomputes, never outages)."""
        try:
            with trace.span("peer_fetch"):
                value = self.peer.get(key, trace=trace)
        except Exception:
            self.stats.bump("peer_errors")
            return None
        if value is None:
            return None
        self.stats.bump("peer_hits")
        self._mem_put(key, value)
        if self.disk_dir:
            self._disk_put(key, value)
        return value

    def put(self, key: str, coords, confidence) -> CachedFold:
        """Store one result (copies taken; never raises past stats)."""
        value = CachedFold(
            coords=np.array(coords, np.float32, copy=True),
            confidence=np.array(confidence, np.float32, copy=True))
        self.stats.bump("puts")
        self._mem_put(key, value)
        if self.disk_dir:
            self._disk_put(key, value)
        if self.peer_write_through and self.peer is not None \
                and hasattr(self.peer, "put"):
            try:
                self.peer.put(key, value)
            except Exception:
                self.stats.bump("peer_errors")
        return value

    def read_raw(self, key: str) -> Optional[bytes]:
        """The key's entry as `encode_fold` bytes, or None — what a
        `fleet.PeerCacheServer` sends to a fetching peer. Serves from
        memory when resident (no disk round-trip on the hot set);
        otherwise reads and VALIDATES the disk file before shipping it
        (a corrupt entry is quarantined — including dropping any
        memory-resident copy with its bytes accounting — never sent:
        the peer protocol's trust model starts at the sender). Does not
        consult this cache's own peer tier (peers answer for what THEY
        hold; fan-out chains would re-introduce unbounded forwarding).
        TTL semantics match `get`."""
        value = self._mem_get(key)
        if value is not None:
            return encode_fold(key, value)
        if not self.disk_dir:
            return None
        hit = self._disk_get(key)
        if hit is None:
            return None
        value, expires_at = hit
        self._mem_put(key, value, expires_at=expires_at)
        return encode_fold(key, value)

    def invalidate(self, key: str) -> bool:
        """Drop `key` from the local tiers (memory accounting included;
        the disk file is removed, not quarantined — invalidation is
        policy, not corruption). Returns True when anything was held."""
        dropped = self._mem_drop(key)
        if self.disk_dir:
            try:
                os.remove(self._path(key))
                dropped = True
            except OSError:
                pass
        return dropped

    # -- views -----------------------------------------------------------

    @property
    def bytes_resident(self) -> int:
        return self._store.bytes_resident

    def __len__(self) -> int:
        return len(self._store)

    def snapshot(self) -> dict:
        out = self.stats.snapshot()
        out["entries_resident"] = len(self._store)
        out["bytes_resident"] = self._store.bytes_resident
        out["max_bytes"] = self.max_bytes
        out["max_entries"] = self.max_entries
        out["ttl_s"] = self.ttl_s
        out["disk_dir"] = self.disk_dir
        out["peer"] = (None if self.peer is None
                       else type(self.peer).__name__)
        return out
