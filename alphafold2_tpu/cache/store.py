"""Two-tier fold result store: in-memory LRU over an optional disk tier.

The memory tier is a byte-budgeted LRU (coords for a 512-residue fold
are ~6 KB; a default 256 MB budget holds tens of thousands of results —
but budgets are enforced, not assumed). The disk tier is one `.npz`
per key under a 2-hex-char fan-out, written atomically (tmp file +
`os.replace`) so a crashed writer can never leave a half-entry a later
reader trusts. Anything wrong with a disk entry — unreadable npz,
missing fields, key mismatch, shape nonsense — is treated as a MISS and
the file is quarantined (renamed `*.quarantined`), never re-read and
never raised to the serving path: a corrupt cache must cost a
recompute, not an outage.

Expiry is TTL-based (wall clock at put time, both tiers) plus
max-entries / max-bytes LRU eviction in memory. `CacheStats` counts
every outcome; `snapshot()` is the JSON-ready health view the serve
stats embed.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE

_QUARANTINE_SUFFIX = ".quarantined"


@dataclass
class CachedFold:
    """One stored result: exact-length (unpadded) arrays, always copies."""

    coords: np.ndarray       # (n, 3) float32
    confidence: np.ndarray   # (n,) float32

    @property
    def nbytes(self) -> int:
        return int(self.coords.nbytes + self.confidence.nbytes)


class CacheStats:
    """Thread-safe counters for every cache outcome.

    Every bump is mirrored into the process-wide metrics registry
    (`fold_cache_events_total{event=...}`), so all FoldCache instances
    in a process add up under one Prometheus series while each
    instance's `snapshot()` stays its own."""

    FIELDS = ("hits", "misses", "puts", "evictions", "expirations",
              "disk_hits", "disk_errors")

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        self._m_events = (registry or get_registry()).counter(
            "fold_cache_events_total",
            "result-store outcomes across all FoldCache instances",
            ("event",))

    def bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
        self._m_events.inc(n, event=field)

    @property
    def hit_ratio(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            out = {f: getattr(self, f) for f in self.FIELDS}
        total = out["hits"] + out["misses"]
        out["hit_ratio"] = out["hits"] / total if total else 0.0
        return out


class _Entry:
    __slots__ = ("value", "expires_at")

    def __init__(self, value: CachedFold, expires_at: Optional[float]):
        self.value = value
        self.expires_at = expires_at


class FoldCache:
    """Content-addressed fold result cache (memory LRU + optional disk).

    max_bytes / max_entries bound the memory tier only; the disk tier
    is bounded by TTL (and by whoever owns the directory). ttl_s=None
    disables expiry. `clock` is injectable for tests.
    """

    def __init__(self, max_bytes: int = 256 << 20, max_entries: int = 4096,
                 ttl_s: Optional[float] = None,
                 disk_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.time,
                 registry: Optional[MetricsRegistry] = None):
        if max_bytes < 0 or max_entries < 0:
            raise ValueError("max_bytes and max_entries must be >= 0")
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self.ttl_s = ttl_s
        self.disk_dir = disk_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._mem: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        reg = registry or get_registry()
        self.stats = CacheStats(registry=reg)
        self._m_bytes = reg.gauge(
            "fold_cache_bytes_resident",
            "memory-tier resident bytes (last-reporting store)")
        self._m_entries = reg.gauge(
            "fold_cache_entries_resident",
            "memory-tier resident entries (last-reporting store)")
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)

    # -- memory tier -----------------------------------------------------

    def _mem_get(self, key: str) -> Optional[CachedFold]:
        now = self._clock()
        with self._lock:
            entry = self._mem.get(key)
            if entry is None:
                return None
            if entry.expires_at is not None and now >= entry.expires_at:
                del self._mem[key]
                self._bytes -= entry.value.nbytes
                self.stats.bump("expirations")
                self._m_bytes.set(self._bytes)
                self._m_entries.set(len(self._mem))
                return None
            self._mem.move_to_end(key)
            return entry.value

    def _mem_put(self, key: str, value: CachedFold,
                 expires_at: Optional[float] = None):
        """expires_at overrides the fresh-write TTL — disk promotions
        pass the ORIGINAL write time's expiry so a value can never live
        past write_time + ttl_s by bouncing between tiers."""
        if self.max_entries == 0 or self.max_bytes == 0:
            return
        if expires_at is not None:
            expires = expires_at
        else:
            expires = (None if self.ttl_s is None
                       else self._clock() + self.ttl_s)
        with self._lock:
            old = self._mem.pop(key, None)
            if old is not None:
                self._bytes -= old.value.nbytes
            self._mem[key] = _Entry(value, expires)
            self._bytes += value.nbytes
            while self._mem and (len(self._mem) > self.max_entries
                                 or self._bytes > self.max_bytes):
                _, evicted = self._mem.popitem(last=False)
                self._bytes -= evicted.value.nbytes
                self.stats.bump("evictions")
            self._m_bytes.set(self._bytes)
            self._m_entries.set(len(self._mem))

    # -- disk tier -------------------------------------------------------

    def _path(self, key: str) -> str:
        return os.path.join(self.disk_dir, key[:2], f"{key}.npz")

    def _quarantine(self, path: str, trace=NULL_TRACE):
        self.stats.bump("disk_errors")
        trace.event("cache_quarantine")
        try:
            os.replace(path, path + _QUARANTINE_SUFFIX)
        except OSError:
            pass                       # racing quarantiners: either wins

    def _disk_get(self, key: str, trace=NULL_TRACE):
        """Returns (value, expires_at) or None."""
        path = self._path(key)
        try:
            if not os.path.exists(path):
                return None
            expires_at = None
            if self.ttl_s is not None:
                expires_at = os.path.getmtime(path) + self.ttl_s
                if self._clock() >= expires_at:
                    self.stats.bump("expirations")
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    return None
        except OSError:
            return None
        try:
            with np.load(path) as z:
                stored_key = bytes(z["key"]).decode("utf-8")
                value = CachedFold(
                    coords=np.asarray(z["coords"], np.float32),
                    confidence=np.asarray(z["confidence"], np.float32))
            if (stored_key != key or value.coords.ndim != 2
                    or value.coords.shape[1] != 3
                    or value.confidence.shape
                    != (value.coords.shape[0],)):
                raise ValueError(f"cache entry {key} fails validation")
        except Exception:              # unreadable/garbage/wrong entry
            self._quarantine(path, trace)
            return None
        return value, expires_at

    def _disk_put(self, key: str, value: CachedFold):
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as fh:
                np.savez(fh, coords=value.coords,
                         confidence=value.confidence,
                         key=np.frombuffer(key.encode("utf-8"), np.uint8))
            os.replace(tmp, path)      # atomic: readers see old or new
        except Exception:
            self.stats.bump("disk_errors")
            try:
                os.remove(tmp)
            except OSError:
                pass

    # -- public API ------------------------------------------------------

    def get(self, key: str, trace=NULL_TRACE) -> Optional[CachedFold]:
        """Lookup; never raises. Disk hits are promoted into memory.
        `trace` (obs.Trace; zero-cost NULL_TRACE default) receives
        cache_hit / cache_miss / cache_quarantine events so a request
        trace shows where its result came from."""
        value = self._mem_get(key)
        tier = "memory"
        if value is None and self.disk_dir:
            hit = self._disk_get(key, trace)
            if hit is not None:
                value, expires_at = hit
                tier = "disk"
                self.stats.bump("disk_hits")
                self._mem_put(key, value, expires_at=expires_at)
        if value is None:
            self.stats.bump("misses")
            trace.event("cache_miss")
            return None
        self.stats.bump("hits")
        trace.event("cache_hit", tier=tier)
        return value

    def put(self, key: str, coords, confidence) -> CachedFold:
        """Store one result (copies taken; never raises past stats)."""
        value = CachedFold(
            coords=np.array(coords, np.float32, copy=True),
            confidence=np.array(confidence, np.float32, copy=True))
        self.stats.bump("puts")
        self._mem_put(key, value)
        if self.disk_dir:
            self._disk_put(key, value)
        return value

    # -- views -----------------------------------------------------------

    @property
    def bytes_resident(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    def snapshot(self) -> dict:
        out = self.stats.snapshot()
        with self._lock:
            out["entries_resident"] = len(self._mem)
            out["bytes_resident"] = self._bytes
        out["max_bytes"] = self.max_bytes
        out["max_entries"] = self.max_entries
        out["ttl_s"] = self.ttl_s
        out["disk_dir"] = self.disk_dir
        return out
