"""THE generic byte-budgeted store (ISSUE 13 satellite).

`cache/store.py` (fold results) and `cache/features.py` (featurized
inputs) grew the same machinery twice: a byte-budgeted in-memory LRU
with TTL expiry over an optional atomic-write on-disk `.npz` tier whose
corrupt entries are quarantined (`*.quarantined`), never re-read, and
never raised into the serving path. The ROADMAP named extracting ONE
copy the prerequisite refactor before the feature tier grows
object-store spill — a third copy was the alternative.

`ByteStore` is that copy, parameterized on what the two (and future)
tiers actually differ in:

- `encode(key, value) -> bytes` / `decode(key, data) -> value`: the
  self-identifying npz wire format and its validation (decode RAISES
  on anything wrong; the store translates that into miss+quarantine);
- `value.nbytes`: the memory budget unit (both `CachedFold` and
  `FeaturizedInput` expose it);
- `on_event(field, n)`: counter fan-out ("expirations", "evictions",
  "disk_errors") into whichever stats object the owner keeps;
- `on_resize(bytes, entries)`: gauge fan-out after any memory-tier
  mutation (the fold store mirrors residency into the metrics
  registry; the feature store doesn't);
- `corrupt(key, data) -> data`: optional chaos hook applied to disk
  bytes BEFORE validation (serve.faults), so injected corruption
  exercises exactly the quarantine path a real bit-rotted entry would;
- `quarantine_event`: the trace event name ("cache_quarantine" /
  "feature_quarantine").

Hit/miss accounting and any peer tier stay with the OWNER: they are
policy (what counts as a hit, what a fleet does on a miss), not
storage. The owner composes `lookup()` (memory -> disk with promotion)
with whatever sits below.

Semantics are exactly the ones both originals shipped (their test
suites pass unmodified against the re-based classes): LRU by
max_entries AND max_bytes, a 0 budget disables the memory tier,
TTL measured from write time with disk promotions carrying the
ORIGINAL expiry (a value can never outlive write_time + ttl_s by
bouncing between tiers), atomic disk writes via tmp + `os.replace`,
quarantine reconciling any memory-resident copy WITH its byte
accounting.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional, Tuple

from alphafold2_tpu.obs.trace import NULL_TRACE

QUARANTINE_SUFFIX = ".quarantined"


class _Entry:
    __slots__ = ("value", "expires_at")

    def __init__(self, value, expires_at: Optional[float]):
        self.value = value
        self.expires_at = expires_at


def _noop_event(field: str, n: int = 1):
    pass


def _noop_resize(nbytes: int, entries: int):
    pass


class ByteStore:
    """Byte-budgeted memory LRU + TTL over an optional atomic-write
    disk tier with quarantine. See the module docstring; thread-safe.
    Values must expose `.nbytes`."""

    def __init__(self, *, encode: Callable[[str, object], bytes],
                 decode: Callable[[str, bytes], object],
                 max_bytes: int, max_entries: int,
                 ttl_s: Optional[float] = None,
                 disk_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.time,
                 on_event: Optional[Callable] = None,
                 on_resize: Optional[Callable] = None,
                 corrupt: Optional[Callable] = None,
                 quarantine_event: str = "cache_quarantine"):
        if max_bytes < 0 or max_entries < 0:
            raise ValueError("max_bytes and max_entries must be >= 0")
        self.encode = encode
        self.decode = decode
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self.ttl_s = ttl_s
        self.disk_dir = disk_dir
        self._clock = clock
        self._on_event = on_event or _noop_event
        self._on_resize = on_resize or _noop_resize
        self._corrupt = corrupt
        self._quarantine_event = quarantine_event
        self._lock = threading.Lock()
        self._mem: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)

    # -- memory tier -----------------------------------------------------

    def mem_get(self, key: str):
        now = self._clock()
        with self._lock:
            entry = self._mem.get(key)
            if entry is None:
                return None
            if entry.expires_at is not None and now >= entry.expires_at:
                del self._mem[key]
                self._bytes -= entry.value.nbytes
                self._on_event("expirations")
                self._on_resize(self._bytes, len(self._mem))
                return None
            self._mem.move_to_end(key)
            return entry.value

    def mem_put(self, key: str, value, expires_at: Optional[float] = None):
        """expires_at overrides the fresh-write TTL — disk promotions
        pass the ORIGINAL write time's expiry so a value can never live
        past write_time + ttl_s by bouncing between tiers."""
        if self.max_entries == 0 or self.max_bytes == 0:
            return
        if expires_at is None:
            expires_at = (None if self.ttl_s is None
                          else self._clock() + self.ttl_s)
        with self._lock:
            old = self._mem.pop(key, None)
            if old is not None:
                self._bytes -= old.value.nbytes
            self._mem[key] = _Entry(value, expires_at)
            self._bytes += value.nbytes
            while self._mem and (len(self._mem) > self.max_entries
                                 or self._bytes > self.max_bytes):
                _, evicted = self._mem.popitem(last=False)
                self._bytes -= evicted.value.nbytes
                self._on_event("evictions")
            self._on_resize(self._bytes, len(self._mem))

    def mem_drop(self, key: str) -> bool:
        """Remove a memory-resident entry WITH its byte accounting.
        Every invalidation path (quarantine, explicit invalidate) must
        come through here: popping from `_mem` without the byte
        decrement leaks resident-byte accounting until restart."""
        with self._lock:
            entry = self._mem.pop(key, None)
            if entry is None:
                return False
            self._bytes -= entry.value.nbytes
            self._on_resize(self._bytes, len(self._mem))
            return True

    # -- disk tier -------------------------------------------------------

    def path(self, key: str) -> str:
        return os.path.join(self.disk_dir, key[:2], f"{key}.npz")

    def quarantine(self, path: str, key: Optional[str] = None,
                   trace=NULL_TRACE):
        self._on_event("disk_errors")
        trace.event(self._quarantine_event)
        if key is not None:
            # the durable copy of `key` failed validation: drop any
            # memory-resident copy too (reconciling resident bytes) so
            # a poisoned key costs one clean recompute, not a tier that
            # keeps serving while its backing entry is quarantined
            self.mem_drop(key)
        try:
            os.replace(path, path + QUARANTINE_SUFFIX)
        except OSError:
            pass                       # racing quarantiners: either wins

    def disk_get(self, key: str, trace=NULL_TRACE
                 ) -> Optional[Tuple[object, Optional[float]]]:
        """Returns (value, expires_at) or None."""
        path = self.path(key)
        try:
            if not os.path.exists(path):
                return None
            expires_at = None
            if self.ttl_s is not None:
                expires_at = os.path.getmtime(path) + self.ttl_s
                if self._clock() >= expires_at:
                    self._on_event("expirations")
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    return None
        except OSError:
            return None
        try:
            with open(path, "rb") as fh:
                data = fh.read()
            if self._corrupt is not None:
                data = self._corrupt(key, data)
            value = self.decode(key, data)
        except Exception:              # unreadable/garbage/wrong entry
            self.quarantine(path, key, trace)
            return None
        return value, expires_at

    def disk_put(self, key: str, value):
        path = self.path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as fh:
                fh.write(self.encode(key, value))
            os.replace(tmp, path)      # atomic: readers see old or new
        except Exception:
            self._on_event("disk_errors")
            try:
                os.remove(tmp)
            except OSError:
                pass

    # -- disk iteration ---------------------------------------------------

    def keys(self, prefix: str = ""):
        """Keys present in the DISK tier (sorted), optionally filtered
        by prefix. Point lookups were the only read path until the
        checkpoint store needed boot-time discovery (ISSUE 18): a
        restarted replica has to enumerate what survived it, not ask
        for keys it no longer remembers. Expired entries are swept
        here, not just skipped — TTL enforced only on `get` left a
        scan able to resurrect a stale key (the ISSUE-18 bugfix);
        quarantined files never enumerate."""
        if not self.disk_dir:
            return []
        now = self._clock()
        out = []
        # fan-out dirs are key[:2]; a prefix >= 2 chars pins the dir
        subdirs = ([prefix[:2]] if len(prefix) >= 2
                   else sorted(d for d in self._listdir(self.disk_dir)
                               if len(d) == 2))
        for sub in subdirs:
            root = os.path.join(self.disk_dir, sub)
            for name in sorted(self._listdir(root)):
                if not name.endswith(".npz"):
                    continue           # quarantined / tmp leftovers
                key = name[:-len(".npz")]
                if prefix and not key.startswith(prefix):
                    continue
                path = os.path.join(root, name)
                if self.ttl_s is not None:
                    try:
                        if now >= os.path.getmtime(path) + self.ttl_s:
                            self._on_event("expirations")
                            try:
                                os.remove(path)
                            except OSError:
                                pass
                            continue
                    except OSError:
                        continue       # raced a concurrent sweep
                out.append(key)
        return out

    def scan(self, prefix: str = "", trace=NULL_TRACE):
        """Iterate (key, value) over the disk tier, optionally
        prefix-filtered. Rides `keys()` so expired entries are swept,
        and `disk_get` so corrupt entries quarantine to a miss instead
        of raising into the caller's boot path."""
        for key in self.keys(prefix):
            hit = self.disk_get(key, trace)
            if hit is None:
                continue
            value, _expires_at = hit
            yield key, value

    @staticmethod
    def _listdir(path: str):
        try:
            return os.listdir(path)
        except OSError:
            return []

    # -- composed lookup -------------------------------------------------

    def lookup(self, key: str, trace=NULL_TRACE):
        """memory -> disk with upward promotion. Returns (value, tier)
        with tier in ("memory", "disk"), or None. The OWNER layers
        hit/miss stats and any lower tier (peer/object store) on top."""
        value = self.mem_get(key)
        if value is not None:
            return value, "memory"
        if not self.disk_dir:
            return None
        hit = self.disk_get(key, trace)
        if hit is None:
            return None
        value, expires_at = hit
        self.mem_put(key, value, expires_at=expires_at)
        return value, "disk"

    # -- views -----------------------------------------------------------

    @property
    def bytes_resident(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)
