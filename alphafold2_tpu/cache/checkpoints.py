"""Durable step-loop checkpoints: the migratable-fold store (ISSUE 18).

PR 14's `_StepCheckpoint` lives in the serving process's host memory —
it survives transient step failures and watchdog fires, but a kill -9
mid-flagship-loop still refolds from recycle 0. This module makes the
checkpoint a DURABLE, MIGRATABLE artifact: one npz payload per batch
ROW (the fold is the unit of migration, not the batch it happened to
share a device slice with), carrying exactly what a resuming replica
needs to continue that fold mid-loop:

- the row's slice of the step carry (`predict.snapshot_step_state`
  leaves, sliced on the batch axis, each with a portable sharding SPEC
  so a mesh-sharded carry re-places on restore);
- the row's host inputs (unpadded seq + msa tokens — enough to verify
  the resumed request is byte-identical work);
- the recycle age the carry was captured at.

`CheckpointStore` rebases on `cache/bytestore.py` (atomic disk writes,
TTL, quarantine, and the new `keys()`/`scan()` iteration this store
motivated) and is keyed by `(fold_key, model_tag, age)`:
`checkpoint_key` digests fold_key + model_tag into a GROUP prefix and
appends the age, so every age of one fold shares a prefix —
`latest()` is a prefix scan, boot discovery (`survivors()`) is a full
scan, and a rollout's tag bump makes old checkpoints unreachable by
lookup and actively DISCARDED by scan (stale-tag resume is the one
unforgivable failure mode: a new model must never continue an old
model's carry). Older ages are pruned after each newer spill, so the
disk holds one checkpoint per in-flight fold.

Tiering mirrors the fold cache: local disk is authoritative; an
optional `ObjectStoreBackend` mirror (one object per fold group, the
shared-volume path) and an optional peer tier (duck-typed
`fetch_checkpoint(group, tag) -> bytes | None`, served by
`fleet.peer.PeerCacheServer`'s `kind=checkpoint` route) let a failover
owner resume a dead replica's fold mid-loop — the fleet hand-off half
of ISSUE 18. Every tier carries the same self-identifying bytes and
validates with the same `decode_checkpoint`.

The treedef is deliberately NOT on the wire: the resuming scheduler
already initializes the row through the normal admission path (the
row-masked init program), then overwrites the row's leaves with the
decoded carry — leaf ORDER is deterministic for one model structure,
and a leaf-count/shape mismatch is a validation failure (discard +
refold-from-zero), never a guess.
"""

from __future__ import annotations

import io
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Tuple

import numpy as np

from alphafold2_tpu.cache.bytestore import ByteStore
from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE
from alphafold2_tpu.utils.hashing import stable_digest

# bump when the payload's fields or meaning change: old spills must
# MISS (and be discarded), never resume into the wrong semantics
CHECKPOINT_SCHEMA = "ckpt-v1"

# orphan manifest (ISSUE 20): the JSON record a preempted replica
# publishes next to its spilled checkpoints so the controller can
# actively re-home every in-flight fold instead of waiting for lazy
# peer probes. Same versioning discipline as the checkpoint payload.
MANIFEST_SCHEMA = "orphans-v1"

# JSON-able reference-leaf types the wire can carry; anything else
# makes the row unspillable (counted, skipped — never a torn payload)
_REF_TYPES = (bool, int, float, str, type(None))


def checkpoint_group(fold_key: str, model_tag: str = "") -> str:
    """Prefix shared by every age of one fold's checkpoints."""
    return stable_digest(CHECKPOINT_SCHEMA, fold_key, model_tag)


def checkpoint_key(fold_key: str, model_tag: str = "",
                   age: int = 0) -> str:
    """(fold_key, model_tag, age) -> store key. Zero-padded age keeps
    lexicographic order == age order within a group's prefix scan."""
    return f"{checkpoint_group(fold_key, model_tag)}-a{int(age):08d}"


def key_age(key: str) -> int:
    """Age component of a `checkpoint_key` (raises on malformed)."""
    return int(key.rsplit("-a", 1)[1])


def manifest_key(replica_id: str) -> str:
    """Object-store key of one replica's orphan manifest. Digested so
    arbitrary replica ids stay filesystem-safe under the same backend
    the checkpoint mirrors live in, with a distinct prefix space from
    `checkpoint_group` (different schema string digests apart)."""
    return stable_digest(MANIFEST_SCHEMA, str(replica_id))


def read_manifest(backend, replica_id: str) -> Optional[dict]:
    """Decode one replica's published orphan manifest from the shared
    backend; None on miss or anything malformed (a torn/alien payload
    must read as 'no manifest', never crash a controller tick)."""
    if backend is None:
        return None
    try:
        data = backend.get(manifest_key(replica_id))
        if data is None:
            return None
        manifest = json.loads(data.decode("utf-8"))
    except Exception:
        return None
    if not isinstance(manifest, dict) \
            or manifest.get("schema") != MANIFEST_SCHEMA \
            or not isinstance(manifest.get("orphans"), list):
        return None
    return manifest


def clear_manifest(backend, replica_id: str) -> bool:
    """Drop a replica's manifest after its orphans were adopted (the
    controller's ack — re-reading on the next tick must find nothing,
    so adoption is idempotent across reconcile rounds)."""
    if backend is None:
        return False
    try:
        backend.delete(manifest_key(replica_id))
        return True
    except Exception:
        return False


# -- sharding specs --------------------------------------------------------


def sharding_spec(sharding) -> Optional[dict]:
    """Portable descriptor of a leaf's sharding — enough to re-place a
    NamedSharding on a same-shaped mesh of the RESUMING process's
    devices. Anything else (single-device, positional, None) restores
    through default placement, exactly `restore_step_state`'s
    fallback."""
    if sharding is None:
        return None
    try:
        mesh = getattr(sharding, "mesh", None)
        spec = getattr(sharding, "spec", None)
        if mesh is None or spec is None:
            return None
        axes, sizes = zip(*mesh.shape.items()) if mesh.shape else ((), ())
        return {"kind": "named",
                "axes": list(axes),
                "sizes": [int(s) for s in sizes],
                "spec": [list(p) if isinstance(p, (tuple, list))
                         else p for p in tuple(spec)]}
    except Exception:
        return None


def sharding_from_spec(desc: Optional[dict]):
    """Rebuild a NamedSharding from a spec on THIS process's devices;
    None when the spec is absent or the device count no longer fits
    (default placement — the restore path's existing fallback)."""
    if not desc or desc.get("kind") != "named":
        return None
    try:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        sizes = [int(s) for s in desc["sizes"]]
        need = int(np.prod(sizes)) if sizes else 1
        devices = jax.devices()
        if len(devices) < need:
            return None
        mesh = Mesh(np.asarray(devices[:need]).reshape(sizes),
                    tuple(desc["axes"]))
        parts = [tuple(p) if isinstance(p, list) else p
                 for p in desc["spec"]]
        return NamedSharding(mesh, PartitionSpec(*parts))
    except Exception:
        return None


# -- the payload -----------------------------------------------------------


@dataclass
class RowCheckpoint:
    """One fold's mid-loop state: everything a resuming replica needs
    to continue THIS row at `age` recycles, detached from the batch it
    was sharing. `leaves` holds the row's slice of the flattened step
    carry in `snapshot_step_state` order — ("dev", (1, ...) np array,
    sharding spec) or ("ref", json-able scalar, None)."""

    fold_key: str
    model_tag: str
    age: int
    seq: np.ndarray                       # (L,) int32, unpadded
    msa: Optional[np.ndarray] = None      # (m, L) int32 or None
    leaves: List[tuple] = field(default_factory=list)
    created_s: float = 0.0

    @property
    def nbytes(self) -> int:
        n = self.seq.nbytes + (0 if self.msa is None else self.msa.nbytes)
        for kind, val, _spec in self.leaves:
            if kind == "dev":
                n += val.nbytes
        return n

    def state_entries(self) -> List[tuple]:
        """`restore_step_state`-shaped entries (kind, value, sharding)
        with each spec rebuilt into a live sharding (or None): the
        resume path re-uploads THROUGH the recorded placement, PR 14's
        restore contract."""
        return [(kind, val, sharding_from_spec(spec) if kind == "dev"
                 else None)
                for kind, val, spec in self.leaves]

    def restore_leaves(self) -> list:
        """Decoded leaves re-placed on device via the PR 14 restore
        path (`predict.restore_step_state` over a flat list treedef):
        device leaves go back through their recorded sharding spec with
        default-device fallback, references pass through."""
        import jax

        from alphafold2_tpu import predict
        entries = self.state_entries()
        treedef = jax.tree_util.tree_structure([0] * len(entries))
        return list(predict.restore_step_state((treedef, entries)))


def row_checkpoint(snapshot, row: int, *, fold_key: str,
                   model_tag: str, age: int, seq: np.ndarray,
                   msa: Optional[np.ndarray] = None,
                   clock=time.time) -> RowCheckpoint:
    """Slice row `row` out of a full-batch `snapshot_step_state`
    result. Raises ValueError when the carry is not row-sliceable (a
    dev leaf without a batch axis, or an opaque reference leaf the
    wire cannot carry) — the caller counts and skips the spill, it
    never writes a partial payload."""
    _treedef, entries = snapshot
    leaves: List[tuple] = []
    for kind, val, sharding in entries:
        if kind == "dev":
            arr = np.asarray(val)
            if arr.ndim < 1 or arr.shape[0] <= row:
                raise ValueError(
                    f"carry leaf shape {arr.shape} has no row {row}")
            leaves.append(("dev", np.ascontiguousarray(arr[row:row + 1]),
                           sharding_spec(sharding)))
        else:
            if not isinstance(val, _REF_TYPES):
                raise ValueError(
                    f"opaque reference leaf {type(val).__name__} is "
                    f"not wire-able")
            leaves.append(("ref", val, None))
    return RowCheckpoint(
        fold_key=fold_key, model_tag=model_tag, age=int(age),
        seq=np.asarray(seq, np.int32),
        msa=None if msa is None else np.asarray(msa, np.int32),
        leaves=leaves, created_s=float(clock()))


# -- wire format -----------------------------------------------------------


def encode_checkpoint(key: str, ckpt: RowCheckpoint) -> bytes:
    """Self-identifying npz bytes — the disk tier, the peer
    `kind=checkpoint` route, and object-store mirrors all carry
    exactly these; every tier validates with `decode_checkpoint`."""
    meta = {"schema": CHECKPOINT_SCHEMA, "key": key,
            "fold_key": ckpt.fold_key, "model_tag": ckpt.model_tag,
            "age": int(ckpt.age), "created_s": float(ckpt.created_s),
            "msa": ckpt.msa is not None,
            "kinds": [kind for kind, _v, _s in ckpt.leaves],
            "shardings": [spec for kind, _v, spec in ckpt.leaves],
            "refs": {str(i): val
                     for i, (kind, val, _s) in enumerate(ckpt.leaves)
                     if kind == "ref"},
            "dtypes": [str(np.asarray(v).dtype) if kind == "dev" else None
                       for kind, v, _s in ckpt.leaves]}
    arrays = {"meta": np.frombuffer(
        json.dumps(meta).encode("utf-8"), np.uint8), "seq": ckpt.seq}
    if ckpt.msa is not None:
        arrays["msa"] = ckpt.msa
    for i, (kind, val, _spec) in enumerate(ckpt.leaves):
        if kind == "dev":
            arrays[f"leaf_{i:05d}"] = val
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_checkpoint(key: str, data: bytes) -> RowCheckpoint:
    """Parse + validate `encode_checkpoint` bytes. Raises on anything
    wrong (unreadable, schema drift, key mismatch, leaf bookkeeping
    nonsense); callers translate into miss + quarantine. Model-tag
    POLICY (discard vs serve) stays with `CheckpointStore` — the codec
    only guarantees the payload says what it is."""
    with np.load(io.BytesIO(data)) as z:
        meta = json.loads(bytes(z["meta"]).decode("utf-8"))
        if meta.get("schema") != CHECKPOINT_SCHEMA:
            raise ValueError(f"checkpoint {key}: schema "
                             f"{meta.get('schema')!r}")
        if meta.get("key") != key:
            raise ValueError(f"checkpoint {key}: embedded key mismatch")
        kinds = list(meta["kinds"])
        shardings = list(meta["shardings"])
        refs = dict(meta.get("refs", {}))
        dtypes = list(meta.get("dtypes") or [None] * len(kinds))
        if len(shardings) != len(kinds):
            raise ValueError(f"checkpoint {key}: leaf bookkeeping "
                             f"mismatch")
        leaves: List[tuple] = []
        for i, kind in enumerate(kinds):
            if kind == "dev":
                arr = np.asarray(z[f"leaf_{i:05d}"])
                if dtypes[i] and str(arr.dtype) != dtypes[i]:
                    # npz round-trips extension dtypes (ml_dtypes
                    # bfloat16) as opaque void bytes — re-view through
                    # the recorded dtype string, byte-identical
                    arr = arr.view(np.dtype(dtypes[i]))
                if arr.ndim < 1 or arr.shape[0] != 1:
                    raise ValueError(
                        f"checkpoint {key}: leaf {i} is not one row")
                leaves.append(("dev", arr, shardings[i]))
            elif kind == "ref":
                if str(i) not in refs:
                    raise ValueError(
                        f"checkpoint {key}: ref leaf {i} missing")
                leaves.append(("ref", refs[str(i)], None))
            else:
                raise ValueError(
                    f"checkpoint {key}: unknown leaf kind {kind!r}")
        ckpt = RowCheckpoint(
            fold_key=str(meta["fold_key"]),
            model_tag=str(meta["model_tag"]),
            age=int(meta["age"]),
            seq=np.asarray(z["seq"], np.int32),
            msa=(np.asarray(z["msa"], np.int32)
                 if meta.get("msa") else None),
            leaves=leaves, created_s=float(meta.get("created_s", 0.0)))
    if ckpt.age < 0 or ckpt.seq.ndim != 1:
        raise ValueError(f"checkpoint {key} fails validation")
    return ckpt


# -- the store -------------------------------------------------------------


class CheckpointStats:
    """Thread-safe outcome counters, mirrored into the registry as
    `fold_checkpoint_events_total{event=...}` (minted only when a
    store is constructed — a spill-off scheduler's metric-name set is
    untouched)."""

    FIELDS = ("spills", "spill_errors", "hits", "misses", "discards",
              "stale_tag_discards", "expirations", "disk_errors",
              "peer_hits", "backend_hits")

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        self._m_events = (registry or get_registry()).counter(
            "fold_checkpoint_events_total",
            "durable step-checkpoint store outcomes", ("event",))

    def bump(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
        self._m_events.inc(n, event=field)

    def event(self, event: str, n: int = 1):
        """Registry-only event, no snapshot field: occasional lifecycle
        events (orphan sweeps) ride the same metric family without
        widening FIELDS — snapshot()'s schema is pinned."""
        self._m_events.inc(n, event=event)

    def snapshot(self) -> dict:
        with self._lock:
            return {f: getattr(self, f) for f in self.FIELDS}


class CheckpointStore:
    """Durable (fold_key, model_tag, age)-keyed row checkpoints over a
    ByteStore disk tier, with optional object-store mirror and peer
    fallback.

    disk_dir: the spill directory (the `RetryPolicy(checkpoint_spill=)`
        knob's value). Required — a memory-only durable store is a
        contradiction.
    model_tag: the serving model identity; `latest`/`survivors` DISCARD
        any decoded payload whose tag differs (counted
        `stale_tag_discards`) — a rolled-out model never continues an
        old model's carry.
    ttl_s: disk TTL; swept on scan as well as get (the ISSUE-18
        ByteStore fix), so boot discovery never resurrects a fold
        nobody has asked about for ttl_s.
    backend: optional `fleet.object_store.ObjectStoreBackend` mirror —
        one object per fold GROUP (latest age wins), so a shared
        volume serves fail-over resume with zero peer servers.
    peer: optional duck-typed `fetch_checkpoint(group, model_tag) ->
        bytes | None` (fleet.peer.PeerCacheClient) consulted on local
        + backend miss.
    """

    def __init__(self, disk_dir: str, *, model_tag: str = "",
                 ttl_s: Optional[float] = None,
                 backend=None, peer=None,
                 registry: Optional[MetricsRegistry] = None,
                 clock=time.time):
        if not disk_dir:
            raise ValueError("CheckpointStore needs a disk_dir")
        self.model_tag = str(model_tag)
        self.backend = backend
        self.peer = peer
        self.stats = CheckpointStats(registry)
        self._clock = clock
        self.orphan_sweeps = 0         # groups GC'd by sweep_orphans
        self.store = ByteStore(
            encode=encode_checkpoint, decode=decode_checkpoint,
            max_bytes=0, max_entries=0,      # durable tier only
            ttl_s=ttl_s, disk_dir=disk_dir, clock=clock,
            on_event=self._on_store_event,
            quarantine_event="checkpoint_quarantine")

    def _on_store_event(self, fld: str, n: int = 1):
        if fld in ("expirations", "disk_errors"):
            self.stats.bump(fld, n)

    # -- keys --------------------------------------------------------------

    def group(self, fold_key: str) -> str:
        return checkpoint_group(fold_key, self.model_tag)

    # -- spill -------------------------------------------------------------

    def put_row(self, ckpt: RowCheckpoint) -> Optional[str]:
        """Spill one row checkpoint; prunes the group's older ages so
        the tier holds exactly the latest. Returns the store key, or
        None on failure (counted — a spill error must never fail the
        step loop it rode along with)."""
        try:
            key = checkpoint_key(ckpt.fold_key, self.model_tag,
                                 ckpt.age)
            self.store.disk_put(key, ckpt)
            prefix = self.group(ckpt.fold_key)
            for old in self.store.keys(prefix):
                if old != key:
                    self._remove(old)
            if self.backend is not None:
                try:
                    self.backend.put(prefix,
                                     encode_checkpoint(key, ckpt))
                except Exception:
                    pass               # mirror is best-effort
            self.stats.bump("spills")
            return key
        except Exception:
            self.stats.bump("spill_errors")
            return None

    # -- resume lookups ----------------------------------------------------

    def latest(self, fold_key: str,
               trace=NULL_TRACE) -> Optional[RowCheckpoint]:
        """Newest-age checkpoint for `fold_key` under THIS store's
        model tag: local disk, then the object-store mirror, then the
        peer tier. Stale-tag payloads (possible through mirror/peer
        bytes, impossible through local keys) are discarded."""
        prefix = self.group(fold_key)
        keys = self.store.keys(prefix)
        if keys:
            key = max(keys, key=key_age)
            hit = self.store.disk_get(key, trace)
            if hit is not None:
                ckpt, _expires = hit
                if self._tag_ok(ckpt):
                    self.stats.bump("hits")
                    return ckpt
                self._remove(key)
        for source, fetch in (("backend", self._backend_fetch),
                              ("peer", self._peer_fetch)):
            ckpt = fetch(fold_key, prefix, trace)
            if ckpt is not None:
                self.stats.bump(f"{source}_hits")
                self.stats.bump("hits")
                # promote: a migrated fold's next spill/discard is local
                self.put_row(ckpt)
                return ckpt
        self.stats.bump("misses")
        return None

    def _backend_fetch(self, fold_key: str, prefix: str,
                       trace) -> Optional[RowCheckpoint]:
        if self.backend is None:
            return None
        try:
            data = self.backend.get(prefix)
            if data is None:
                return None
            ckpt = decode_checkpoint(
                checkpoint_key(fold_key, self.model_tag,
                               _peek_age(data)), data)
        except Exception:
            # shared-store quarantine analogue: a corrupt object costs
            # every replica a failed parse until someone deletes it
            try:
                self.backend.delete(prefix)
            except Exception:
                pass
            self.stats.bump("disk_errors")
            return None
        if not self._tag_ok(ckpt) or ckpt.fold_key != fold_key:
            try:
                self.backend.delete(prefix)
            except Exception:
                pass
            return None
        trace.event("peer_fetch", peer="object_store", outcome="hit")
        return ckpt

    def _peer_fetch(self, fold_key: str, prefix: str,
                    trace) -> Optional[RowCheckpoint]:
        if self.peer is None:
            return None
        try:
            data = self.peer.fetch_checkpoint(prefix, self.model_tag)
            if data is None:
                return None
            ckpt = decode_checkpoint(
                checkpoint_key(fold_key, self.model_tag,
                               _peek_age(data)), data)
        except Exception:
            return None
        if not self._tag_ok(ckpt) or ckpt.fold_key != fold_key:
            return None
        return ckpt

    def latest_raw(self, group: str) -> Optional[bytes]:
        """Raw wire bytes of a group's newest checkpoint — the peer
        server's read path (`kind=checkpoint`), mirroring
        `FoldCache.read_raw`: the serving side never decodes."""
        keys = self.store.keys(group)
        if not keys:
            return None
        try:
            with open(self.store.path(max(keys, key=key_age)),
                      "rb") as fh:
                return fh.read()
        except OSError:
            return None

    # -- lifecycle ---------------------------------------------------------

    def discard(self, fold_key: str):
        """Drop every age of one fold (resolved, cancelled, or
        poisoned: the checkpoint must not outlive the work)."""
        prefix = self.group(fold_key)
        removed = 0
        for key in self.store.keys(prefix):
            removed += self._remove(key)
        if self.backend is not None:
            try:
                self.backend.delete(prefix)
            except Exception:
                pass
        if removed:
            self.stats.bump("discards", removed)

    def sweep_orphans(self, terminal_fold_keys) -> int:
        """GC beyond TTL (ISSUE 19): drop every checkpoint group whose
        fold key is in `terminal_fold_keys` — folds the ledger or the
        quarantine already recorded as finished for good (served,
        poisoned, permanently failed). TTL alone can strand these for
        hours: a bulk campaign's served fold has no reason to keep its
        mid-loop carry on disk until the clock runs out, and a
        quarantined key's checkpoint would only ever resume into
        another poisoning. Returns the number of GROUPS swept; counted
        as `fold_checkpoint_events_total{event="orphan_sweep"}` (the
        removed files themselves land in the ordinary `discards`
        counter via discard())."""
        swept = 0
        for fold_key in terminal_fold_keys:
            if not self.store.keys(self.group(fold_key)):
                continue
            self.discard(fold_key)
            swept += 1
        if swept:
            self.orphan_sweeps += swept
            self.stats.event("orphan_sweep", swept)
        return swept

    def survivors(self, trace=NULL_TRACE
                  ) -> Iterator[Tuple[str, RowCheckpoint]]:
        """Boot-time discovery: every (store_key, checkpoint) the disk
        tier holds under THIS model tag, newest age per group. Expired
        entries are swept by the scan itself; decoded payloads whose
        tag mismatches (an old tag's leftovers after a rollout) are
        discarded + counted, never yielded — a restarted replica can
        trust every survivor it sees."""
        newest: dict = {}
        for key in self.store.keys():
            group = key.rsplit("-a", 1)[0]
            prev = newest.get(group)
            if prev is None or key_age(key) > key_age(prev):
                newest[group] = key
        for group in sorted(newest):
            key = newest[group]
            hit = self.store.disk_get(key, trace)
            if hit is None:
                continue
            ckpt, _expires = hit
            if not self._tag_ok(ckpt):
                for stale in self.store.keys(group):
                    self._remove(stale)
                continue
            yield key, ckpt

    # -- orphan manifest (ISSUE 20) ---------------------------------------

    def publish_manifest(self, replica_id: str) -> Optional[dict]:
        """Preemption hand-off: enumerate every resumable survivor this
        store holds (newest age per group, current tag), make sure each
        is mirrored to the shared backend, and publish one JSON
        manifest under `manifest_key(replica_id)` so the controller can
        assign the orphans to a live survivor. Also written as a
        sibling disk file next to the checkpoints (debuggability: the
        spill directory is self-describing post-mortem). Returns the
        manifest dict, or None when there is nothing to hand off —
        publishing an empty manifest would only make every controller
        tick pay a read for a replica that owed nobody anything."""
        orphans = []
        for key, ckpt in self.survivors():
            group = key.rsplit("-a", 1)[0]
            if self.backend is not None:
                # spills mirror on put_row, but the backend may have
                # been attached after early spills — re-mirror so the
                # adopter's backend fetch cannot miss what we advertise
                try:
                    self.backend.put(group, encode_checkpoint(key, ckpt))
                except Exception:
                    pass
            orphans.append({"group": group,
                            "fold_key": ckpt.fold_key,
                            "age": int(ckpt.age),
                            "model_tag": ckpt.model_tag})
        if not orphans:
            return None
        manifest = {"schema": MANIFEST_SCHEMA,
                    "replica_id": str(replica_id),
                    "model_tag": self.model_tag,
                    "published_s": float(self._clock()),
                    "orphans": orphans}
        data = json.dumps(manifest).encode("utf-8")
        if self.backend is not None:
            try:
                self.backend.put(manifest_key(replica_id), data)
            except Exception:
                self.stats.bump("disk_errors")
        try:
            import os
            path = os.path.join(self.store.disk_dir,
                                f"orphans-{manifest_key(replica_id)}.json")
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(data)
            os.replace(tmp, path)
        except OSError:
            pass                  # the sibling copy is best-effort
        self.stats.event("manifest_published")
        return manifest

    # -- plumbing ----------------------------------------------------------

    def _tag_ok(self, ckpt: RowCheckpoint) -> bool:
        if ckpt.model_tag == self.model_tag:
            return True
        self.stats.bump("stale_tag_discards")
        return False

    def _remove(self, key: str) -> int:
        import os
        try:
            os.remove(self.store.path(key))
            return 1
        except OSError:
            return 0

    def snapshot(self) -> dict:
        out = {"model_tag": self.model_tag,
               "disk_dir": self.store.disk_dir,
               "resident_keys": len(self.store.keys()),
               "stats": self.stats.snapshot()}
        if self.orphan_sweeps:
            # only after a sweep: a GC-less store's snapshot stays
            # byte-identical to PR 18
            out["orphan_sweeps"] = self.orphan_sweeps
        return out


def _peek_age(data: bytes) -> int:
    """Age embedded in wire bytes (needed to reconstruct the exact
    store key a mirrored/peer payload was encoded under, so the codec's
    embedded-key check still bites on those tiers)."""
    with np.load(io.BytesIO(data)) as z:
        return int(json.loads(bytes(z["meta"]).decode("utf-8"))["age"])
