"""Sidechainnet-format mask/embedding utilities.

Parity with the reference's scn helpers
(/root/reference/alphafold2_pytorch/utils.py:423-495): per-residue atom
cloud masks over the 14-slot layout, backbone (N/CA/C) index masks, and
atom-id token embeddings — reimplemented as dense table lookups
(constants.CLOUD_MASK_TABLE / ATOM_ID_TABLE) so they are single gathers on
TPU instead of per-residue Python dict lookups.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from alphafold2_tpu import constants


def scn_cloud_mask(
    seq: jnp.ndarray,
    coords: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """(b, L) int tokens -> (b, L, 14) occupancy mask. If `coords`
    ((b, L, 14, 3) or (b, L*14, 3)) is given, derive the mask from nonzero
    coordinates instead (reference utils.py:423-455 `scn_cloud_mask` with
    coords)."""
    if coords is not None:
        if coords.ndim == 3:
            coords = coords.reshape(coords.shape[0], -1,
                                    constants.NUM_COORDS_PER_RES, 3)
        return (jnp.abs(coords).sum(-1) != 0).astype(jnp.float32)
    table = jnp.asarray(constants.CLOUD_MASK_TABLE)
    return table[seq]


def scn_backbone_mask(seq: jnp.ndarray, boolean: bool = True):
    """(b, L) -> masks over the flat (L*14,) atom cloud selecting N, CA, C
    (slots 0, 1, 2) (reference utils.py:457-477). Returns (n_mask, ca_mask,
    c_mask), each (b, L*14) bool or index arrays when boolean=False."""
    b, l = seq.shape
    k = constants.NUM_COORDS_PER_RES
    slot = np.tile(np.arange(k), l)
    n_mask = jnp.asarray(slot == 0)
    ca_mask = jnp.asarray(slot == 1)
    c_mask = jnp.asarray(slot == 2)
    if boolean:
        tile = lambda m: jnp.broadcast_to(m[None], (b, l * k))
        return tile(n_mask), tile(ca_mask), tile(c_mask)
    idx = lambda m: jnp.asarray(np.nonzero(np.asarray(m))[0])
    return idx(n_mask), idx(ca_mask), idx(c_mask)


def backbone_indices(seq_len: int):
    """Static (L,) index arrays of N/CA/C atoms in the flat L*14 cloud —
    the form `core.mds.mirror_fix` consumes."""
    k = constants.NUM_COORDS_PER_RES
    base = np.arange(seq_len) * k
    return (jnp.asarray(base), jnp.asarray(base + 1), jnp.asarray(base + 2))


def scn_atom_embedd(seq: jnp.ndarray) -> jnp.ndarray:
    """(b, L) -> (b, L, 14) atom-id tokens (reference utils.py:479-495)."""
    table = jnp.asarray(constants.ATOM_ID_TABLE)
    return table[seq]


def chain2atoms(x: jnp.ndarray, mask: Optional[jnp.ndarray] = None):
    """Expand per-residue features to per-atom (reference utils.py:417-421):
    (b, L, d) -> (b, L, 14, d)."""
    out = jnp.broadcast_to(
        x[..., None, :],
        (*x.shape[:-1], constants.NUM_COORDS_PER_RES, x.shape[-1]))
    if mask is not None:
        out = out * mask[..., None]
    return out
