"""trRosetta-style dataset: (a3m MSA, PDB structure) pairs from disk.

Parity with the reference's TrRosettaDataset / TrRosettaDataModule
(/root/reference/training_scripts/datasets/trrosetta.py:136-497): MSA
parsing, per-item featurized cache, query-preserving MSA subsampling,
contiguous crops, CA/CB bucketized distance maps, fixed-shape collation.
Differences by design:

- no tarball auto-download (the reference pulls 3.5 GB from S3 at
  trrosetta.py:91-114; this container is zero-egress) — point `root` at a
  directory of `<id>.a3m` + `<id>.pdb` (and/or `<id>.npz`) files;
- parsing runs through the native C++ loader (data/native.py) when built;
- featurized samples cache as .npz next to the data (the reference uses
  per-item pickle, trrosetta.py:178-200), named by a stable digest of
  the featurize config (utils.hashing.stable_digest) so a config change
  — e.g. max_msa_rows — misses cleanly instead of serving stale
  features;
- batches come out fixed-shape (static XLA shapes), not ragged-padded.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List

import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.data import featurize, native
from alphafold2_tpu.utils.hashing import stable_digest

# bump when the cached sample layout changes (keys, dtypes, semantics)
_FEAT_SCHEMA = "trrosetta-feat-v1"


class TrRosettaDataset:
    """Iterable dataset over featurized samples."""

    def __init__(self, root: str, cache: bool = True,
                 max_msa_rows: int = 1000):
        self.root = root
        self.cache = cache
        self.max_msa_rows = max_msa_rows
        # everything that changes the featurized content is in the name:
        # a different config misses and refeaturizes instead of loading
        # a stale cache written under other settings
        self._cache_tag = stable_digest(
            _FEAT_SCHEMA, max_msa_rows, digest_size=4)
        self.ids = sorted(
            os.path.splitext(f)[0] for f in os.listdir(root)
            if f.endswith(".a3m"))
        if not self.ids:
            raise FileNotFoundError(f"no .a3m files under {root}")

    def __len__(self) -> int:
        return len(self.ids)

    def _cache_path(self, sample_id: str) -> str:
        return os.path.join(
            self.root, f"{sample_id}.feat-{self._cache_tag}.npz")

    def load(self, sample_id: str) -> Dict[str, np.ndarray]:
        cpath = self._cache_path(sample_id)
        if self.cache and os.path.exists(cpath):
            with np.load(cpath) as z:
                return {k: z[k] for k in z.files}

        with open(os.path.join(self.root, f"{sample_id}.a3m")) as f:
            msa = native.parse_a3m(f.read()).astype(np.int32)
        msa = msa[: self.max_msa_rows]
        sample: Dict[str, np.ndarray] = {
            "seq": msa[0].copy(), "msa": msa}

        pdb_path = os.path.join(self.root, f"{sample_id}.pdb")
        npz_path = os.path.join(self.root, f"{sample_id}.npz")
        if os.path.exists(pdb_path):
            with open(pdb_path) as f:
                _, coords, mask = native.parse_pdb(f.read())
            n = min(len(coords), msa.shape[1])
            c14 = np.zeros((msa.shape[1], constants.NUM_COORDS_PER_RES, 3),
                           np.float32)
            c14[:n] = coords[:n] * mask[:n, :, None]
            sample["coords"] = c14
        elif os.path.exists(npz_path):
            with np.load(npz_path) as z:
                if "coords" in z.files:
                    sample["coords"] = z["coords"].astype(np.float32)

        if self.cache:
            np.savez_compressed(cpath, **sample)
        return sample

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        return self.load(self.ids[idx])


class TrRosettaDataModule:
    """Batched loader facade (the reference's Lightning DataModule analog,
    trrosetta.py:352-497) producing fixed-shape numpy batches."""

    def __init__(
        self,
        root: str,
        crop_len: int = 128,
        batch_size: int = 1,
        max_msa_rows: int = constants.MAX_NUM_MSA,
        val_fraction: float = 0.1,
        seed: int = 0,
    ):
        self.dataset = TrRosettaDataset(root)
        self.crop_len = crop_len
        self.batch_size = batch_size
        self.max_msa_rows = max_msa_rows
        rng = np.random.default_rng(seed)
        order = rng.permutation(len(self.dataset))
        n_val = max(1, int(len(order) * val_fraction)) \
            if len(order) > 1 else 0
        self.val_ids = [self.dataset.ids[i] for i in order[:n_val]]
        self.train_ids = [self.dataset.ids[i] for i in order[n_val:]]
        self._rng = rng

    def _batches(self, ids: List[str], shuffle: bool) -> Iterator[dict]:
        while True:
            order = list(ids)
            if shuffle:
                self._rng.shuffle(order)
            # fewer samples than a batch: cycle ids so one batch always
            # comes out (fixed batch shape for XLA)
            while 0 < len(order) < self.batch_size:
                order = order + list(ids)
            for start in range(0, len(order) - self.batch_size + 1,
                               self.batch_size):
                samples = [self.dataset.load(i)
                           for i in order[start:start + self.batch_size]]
                yield featurize.collate(samples, self.crop_len,
                                        self.max_msa_rows, self._rng)

    def train_batches(self) -> Iterator[dict]:
        return self._batches(self.train_ids, shuffle=True)

    def val_batches(self) -> Iterator[dict]:
        return self._batches(self.val_ids or self.train_ids, shuffle=False)
