"""PDB input/output utilities.

Parity with the reference's PDB helpers
(/root/reference/alphafold2_pytorch/utils.py:152-236): fetching entries
(`download_pdb`), chain cleaning, and writing predicted coordinates back
out (`coords2pdb` — there via sidechainnet's StructureBuilder). Reading
lives in data/native.py (C++ parser with Python fallback); writing is
implemented here directly — no BioPython/mdtraj dependency.
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional

import numpy as np

from alphafold2_tpu import constants


def download_pdb(name: str, route: str) -> str:
    """Fetch a PDB entry from RCSB (reference utils.py:152-160). Requires
    network access; raises RuntimeError in offline environments."""
    result = subprocess.run(
        ["curl", "-sf", f"https://files.rcsb.org/download/{name}.pdb",
         "-o", route], capture_output=True)
    if result.returncode != 0 or not os.path.exists(route):
        raise RuntimeError(f"download of {name} failed (offline?)")
    return route


def clean_pdb(name: str, route: Optional[str] = None,
              chain: Optional[str] = None) -> str:
    """Keep only ATOM records of the selected chain (first model); the
    reference's mdtraj-based clean (utils.py:162-190) without mdtraj."""
    destin = route if route is not None else name
    with open(name) as f:
        text = f.read()
    out_lines = []
    active = chain
    for line in text.splitlines():
        if line.startswith("ENDMDL"):
            break
        if line.startswith("ATOM") and len(line) >= 54:
            ch = line[21]
            if active is None:
                active = ch
            if ch == active:
                out_lines.append(line)
    with open(destin, "w") as f:
        f.write("\n".join(out_lines) + "\nEND\n")
    return destin


def coords2pdb(
    seq: np.ndarray,
    coords: np.ndarray,
    cloud_mask: Optional[np.ndarray] = None,
    prefix: str = "",
    name: str = "af2_struct.pdb",
) -> str:
    """Write a (L, 14, 3) scaffold (or (L, 3) CA trace) as PDB text
    (reference utils.py:223-236). Returns the written path."""
    seq = np.asarray(seq)
    coords = np.asarray(coords)
    if coords.ndim == 2:  # CA trace -> put in slot 1
        ca = coords
        coords = np.zeros((len(seq), constants.NUM_COORDS_PER_RES, 3),
                          dtype=np.float32)
        coords[:, 1] = ca
        cloud_mask = np.zeros(coords.shape[:2], dtype=bool)
        cloud_mask[:, 1] = True
    if cloud_mask is None:
        cloud_mask = np.abs(coords).sum(-1) != 0

    lines = []
    serial = 1
    for i, tok in enumerate(seq):
        aa = constants.AA_ALPHABET[int(tok)]
        if aa == "_":
            continue
        three = constants.ONE_TO_THREE[aa]
        atoms = constants.BACKBONE_ATOMS + constants.SIDECHAIN_ATOMS[three]
        for slot, atom in enumerate(atoms):
            if slot >= coords.shape[1] or not cloud_mask[i, slot]:
                continue
            x, y, z = coords[i, slot]
            element = atom[0]
            # strict PDB columns: atom 13-16, altLoc 17, resName 18-20,
            # chain 22, resSeq 23-26, coords 31-54, element 77-78
            lines.append(
                f"ATOM  {serial:5d} {atom:<4} {three:>3} A{i + 1:4d}    "
                f"{x:8.3f}{y:8.3f}{z:8.3f}  1.00  0.00          "
                f"{element:>2}")
            serial += 1
    lines.append("END")
    path = os.path.join(prefix, name) if prefix else name
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path
