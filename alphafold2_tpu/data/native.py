"""ctypes bindings for the native data loader (native/af2data.cc) with
pure-Python fallbacks.

The native library covers the host-side hot path: a3m/FASTA MSA parsing +
tokenization and PDB -> 14-slot coordinate extraction (the work the
reference delegates to BioPython/proDy/sidechainnet native cores,
SURVEY.md §2.4). `load_library()` builds on demand via native/Makefile;
every entry point transparently falls back to the Python implementation
when no compiler/library is available, so the package never hard-depends
on the native build.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.data import featurize

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           os.pardir, os.pardir, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libaf2data.so")

_lib = None
_lib_failed = False


def load_library(rebuild: bool = False):
    """Load (building if needed) libaf2data.so; returns None on failure."""
    global _lib, _lib_failed
    if _lib is not None and not rebuild:
        return _lib
    if _lib_failed and not rebuild:
        return None
    try:
        if rebuild or not os.path.exists(_LIB_PATH):
            subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                           capture_output=True)
        lib = ctypes.CDLL(_LIB_PATH)
        c = ctypes
        lib.msa_parse_a3m_size.restype = c.c_int
        lib.msa_parse_a3m_size.argtypes = [
            c.c_char_p, c.c_int64, c.POINTER(c.c_int64),
            c.POINTER(c.c_int64)]
        lib.msa_parse_a3m.restype = c.c_int
        lib.msa_parse_a3m.argtypes = [
            c.c_char_p, c.c_int64, c.POINTER(c.c_int8), c.c_int64, c.c_int64]
        lib.pdb_parse_size.restype = c.c_int
        lib.pdb_parse_size.argtypes = [
            c.c_char_p, c.c_int64, c.c_char, c.POINTER(c.c_int64)]
        lib.pdb_parse.restype = c.c_int
        lib.pdb_parse.argtypes = [
            c.c_char_p, c.c_int64, c.c_char, c.POINTER(c.c_int8),
            c.POINTER(c.c_float), c.POINTER(c.c_int8), c.c_int64]
        lib.tokenize_seq.restype = None
        lib.tokenize_seq.argtypes = [
            c.c_char_p, c.c_int64, c.POINTER(c.c_int8)]
        _lib = lib
        return lib
    except Exception:
        _lib_failed = True
        return None


def native_available() -> bool:
    return load_library() is not None


# ---------------------------------------------------------------------------
# MSA parsing
# ---------------------------------------------------------------------------


def parse_a3m(text: str) -> np.ndarray:
    """a3m/FASTA alignment text -> (rows, cols) int8 token matrix with
    insertions (lowercase, '.') removed and gaps mapped to padding."""
    lib = load_library()
    if lib is None:
        return _parse_a3m_py(text)
    raw = text.encode()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.msa_parse_a3m_size(raw, len(raw), ctypes.byref(rows),
                                ctypes.byref(cols))
    if rc != 0:
        raise ValueError(f"malformed a3m (code {rc})")
    out = np.empty((rows.value, cols.value), dtype=np.int8)
    rc = lib.msa_parse_a3m(raw, len(raw),
                           out.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
                           rows.value, cols.value)
    if rc != 0:
        raise ValueError(f"malformed a3m (code {rc})")
    return out


def _parse_a3m_py(text: str) -> np.ndarray:
    seqs = []
    cur = []
    started = False
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith(">"):
            if started and cur:
                seqs.append("".join(cur))
            started = True
            cur = []
        else:
            started = True
            cur.append(line)
    if started and cur:
        seqs.append("".join(cur))
    rows = []
    width = None
    for s in seqs:
        s = "".join(c for c in s if not (c.islower() or c == "."))
        if width is None:
            width = len(s)
        elif len(s) != width:
            raise ValueError("malformed a3m (code -2)")
        rows.append(featurize.tokenize(s).astype(np.int8))
    if not rows:
        return np.zeros((0, 0), dtype=np.int8)
    return np.stack(rows)


# ---------------------------------------------------------------------------
# PDB parsing
# ---------------------------------------------------------------------------


def parse_pdb(text: str, chain: Optional[str] = None
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """PDB text -> (seq tokens (L,), coords (L, 14, 3) float32,
    mask (L, 14) bool). First model; `chain` selects a chain id (default:
    first chain encountered)."""
    lib = load_library()
    if lib is None:
        return _parse_pdb_py(text, chain)
    raw = text.encode()
    ch = (chain or "\0").encode()[0]
    n_res = ctypes.c_int64()
    rc = lib.pdb_parse_size(raw, len(raw), ctypes.c_char(bytes([ch])),
                            ctypes.byref(n_res))
    if rc != 0:
        raise ValueError(f"malformed pdb (code {rc})")
    l = n_res.value
    seq = np.empty((l,), dtype=np.int8)
    coords = np.zeros((l, constants.NUM_COORDS_PER_RES, 3), dtype=np.float32)
    mask = np.zeros((l, constants.NUM_COORDS_PER_RES), dtype=np.int8)
    rc = lib.pdb_parse(raw, len(raw), ctypes.c_char(bytes([ch])),
                       seq.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
                       coords.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                       mask.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
                       l)
    if rc != 0:
        raise ValueError(f"malformed pdb (code {rc})")
    return seq.astype(np.int32), coords, mask.astype(bool)


def _parse_pdb_py(text: str, chain: Optional[str] = None):
    slots = {
        constants.ONE_TO_THREE[aa]:
            {name: i for i, name in enumerate(
                constants.BACKBONE_ATOMS +
                constants.SIDECHAIN_ATOMS[constants.ONE_TO_THREE[aa]])}
        for aa in constants.ONE_TO_THREE
    }
    def atoi(s: str) -> int:
        # C atoi semantics (af2data.cc pdb_parse uses atoi on cols 22-26):
        # leading whitespace skipped, parse signed digits, 0 on garbage
        s = s.strip()
        n = 0
        while n < len(s) and (s[n].isdigit() or (n == 0 and s[n] in "+-")):
            n += 1
        try:
            return int(s[:n])
        except ValueError:
            return 0

    # residue identity is *sequential* (resseq, icode) change-detection,
    # matching the native parser (af2data.cc pdb_parse): a residue id seen
    # again after an intervening one starts a NEW residue rather than
    # merging atoms into the earlier record, so both backends produce the
    # same length/sequence on interleaved or duplicated residue records
    residues = []
    last_key = None
    active = chain
    for line in text.splitlines():
        if line.startswith("ENDMDL"):
            break
        if not line.startswith("ATOM") or len(line) < 54:
            continue
        ch = line[21]
        if active is None:
            active = ch
        if ch != active or line[16] not in (" ", "A"):
            continue
        key = (atoi(line[22:26]), line[26])
        if key != last_key:
            last_key = key
            resname = line[17:20].strip()
            residues.append({"name": resname, "atoms": {}})
        atom = line[12:16].strip()
        residues[-1]["atoms"][atom] = (
            float(line[30:38]), float(line[38:46]), float(line[46:54]))

    l = len(residues)
    k = constants.NUM_COORDS_PER_RES
    seq = np.full((l,), featurize.AA_INDEX["_"], dtype=np.int32)
    coords = np.zeros((l, k, 3), dtype=np.float32)
    mask = np.zeros((l, k), dtype=bool)
    for i, res in enumerate(residues):
        one = constants.THREE_TO_ONE.get(res["name"])
        if one is not None:
            seq[i] = featurize.AA_INDEX[one]
        slot_map = slots.get(res["name"], {})
        for atom, xyz in res["atoms"].items():
            slot = slot_map.get(atom)
            if slot is not None:
                coords[i, slot] = xyz
                mask[i, slot] = True
    return seq, coords, mask
