"""Sidechainnet local-corpus adapter: the reference's primary training
data source, loadable from a locally mounted pickle.

Parity with the reference's `scn.load(casp_version=12, thinning=30,
with_pytorch='dataloaders', ...)` path (/root/reference/train_pre.py:37-47
and training_scripts/train_end2end.py) — minus the network: sidechainnet
downloads its pickles from an upstream bucket, which a zero-egress
container cannot do, so this module consumes the SAME pickle format from
a local path instead. A sidechainnet pickle is a dict of splits
('train', 'valid-10', ..., 'test'), each a dict of parallel lists:

  {'seq': [str AA sequence],        'crd': [(L*14, 3) float array],
   'msk': [str of '+'/'-'],         'ids': [str], ...}

(plus 'ang'/'evolutionary'/'secondary', unused here — the reference's
train_pre.py consumes exactly seq/crd/msk via batch.seqs/.crds/.msks).

For demos and tests without a mounted corpus, `corpus_from_pdb` builds a
split-dict of the same shape from PDB files (e.g. the 1H22 crystal
fixture under tests/data/), so the full train path runs on real
structure data end to end (scripts/train_distogram.py --scn / --pdb).
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.data import featurize, native

_SPLIT_KEYS = ("seq", "crd")


def load_scn_pickle(path: str) -> Dict[str, dict]:
    """Load a sidechainnet pickle; returns {split_name: split_dict} for
    every entry that looks like a data split (has seq + crd lists)."""
    with open(path, "rb") as f:
        raw = pickle.load(f)
    splits = {k: v for k, v in raw.items()
              if isinstance(v, dict) and all(x in v for x in _SPLIT_KEYS)}
    if not splits:
        raise ValueError(
            f"{path} contains no sidechainnet-format splits "
            f"(dicts with {_SPLIT_KEYS}); found keys {sorted(raw)[:10]}")
    return splits


def corpus_from_pdb(paths: Sequence[str]) -> dict:
    """PDB files -> one sidechainnet-format split dict (seq strings,
    (L*14, 3) coords, '+'/'-' masks), via the native PDB parser."""
    seqs, crds, msks, ids = [], [], [], []
    for p in paths:
        with open(p) as f:
            seq_tok, coords, mask = native.parse_pdb(f.read())
        seqs.append(featurize.detokenize(seq_tok))
        crds.append((coords * mask[:, :, None]).reshape(-1, 3)
                    .astype(np.float32))
        resolved = mask.any(-1)
        msks.append("".join("+" if r else "-" for r in resolved))
        ids.append(os.path.splitext(os.path.basename(p))[0])
    return {"seq": seqs, "crd": crds, "msk": msks, "ids": ids}


class SidechainnetDataset:
    """One split as featurize-ready samples.

    Items: {"seq": (L,) int tokens, "msa": (1, L) single-row MSA (scn has
    no MSAs; the reference likewise trains single-sequence from scn),
    "coords": (L, 14, 3) with unresolved residues zeroed} — the contract
    `featurize.collate` consumes.
    """

    def __init__(self, split: dict, max_len: Optional[int] = None):
        n = len(split["seq"])
        keep = [i for i in range(n)
                if max_len is None or len(split["seq"][i]) <= max_len]
        self.seqs: List[str] = [split["seq"][i] for i in keep]
        self.crds = [np.asarray(split["crd"][i], np.float32)
                     for i in keep]
        self.msks = [split.get("msk", [None] * n)[i] for i in keep]
        self.ids = [split.get("ids", list(map(str, range(n))))[i]
                    for i in keep]

    def __len__(self) -> int:
        return len(self.seqs)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        seq = featurize.tokenize(self.seqs[idx])
        length = len(seq)
        c14 = self.crds[idx].reshape(length, constants.NUM_COORDS_PER_RES, 3)
        if self.msks[idx] is not None:
            resolved = np.asarray([c == "+" for c in self.msks[idx]])
            c14 = c14 * resolved[:, None, None]
        return {"seq": seq, "msa": seq[None].copy(), "coords": c14}


class SidechainnetDataModule:
    """Batched loader facade matching TrRosettaDataModule's surface:
    fixed-shape numpy batches from a local sidechainnet pickle
    (reference train_pre.py's scn.load + DataLoader + cycle, :27-47).
    `max_len` mirrors the reference's THRESHOLD_LENGTH filter
    (train_pre.py:19 — it skips proteins over 250 residues)."""

    def __init__(
        self,
        path_or_splits,
        crop_len: int = 128,
        batch_size: int = 1,
        max_msa_rows: int = 1,
        max_len: Optional[int] = 250,
        train_split: str = "train",
        val_split: Optional[str] = None,
        seed: int = 0,
    ):
        splits = load_scn_pickle(path_or_splits) \
            if isinstance(path_or_splits, str) else dict(path_or_splits)
        if train_split not in splits:
            # demo corpora (corpus_from_pdb) are a bare split dict
            splits = {"train": splits} if all(
                k in splits for k in _SPLIT_KEYS) else splits
        if train_split not in splits:
            raise KeyError(f"split {train_split!r} not in "
                           f"{sorted(splits)}")
        self.train_ds = SidechainnetDataset(splits[train_split], max_len)
        if not len(self.train_ds):
            raise ValueError(f"split {train_split!r} has no proteins "
                             f"<= {max_len} residues")
        if val_split is not None and val_split not in splits:
            # an explicitly requested split must exist — silently serving
            # train data as "validation" hides the mistake
            raise KeyError(f"val_split {val_split!r} not in "
                           f"{sorted(splits)}")
        val = val_split or next(
            (k for k in sorted(splits) if k.startswith("valid")), None)
        self.val_ds = SidechainnetDataset(splits[val], max_len) \
            if val in splits else None
        if self.val_ds is not None and not len(self.val_ds):
            # post-filter emptiness: a val split whose proteins all
            # exceed max_len must fall back (an empty dataset would spin
            # _batches forever without yielding)
            self.val_ds = None
        self.crop_len = crop_len
        self.batch_size = batch_size
        self.max_msa_rows = max_msa_rows
        self._rng = np.random.default_rng(seed)

    def _batches(self, ds: SidechainnetDataset,
                 shuffle: bool) -> Iterator[dict]:
        while True:
            order = list(range(len(ds)))
            if shuffle:
                self._rng.shuffle(order)
            while 0 < len(order) < self.batch_size:
                order = order + order  # cycle; one fixed-shape batch min
            for start in range(0, len(order) - self.batch_size + 1,
                               self.batch_size):
                samples = [ds[i]
                           for i in order[start:start + self.batch_size]]
                yield featurize.collate(samples, self.crop_len,
                                        self.max_msa_rows, self._rng)

    def train_batches(self) -> Iterator[dict]:
        return self._batches(self.train_ds, shuffle=True)

    def val_batches(self) -> Iterator[dict]:
        return self._batches(self.val_ds or self.train_ds, shuffle=False)
