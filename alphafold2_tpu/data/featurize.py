"""Host-side featurization: tokenize, MSA subsample, crop, pad, distance
targets.

Parity with the reference's TrRosettaDataset featurization
(/root/reference/training_scripts/datasets/trrosetta.py:202-349): token
ids, MSA subsampling that always keeps the query row, contiguous cropping,
pad-and-mask collation, and CA/CB bucketized distance maps (36 x 0.5 A bins
from 2 A plus a far bucket) with the Gly virtual-CB built by a
Gram-Schmidt-style construction from N/CA/C.

Pure numpy on the host (out of the XLA graph — SURVEY.md §2.4's data/IO
rule); outputs are fixed-shape arrays ready for device upload.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from alphafold2_tpu import constants

AA_INDEX = {aa: i for i, aa in enumerate(constants.AA_ALPHABET)}
GAP_CHARS = "-."


def tokenize(seq: str) -> np.ndarray:
    """AA string -> int tokens; gaps and unknown characters map to the
    padding token (index of '_')."""
    pad = AA_INDEX["_"]
    return np.asarray([AA_INDEX.get(c, pad) if c not in GAP_CHARS else pad
                       for c in seq.upper()], dtype=np.int32)


def detokenize(tokens: Sequence[int]) -> str:
    return "".join(constants.AA_ALPHABET[t] for t in tokens)


def subsample_msa(
    msa_tokens: np.ndarray,
    max_rows: int,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Keep the query (first) row, sample the rest uniformly
    (reference trrosetta.py:284-296)."""
    rng = rng or np.random.default_rng()
    rows = msa_tokens.shape[0]
    if rows <= max_rows:
        return msa_tokens
    picked = rng.choice(np.arange(1, rows), size=max_rows - 1, replace=False)
    return np.concatenate([msa_tokens[:1], msa_tokens[np.sort(picked)]], 0)


def contiguous_crop(
    length: int,
    crop_len: int,
    rng: Optional[np.random.Generator] = None,
) -> slice:
    """Random contiguous crop window (reference trrosetta.py:268-282)."""
    if length <= crop_len:
        return slice(0, length)
    rng = rng or np.random.default_rng()
    start = int(rng.integers(0, length - crop_len + 1))
    return slice(start, start + crop_len)


def virtual_cb(n: np.ndarray, ca: np.ndarray, c: np.ndarray) -> np.ndarray:
    """Virtual C-beta from the backbone frame (the reference's
    Gram-Schmidt-style construction for Gly, trrosetta.py:229-266 region;
    standard trRosetta constants)."""
    b1 = ca - n
    b2 = c - ca
    b3 = np.cross(b1, b2)
    return -0.58273431 * b3 + 0.56802827 * b1 - 0.54067466 * b2 + ca


def distance_map_targets(
    coords14: np.ndarray,
    seq_tokens: np.ndarray,
    mask: np.ndarray,
    mode: str = "cb",
    num_buckets: int = 37,
    ignore_index: int = constants.IGNORE_INDEX,
) -> np.ndarray:
    """Bucketized distance targets from 14-slot coordinates
    (reference trrosetta.py:229-266): CA-CA or CB-CB (virtual CB for Gly /
    missing CB), 0.5 A bins from 2 A, last bucket = beyond-range.

    coords14: (L, 14, 3); seq_tokens: (L,); mask: (L,). Returns (L, L)."""
    n_at, ca, c_at = coords14[:, 0], coords14[:, 1], coords14[:, 2]
    if mode == "ca":
        points = ca
    else:
        cb = coords14[:, 4].copy()
        has_cb = (np.abs(cb).sum(-1) != 0) & \
            (seq_tokens != AA_INDEX["G"]) & (seq_tokens != AA_INDEX["_"])
        vcb = virtual_cb(n_at, ca, c_at)
        points = np.where(has_cb[:, None], cb, vcb)

    diff = points[:, None, :] - points[None, :, :]
    dist = np.sqrt((diff ** 2).sum(-1))
    boundaries = np.linspace(2.0, 20.0, num_buckets)[:-1]
    buckets = np.searchsorted(boundaries, dist, side="left")
    pair_mask = mask[:, None] & mask[None, :]
    return np.where(pair_mask, buckets, ignore_index).astype(np.int32)


def collate(
    samples: List[Dict[str, np.ndarray]],
    crop_len: int,
    max_msa_rows: int = constants.MAX_NUM_MSA,
    rng: Optional[np.random.Generator] = None,
) -> Dict[str, np.ndarray]:
    """Crop + pad a list of samples into one fixed-shape batch
    (reference trrosetta.py:298-349, made static-shape for XLA).

    Each sample: {"seq": (L,), "msa": (R, L) optional, "coords": (L, 14, 3)
    optional}. Output keys mirror the model's forward contract."""
    rng = rng or np.random.default_rng()
    b = len(samples)
    out: Dict[str, np.ndarray] = {
        "seq": np.zeros((b, crop_len), np.int32),
        "mask": np.zeros((b, crop_len), bool),
    }
    any_msa = any("msa" in s for s in samples)
    any_coords = any("coords" in s for s in samples)
    if any_msa:
        out["msa"] = np.zeros((b, max_msa_rows, crop_len), np.int32)
        out["msa_mask"] = np.zeros((b, max_msa_rows, crop_len), bool)
    if any_coords:
        out["coords14"] = np.zeros((b, crop_len, 14, 3), np.float32)
        out["coords"] = np.zeros((b, crop_len, 3), np.float32)
        out["dist"] = np.full((b, crop_len, crop_len), constants.IGNORE_INDEX,
                              np.int32)

    for i, s in enumerate(samples):
        length = len(s["seq"])
        window = contiguous_crop(length, crop_len, rng)
        n = window.stop - window.start
        out["seq"][i, :n] = s["seq"][window]
        out["mask"][i, :n] = True
        if "msa" in s:
            msa = subsample_msa(s["msa"], max_msa_rows, rng)[:, window]
            out["msa"][i, :msa.shape[0], :n] = msa
            out["msa_mask"][i, :msa.shape[0], :n] = True
        if "coords" in s:
            c14 = s["coords"][window]
            out["coords14"][i, :n] = c14
            out["coords"][i, :n] = c14[:, 1]  # CA track
            # residues with all-zero coordinates (unresolved, sidechainnet
            # convention) must not produce supervised distance targets
            resolved = np.abs(c14).sum((-1, -2)) != 0
            out["dist"][i, :n, :n] = distance_map_targets(
                c14, s["seq"][window], resolved)
    return out
