from alphafold2_tpu.data.synthetic import pad_to, synthetic_batch  # noqa: F401
