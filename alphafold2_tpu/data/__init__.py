from alphafold2_tpu.data import (  # noqa: F401
    featurize,
    graph,
    native,
    pdb_io,
    scn,
    sidechainnet,
    trrosetta,
)
from alphafold2_tpu.data.featurize import (  # noqa: F401
    collate,
    distance_map_targets,
    subsample_msa,
    tokenize,
)
from alphafold2_tpu.data.graph import (  # noqa: F401
    mat_input_to_masked,
    nth_deg_adjacency,
    prot_covalent_bond,
)
from alphafold2_tpu.data.scn import (  # noqa: F401
    chain2atoms,
    scn_atom_embedd,
    scn_backbone_mask,
    scn_cloud_mask,
)
from alphafold2_tpu.data.sidechainnet import (  # noqa: F401
    SidechainnetDataModule,
    SidechainnetDataset,
    corpus_from_pdb,
    load_scn_pickle,
)
from alphafold2_tpu.data.synthetic import (  # noqa: F401
    pad_to,
    synthetic_batch,
    synthetic_requests,
)
