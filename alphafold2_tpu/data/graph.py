"""Protein graph utilities.

Parity with the reference's graph layer
(/root/reference/alphafold2_pytorch/utils.py:497-650): covalent-bond
adjacency built from the per-AA bond tables, n-th degree adjacency by
repeated matmul, and padded-batch -> flat graph conversion. TPU-first:
everything is dense and static-shaped — protein graphs are tiny (L*14
nodes), so dense matmul adjacency powers beat the reference's
torch-sparse path on an accelerator (and need no native sparse dep,
SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from alphafold2_tpu import constants


def prot_covalent_bond(
    seq: jnp.ndarray,
    include_peptide_bonds: bool = True,
) -> jnp.ndarray:
    """(b, L) tokens -> (b, L*14, L*14) covalent-bond adjacency
    (reference utils.py:604-650). Intra-residue bonds come from the dense
    BOND_ADJACENCY_TABLE; inter-residue peptide bonds connect C(i)->N(i+1).
    """
    b, l = seq.shape
    k = constants.NUM_COORDS_PER_RES
    n = l * k

    intra = jnp.asarray(constants.BOND_ADJACENCY_TABLE)[seq]  # (b, l, 14, 14)
    adj = jnp.zeros((b, n, n), intra.dtype)
    # scatter each residue's block onto the diagonal
    res_base = jnp.arange(l) * k
    rows = (res_base[:, None, None] + jnp.arange(k)[None, :, None])
    cols = (res_base[:, None, None] + jnp.arange(k)[None, None, :])
    adj = adj.at[:, rows, cols].set(intra)

    if include_peptide_bonds and l > 1:
        c_idx = res_base[:-1] + 2   # C of residue i
        n_idx = res_base[1:]        # N of residue i+1
        adj = adj.at[:, c_idx, n_idx].set(1.0)
        adj = adj.at[:, n_idx, c_idx].set(1.0)
    return adj


def nth_deg_adjacency(
    adj: jnp.ndarray,
    n: int = 1,
    sparse: bool = False,  # kept for API parity; dense is the TPU path
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Neighbors at exactly degree <= n, with the degree recorded
    (reference utils.py:564-602). Returns (attr_mat, hops):
    attr_mat[i, j] = smallest hop count (0 if unreachable within n)."""
    del sparse
    attr = adj
    hops = (adj > 0).astype(adj.dtype)
    power = adj
    for deg in range(2, n + 1):
        power = jnp.clip(power @ adj, 0.0, 1.0)
        new = (power > 0) & (hops == 0)
        hops = hops + new.astype(adj.dtype) * deg
        attr = jnp.where(new, power * deg, attr)
    return attr, hops


def mat_input_to_masked(
    x: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    edges_mat: Optional[jnp.ndarray] = None,
):
    """Padded batch -> flat node/edge tensors (reference utils.py:497-560),
    static-shape variant: instead of compacting to ragged lists (impossible
    under XLA), returns flat nodes with a validity mask and dense edge
    (adjacency) matrices plus an edge mask.

    x: (b, N, d); mask: (b, N) bool; edges_mat: (b, N, N).
    Returns (nodes (b*N, d), node_mask (b*N,), edges (b, N, N),
    edge_mask (b, N, N))."""
    b, n, d = x.shape
    nodes = x.reshape(b * n, d)
    node_mask = (jnp.ones((b, n), bool) if mask is None else mask
                 ).reshape(b * n)
    if edges_mat is None:
        return nodes, node_mask, None, None
    m = mask if mask is not None else jnp.ones((b, n), bool)
    edge_mask = m[:, :, None] & m[:, None, :] & (edges_mat > 0)
    return nodes, node_mask, edges_mat, edge_mask


# ---------------------------------------------------------------------------
# Static-degree covalent neighbor list (no dense (N, N) adjacency)
# ---------------------------------------------------------------------------

_INTRA_TABLES = None


def _intra_neighbor_tables():
    """(21, 14, 3) local neighbor-slot ids + mask from the bond table
    (max intra-residue heavy-atom degree in the 14-slot layout is 3)."""
    global _INTRA_TABLES
    if _INTRA_TABLES is None:
        import numpy as np
        t = np.asarray(constants.BOND_ADJACENCY_TABLE)
        k_intra = int((t > 0).sum(-1).max())
        idx = np.zeros((*t.shape[:2], k_intra), np.int32)
        msk = np.zeros((*t.shape[:2], k_intra), np.float32)
        for a in range(t.shape[0]):
            for s in range(t.shape[1]):
                nb = np.nonzero(t[a, s])[0]
                idx[a, s, :len(nb)] = nb
                msk[a, s, :len(nb)] = 1.0
        _INTRA_TABLES = (idx, msk)
    return _INTRA_TABLES


def covalent_neighbor_table(
    seq: jnp.ndarray,
    include_peptide_bonds: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(b, L) tokens -> neighbor list over the flat L*14 atom cloud:
    (idx (b, L*14, 4), mask (b, L*14, 4)).

    The O(N*K) form of `prot_covalent_bond` (same bonds: per-AA table
    intra-residue, C(i)<->N(i+1) peptide) for consumers that only need
    each atom's <=4 bonded partners — building the dense (N, N)
    adjacency and top_k-ing it costs O(N^2) memory for a degree-<=4
    graph (822 MB/batch at 1024 res; r05 review). Slots are [3 intra
    bonds | 1 peptide bond], masked where absent."""
    import numpy as np

    b, l = seq.shape
    k = constants.NUM_COORDS_PER_RES
    intra_idx, intra_mask = _intra_neighbor_tables()
    li = jnp.asarray(intra_idx)[seq]                  # (b, l, 14, 3)
    lm = jnp.asarray(intra_mask)[seq]
    base = (jnp.arange(l) * k)[None, :, None, None]
    gidx = (li + base).reshape(b, l * k, -1)
    gmask = lm.reshape(b, l * k, -1)

    # peptide column is sequence-independent: N slot 0 bonds back to
    # C (slot 2) of residue i-1; C slot 2 bonds forward to N of i+1
    pep = np.zeros((l, k), np.int32)
    pmask = np.zeros((l, k), np.float32)
    if include_peptide_bonds and l > 1:
        rows = np.arange(l)
        pep[1:, 0] = (rows[1:] - 1) * k + 2
        pmask[1:, 0] = 1.0
        pep[:-1, 2] = (rows[:-1] + 1) * k
        pmask[:-1, 2] = 1.0
    pep_idx = jnp.broadcast_to(jnp.asarray(pep).reshape(1, l * k, 1),
                               (b, l * k, 1))
    pep_mask = jnp.broadcast_to(jnp.asarray(pmask).reshape(1, l * k, 1),
                                (b, l * k, 1))
    return (jnp.concatenate([gidx, pep_idx], axis=-1),
            jnp.concatenate([gmask, pep_mask], axis=-1))
