"""Protein graph utilities.

Parity with the reference's graph layer
(/root/reference/alphafold2_pytorch/utils.py:497-650): covalent-bond
adjacency built from the per-AA bond tables, n-th degree adjacency by
repeated matmul, and padded-batch -> flat graph conversion. TPU-first:
everything is dense and static-shaped — protein graphs are tiny (L*14
nodes), so dense matmul adjacency powers beat the reference's
torch-sparse path on an accelerator (and need no native sparse dep,
SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from alphafold2_tpu import constants


def prot_covalent_bond(
    seq: jnp.ndarray,
    include_peptide_bonds: bool = True,
) -> jnp.ndarray:
    """(b, L) tokens -> (b, L*14, L*14) covalent-bond adjacency
    (reference utils.py:604-650). Intra-residue bonds come from the dense
    BOND_ADJACENCY_TABLE; inter-residue peptide bonds connect C(i)->N(i+1).
    """
    b, l = seq.shape
    k = constants.NUM_COORDS_PER_RES
    n = l * k

    intra = jnp.asarray(constants.BOND_ADJACENCY_TABLE)[seq]  # (b, l, 14, 14)
    adj = jnp.zeros((b, n, n), intra.dtype)
    # scatter each residue's block onto the diagonal
    res_base = jnp.arange(l) * k
    rows = (res_base[:, None, None] + jnp.arange(k)[None, :, None])
    cols = (res_base[:, None, None] + jnp.arange(k)[None, None, :])
    adj = adj.at[:, rows, cols].set(intra)

    if include_peptide_bonds and l > 1:
        c_idx = res_base[:-1] + 2   # C of residue i
        n_idx = res_base[1:]        # N of residue i+1
        adj = adj.at[:, c_idx, n_idx].set(1.0)
        adj = adj.at[:, n_idx, c_idx].set(1.0)
    return adj


def nth_deg_adjacency(
    adj: jnp.ndarray,
    n: int = 1,
    sparse: bool = False,  # kept for API parity; dense is the TPU path
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Neighbors at exactly degree <= n, with the degree recorded
    (reference utils.py:564-602). Returns (attr_mat, hops):
    attr_mat[i, j] = smallest hop count (0 if unreachable within n)."""
    del sparse
    attr = adj
    hops = (adj > 0).astype(adj.dtype)
    power = adj
    for deg in range(2, n + 1):
        power = jnp.clip(power @ adj, 0.0, 1.0)
        new = (power > 0) & (hops == 0)
        hops = hops + new.astype(adj.dtype) * deg
        attr = jnp.where(new, power * deg, attr)
    return attr, hops


def mat_input_to_masked(
    x: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    edges_mat: Optional[jnp.ndarray] = None,
):
    """Padded batch -> flat node/edge tensors (reference utils.py:497-560),
    static-shape variant: instead of compacting to ragged lists (impossible
    under XLA), returns flat nodes with a validity mask and dense edge
    (adjacency) matrices plus an edge mask.

    x: (b, N, d); mask: (b, N) bool; edges_mat: (b, N, N).
    Returns (nodes (b*N, d), node_mask (b*N,), edges (b, N, N),
    edge_mask (b, N, N))."""
    b, n, d = x.shape
    nodes = x.reshape(b * n, d)
    node_mask = (jnp.ones((b, n), bool) if mask is None else mask
                 ).reshape(b * n)
    if edges_mat is None:
        return nodes, node_mask, None, None
    m = mask if mask is not None else jnp.ones((b, n), bool)
    edge_mask = m[:, :, None] & m[:, None, :] & (edges_mat > 0)
    return nodes, node_mask, edges_mat, edge_mask
