"""Synthetic batch generation for tests and benchmarks.

Mirrors the reference's random-tensor test pattern
(/root/reference/tests/test_attention.py:16-19) and provides fixed-shape
batches: on TPU every shape must be static (SURVEY.md §2.5 batch strategy),
so the generator emits crop-sized tensors directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu import constants


def synthetic_batch(
    rng: jax.Array,
    batch: int = 1,
    seq_len: int = 128,
    msa_depth: int = 5,
    with_coords: bool = True,
    with_angles: bool = False,
    pad_fraction: float = 0.0,
):
    """Returns a dict batch with keys seq, msa, mask, msa_mask and optional
    coords (CA, (b, n, 3)) / theta/phi/omega bucket targets."""
    k_seq, k_msa, k_coords, k_ang = jax.random.split(rng, 4)
    out = {
        "seq": jax.random.randint(k_seq, (batch, seq_len), 0,
                                  constants.NUM_AMINO_ACIDS),
        "msa": jax.random.randint(k_msa, (batch, msa_depth, seq_len), 0,
                                  constants.NUM_AMINO_ACIDS),
    }
    n_valid = seq_len - int(seq_len * pad_fraction)
    mask = jnp.arange(seq_len)[None, :] < n_valid
    out["mask"] = jnp.broadcast_to(mask, (batch, seq_len))
    out["msa_mask"] = jnp.broadcast_to(mask[:, None, :],
                                       (batch, msa_depth, seq_len))
    if with_coords:
        # random-walk chain ~3.8 A steps: realistic distance distribution
        steps = jax.random.normal(k_coords, (batch, seq_len, 3))
        steps = steps / jnp.linalg.norm(steps, axis=-1, keepdims=True) * 3.8
        out["coords"] = jnp.cumsum(steps, axis=1)
    if with_angles:
        ks = jax.random.split(k_ang, 3)
        for key, name, buckets in (
            (ks[0], "theta", constants.THETA_BUCKETS),
            (ks[1], "phi", constants.PHI_BUCKETS),
            (ks[2], "omega", constants.OMEGA_BUCKETS),
        ):
            out[name] = jax.random.randint(
                key, (batch, seq_len, seq_len), 0, buckets)
    return out


def synthetic_requests(
    rng: jax.Array,
    num: int = 32,
    lengths=(24, 48, 96),
    msa_depth: int = 3,
    deadline_s=None,
    priority_levels: int = 1,
):
    """Random mixed-length `serve.FoldRequest`s for load tests.

    Lengths cycle through `lengths` (deterministic coverage of every
    bucket regardless of `num`); tokens are random as in
    synthetic_batch. msa_depth=0 emits MSA-free requests.
    """
    import numpy as np

    from alphafold2_tpu.serve.request import FoldRequest  # lazy: no cycle

    requests = []
    for i in range(num):
        k_seq, k_msa, rng = jax.random.split(rng, 3)
        n = int(lengths[i % len(lengths)])
        seq = np.asarray(jax.random.randint(
            k_seq, (n,), 0, constants.NUM_AMINO_ACIDS))
        msa = None
        if msa_depth > 0:
            msa = np.asarray(jax.random.randint(
                k_msa, (msa_depth, n), 0, constants.NUM_AMINO_ACIDS))
        requests.append(FoldRequest(
            seq=seq, msa=msa, deadline_s=deadline_s,
            priority=i % max(priority_levels, 1)))
    return requests


def pad_to(x: jnp.ndarray, target_len: int, axis: int = 1,
           value: float = 0) -> jnp.ndarray:
    """Pad one axis to a fixed crop size (static-shape discipline)."""
    pad = target_len - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)
