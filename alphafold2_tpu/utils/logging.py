"""Metrics logging.

Net-new vs the reference's `print('loss:', ...)` (SURVEY.md §5.5;
train_pre.py:93): structured scalar logging to stdout and/or a JSONL file,
compatible with `train.fit(logger=...)`.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import IO, Optional


class MetricsLogger:
    """log(step=..., **scalars) -> one JSONL record (+ pretty stdout).

    Values are scalars, or ONE level of dict-of-scalars for grouped
    sections (e.g. the serving cache section: `cache={"hits": 3, ...}`
    emits a nested object and pretty-prints as `cache.hits=3`).
    """

    def __init__(self, path: Optional[str] = None, stdout: bool = True):
        self.stdout = stdout
        self._fh: Optional[IO] = None
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            self._fh = open(path, "a")
        self._t0 = time.time()

    @staticmethod
    def _scalar(v):
        return v if isinstance(v, (str, type(None))) else float(v)

    def log(self, step: int, **scalars):
        record = {"step": int(step),
                  "wall_s": round(time.time() - self._t0, 3)}
        for k, v in scalars.items():
            record[k] = ({k2: self._scalar(v2) for k2, v2 in v.items()}
                         if isinstance(v, dict) else self._scalar(v))
        if self._fh is not None:
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()
        if self.stdout:
            flat = {}
            for k, v in record.items():
                if k in ("step", "wall_s"):
                    continue
                if isinstance(v, dict):
                    flat.update({f"{k}.{k2}": v2 for k2, v2 in v.items()})
                else:
                    flat[k] = v
            parts = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in flat.items())
            print(f"[step {record['step']:>6}] {parts}", file=sys.stdout,
                  flush=True)
        return record

    def flush(self):
        if self._fh is not None:
            self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
