"""Metrics logging.

Net-new vs the reference's `print('loss:', ...)` (SURVEY.md §5.5;
train_pre.py:93): structured scalar logging to stdout and/or a JSONL file,
compatible with `train.fit(logger=...)`.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import IO, Optional


class MetricsLogger:
    """log(step=..., **scalars) -> one JSONL record (+ pretty stdout)."""

    def __init__(self, path: Optional[str] = None, stdout: bool = True):
        self.stdout = stdout
        self._fh: Optional[IO] = None
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            self._fh = open(path, "a")
        self._t0 = time.time()

    def log(self, step: int, **scalars):
        record = {"step": int(step),
                  "wall_s": round(time.time() - self._t0, 3)}
        record.update({k: float(v) for k, v in scalars.items()})
        if self._fh is not None:
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()
        if self.stdout:
            parts = " ".join(f"{k}={v:.4g}" for k, v in record.items()
                             if k not in ("step", "wall_s"))
            print(f"[step {record['step']:>6}] {parts}", file=sys.stdout,
                  flush=True)
        return record

    def flush(self):
        if self._fh is not None:
            self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
