"""Metrics logging.

Net-new vs the reference's `print('loss:', ...)` (SURVEY.md §5.5;
train_pre.py:93): structured scalar logging to stdout and/or a JSONL file,
compatible with `train.fit(logger=...)`.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import IO, Optional


class MetricsLogger:
    """log(step=..., **scalars) -> one JSONL record (+ pretty stdout).

    Values are scalars or arbitrarily nested dicts of scalars (grouped
    sections, e.g. the serving cache section: `cache={"disk": {"hits":
    3}}` emits the nested object in the JSONL record and pretty-prints
    as `cache.disk.hits=3` via obs.export.flatten). Every record
    carries the shared observability `"schema": 1` version field
    (obs/export.py; see MIGRATING) so consumers can reject records
    they do not understand.
    """

    def __init__(self, path: Optional[str] = None, stdout: bool = True):
        self.stdout = stdout
        self._fh: Optional[IO] = None
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            self._fh = open(path, "a")
        self._t0 = time.time()

    @staticmethod
    def _scalar(v):
        return v if isinstance(v, (str, type(None))) else float(v)

    @classmethod
    def _convert(cls, v):
        """Scalar coercion at arbitrary nesting depth."""
        if isinstance(v, dict):
            return {k: cls._convert(v2) for k, v2 in v.items()}
        return cls._scalar(v)

    def log(self, step: int, **scalars):
        from alphafold2_tpu.obs.export import SCHEMA_VERSION, flatten

        record = {"schema": SCHEMA_VERSION, "step": int(step),
                  "wall_s": round(time.time() - self._t0, 3)}
        for k, v in scalars.items():
            record[k] = self._convert(v)
        if self._fh is not None:
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()
        if self.stdout:
            flat = flatten({k: v for k, v in record.items()
                            if k not in ("schema", "step", "wall_s")})
            parts = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in flat.items())
            print(f"[step {record['step']:>6}] {parts}", file=sys.stdout,
                  flush=True)
        return record

    def flush(self):
        if self._fh is not None:
            self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
