from alphafold2_tpu.utils.logging import MetricsLogger  # noqa: F401
from alphafold2_tpu.utils.profiling import StepTimer, annotate, trace  # noqa: F401
