from alphafold2_tpu.utils.hashing import stable_digest  # noqa: F401
from alphafold2_tpu.utils.logging import MetricsLogger  # noqa: F401
from alphafold2_tpu.utils.profiling import (  # noqa: F401
    StepTimer,
    annotate,
    percentile,
    trace,
)
