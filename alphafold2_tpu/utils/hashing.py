"""Stable content digests shared by every cache in the tree.

Python's builtin `hash()` is salted per process and `repr()`-based keys
drift with dtype/printing changes, so anything persisted to disk (fold
result cache, trrosetta featurize cache) or compared across processes
needs one canonical digest. `stable_digest` is blake2b over a
type-tagged encoding of each part: arrays contribute dtype + shape +
raw bytes (so an int32 and int64 view of the same values differ, as
they must — they trace to different XLA programs), scalars and strings
contribute their tag + utf-8 form, and None is its own tag (distinct
from 0, "", and the empty array). Nested tuples/lists frame their
items, so ("ab",) and ("a", "b") cannot collide.
"""

from __future__ import annotations

import hashlib
from typing import Any

import numpy as np


def _feed(h, part: Any):
    if part is None:
        h.update(b"\x00N")
    elif isinstance(part, bytes):
        h.update(b"\x00B" + len(part).to_bytes(8, "little"))
        h.update(part)
    elif isinstance(part, str):
        _feed(h, part.encode("utf-8"))
        h.update(b"S")                 # distinguish str from raw bytes
    elif isinstance(part, bool):       # before int: bool is an int subclass
        h.update(b"\x00b" + (b"1" if part else b"0"))
    elif isinstance(part, (int, np.integer)):
        h.update(b"\x00i" + str(int(part)).encode())
    elif isinstance(part, (float, np.floating)):
        h.update(b"\x00f" + repr(float(part)).encode())
    elif isinstance(part, (tuple, list)):
        h.update(b"\x00T" + len(part).to_bytes(8, "little"))
        for item in part:
            _feed(h, item)
        h.update(b"t")
    else:
        # ndarray or anything array-like (jax arrays land here too)
        arr = np.asarray(part)
        if arr.dtype.hasobject:
            # an object array's .tobytes() is MEMORY ADDRESSES: two
            # equal dicts digest differently while alive and two
            # different ones can collide after address reuse. Refuse
            # loudly so callers fall back to not caching.
            raise TypeError(
                f"stable_digest cannot content-hash {type(part).__name__}"
                f" (object dtype); pass bytes/str/numbers/arrays or "
                f"nested tuples/lists of those")
        h.update(b"\x00A")
        _feed(h, str(arr.dtype))
        _feed(h, arr.shape)
        h.update(np.ascontiguousarray(arr).tobytes())


def stable_digest(*parts: Any, digest_size: int = 16) -> str:
    """Hex blake2b digest of `parts`, stable across processes and runs.

    Accepts None / bytes / str / bool / int / float / array-likes and
    nested tuples or lists of those. Order matters; type matters
    (1 != 1.0 != "1" != np.int32(1)-as-array).
    """
    h = hashlib.blake2b(digest_size=digest_size)
    for part in parts:
        _feed(h, part)
    return h.hexdigest()
