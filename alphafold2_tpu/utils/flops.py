"""Analytic FLOP accounting for benchmark/MFU claims (round-4 VERDICT #2).

Why not XLA ``cost_analysis``: it cannot see through custom calls — the
AMX FFI GEMMs on the CPU path and ``pallas_call`` kernels on TPU simply
vanish from its flop count (observed: reported tflops *fell* 10x when the
AMX kernels made the step 2x faster). Any MFU computed from it is wrong
exactly when the fast path is engaged.

The model here is analytic and backend-independent: trace the FORWARD
loss function once with every custom kernel disabled (pure
``dot_general``/``conv`` jaxpr — the trace is only counted, never run),
walk the jaxpr counting matmul/conv FLOPs, and charge the training step

    F_step = 3 x F_forward

— the standard accounting where each matmul's backward is two matmuls of
equal cost (input-grad + weight-grad). Elementwise/softmax/LN work is
excluded (negligible next to the contractions, and excluded by the MFU
convention), and rematerialized recompute is excluded BY CONSTRUCTION
(the forward trace contains each op once), so the resulting figure is
model FLOPs — the "MFU" numerator — not hardware FLOPs ("HFU"). The same
count applies to AMX-on/AMX-off/Pallas runs of one config by definition,
which is the agreement property the round-4 verdict demanded.

`lax.scan` bodies are counted once and multiplied by trip count;
`lax.cond` charges the most expensive branch; `shard_map` bodies count
per-device work times the number of devices doing DISTINCT work (mesh
axes appearing in the in/out specs — axes the operands are replicated
over are hardware redundancy, not model FLOPs); `while_loop` bodies are
charged for ONE trip (no static trip count exists — none of the benched
models put contractions in a while body; documented limitation).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.extend import core as jax_core


def _prod(xs) -> float:
    out = 1.0
    for x in xs:
        out *= float(x)
    return out


def _dot_general_flops(eqn) -> float:
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = _prod(lhs[i] for i in lb)
    k = _prod(lhs[i] for i in lc)
    m = _prod(d for i, d in enumerate(lhs) if i not in set(lc) | set(lb))
    n = _prod(d for i, d in enumerate(rhs) if i not in set(rc) | set(_rb))
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval.shape
    kernel = eqn.invars[1].aval.shape
    dn = eqn.params["dimension_numbers"]
    # kernel's in-channel dim already holds C_in/groups
    rhs_spec = dn.rhs_spec  # (out_c, in_c, *spatial) positions
    in_c = kernel[rhs_spec[1]]
    spatial = _prod(kernel[i] for i in rhs_spec[2:])
    return 2.0 * _prod(out) * in_c * spatial


def _iter_sub_jaxprs(params):
    for v in params.values():
        if isinstance(v, jax_core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax_core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                if isinstance(x, jax_core.ClosedJaxpr):
                    yield x.jaxpr
                elif isinstance(x, jax_core.Jaxpr):
                    yield x


def _shard_map_multiplier(params) -> float:
    """Number of devices doing DISTINCT work in a shard_map: the product
    of the sizes of mesh axes that actually appear in an in/out spec.
    Axes the operands are not sharded over hold replicas — replicated
    compute is hardware work, not model FLOPs, so it must not inflate
    the MFU numerator (e.g. a batch too small to tile the data axis
    makes the ring kernel drop that axis from its specs)."""
    used = set()
    for spec in tuple(params.get("in_specs", ())) + \
            tuple(params.get("out_specs", ())):
        for entry in tuple(spec):
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                used.update(entry)
            else:
                used.add(entry)
    try:
        shape = dict(params["mesh"].shape)
    except Exception:
        return 1.0
    return _prod(shape.get(a, 1) for a in used)


def count_jaxpr_flops(jaxpr) -> float:
    """Contraction FLOPs (dot_general + conv) of one jaxpr, recursive."""
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif name == "scan":
            total += eqn.params["length"] * count_jaxpr_flops(
                eqn.params["jaxpr"].jaxpr)
        elif name == "while":
            # no static trip count: charge one iteration (documented)
            total += count_jaxpr_flops(eqn.params["body_jaxpr"].jaxpr)
        elif name == "cond":
            total += max(count_jaxpr_flops(b.jaxpr)
                         for b in eqn.params["branches"])
        elif name == "shard_map":
            inner = sum(count_jaxpr_flops(s)
                        for s in _iter_sub_jaxprs(eqn.params))
            total += _shard_map_multiplier(eqn.params) * inner
        else:
            # pjit / remat(checkpoint) / custom_vjp / custom_jvp / core
            # calls: count their sub-jaxpr once
            for sub in _iter_sub_jaxprs(eqn.params):
                total += count_jaxpr_flops(sub)
    return total


def forward_flops(fn, *args, **kwargs) -> float:
    """Contraction FLOPs of fn's forward pass (traced, never executed)."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return count_jaxpr_flops(closed.jaxpr)


def _pure_trace_context():
    """Disable every custom-kernel routing for a counting trace, returning
    a restore callable. Counting must see plain dot_general — the AMX FFI
    and Pallas calls hide their contractions behind opaque primitives."""
    from alphafold2_tpu.ops import cpu_gemm
    from alphafold2_tpu.ops import attention as pallas_attn

    prev_amx = cpu_gemm._enabled
    prev_pallas = pallas_attn.pallas_attention_enabled()
    cpu_gemm.use_amx_dense(False)
    pallas_attn.use_pallas_attention(False)

    def restore():
        cpu_gemm._enabled = prev_amx
        pallas_attn.use_pallas_attention(prev_pallas)

    return restore


def train_step_flops(model, params, batch, rng=None) -> float:
    """Analytic FLOPs of one training step of `model` on `batch`:
    3 x forward contraction FLOPs of the composite loss (fwd 1x, bwd 2x).
    Optimizer update FLOPs (~10 x n_params elementwise) are excluded as
    negligible and non-contraction."""
    from alphafold2_tpu.train.loop import compute_loss

    rng = jax.random.PRNGKey(0) if rng is None else rng
    restore = _pure_trace_context()
    try:
        fwd = forward_flops(
            lambda p, b: compute_loss(model, p, b, rng, train=True)[0],
            params, batch)
    finally:
        restore()
    return 3.0 * fwd


def evoformer_step_flops_formula(
    dim: int, depth: int, seq_len: int, msa_depth: int,
    heads: int = 8, dim_head: int = 64, batch: int = 1,
    num_tokens: int = 21, distogram_buckets: int = 37,
) -> float:
    """Closed-form cross-check of the dominant terms of the benched
    distogram train step (documented FLOP model, fwd x3). Per Evoformer
    layer, with L = seq_len, M = msa_depth, d = dim, h*dh = inner:

      MSA row/col attention:   QKV/out projections 4*(M*L)*d*inner each
                               axis + logits/AV 2*(L + M) contractions
      Pair tri-attn row/col:   projections over L^2 cells + L^3 logits/AV
      Triangle mult out/in:    2 mixes, each ~ L^3 * d einsum + 4 L^2 d^2
                               projections
      OuterMean:               L^2 * M * d_hidden outer + projections
      FeedForwards:            MSA (M*L) and pair (L^2) * 2*(2*4d*d + 4d*d)

    This intentionally re-derives the big-O structure only to sanity-check
    `train_step_flops` (the jaxpr count is the number of record); tests
    assert agreement of the leading L^3/L^2 terms within ~15%.
    """
    L, M, d = float(seq_len), float(msa_depth), float(dim)
    inner = float(heads * dim_head)
    b = float(batch)

    def attn(tokens, ctx):
        # q,k,v,out projections + gating: 5 GEMMs of tokens*d*inner
        proj = 5 * 2.0 * tokens * d * inner
        # logits + AV: 2 * tokens * ctx * inner
        core = 2 * 2.0 * tokens * ctx * inner
        return proj + core

    msa_tokens = M * L
    pair_tokens = L * L
    layer = 0.0
    layer += attn(msa_tokens, L)          # MSA row attention
    layer += attn(msa_tokens, M)          # MSA col attention
    layer += attn(pair_tokens, L) * 2     # triangle attn out + in
    # triangle multiplicative x2: left/right/out projections (+3 gates)
    # ~6 GEMMs of L^2*d*d, plus the L^3 mix einsum (2 * L^3 * d)
    layer += 2 * (6 * 2.0 * pair_tokens * d * d + 2.0 * L ** 3 * d)
    # outer mean: hidden d_h=d//4 typical? use d (upper bound, small term)
    layer += 2.0 * L * L * M * d + 2 * 2.0 * msa_tokens * d * d
    # feedforwards (GEGLU: in proj 2*4d, out proj 4d)
    ff = lambda tokens: 2.0 * tokens * d * (2 * 4 * d) + \
        2.0 * tokens * (4 * d) * d
    layer += ff(msa_tokens) + ff(pair_tokens)

    trunk = depth * layer
    # embeds + distogram head (small)
    heads_flops = 2.0 * pair_tokens * d * distogram_buckets + \
        2.0 * (L + msa_tokens) * num_tokens * d
    return 3.0 * b * (trunk + heads_flops)
