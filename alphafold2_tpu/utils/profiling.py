"""Tracing / profiling utilities.

Net-new vs the reference, which has no profiler hooks at all (SURVEY.md
§5.1 — ad-hoc time.time() in a notebook is all it offers). Step time IS
the benchmark metric (BASELINE.json), so the timer is first-class:

- `percentile`: the one interpolating percentile everything reports
  through (StepTimer, serve.ServeMetrics, bench) — one stats path, no
  two subtly-different p99 definitions;
- `StepTimer`: wall-clock accumulator with mean/p50/p90/p99/min stats,
  used by `train.fit(step_timer=...)`, bench.py, and serve warmup;
- `trace`: context manager around `jax.profiler` emitting a TensorBoard-
  loadable trace directory;
- `annotate`: named-scope annotation that shows up in profiler timelines.
"""

from __future__ import annotations

import contextlib
import time
from typing import List, Optional, Sequence

import jax


def percentile(values: Sequence[float], q: float) -> float:
    """Linear-interpolation percentile (numpy's default method) on a
    possibly-unsorted sequence or array; 0.0 for empty input."""
    import numpy as np

    return float(np.percentile(values, q)) if len(values) else 0.0


class StepTimer:
    """Accumulates wall-clock step durations (seconds).

    `histogram`: optional obs.registry.Histogram every stop() also
    observes into, so step timings land in the process-wide metrics
    registry (Prometheus-exportable) without a second timing path. The
    p50/p90/p99 properties and Histogram.percentile share ONE quantile
    implementation — `percentile` above — so the two views can never
    disagree on what a p99 means."""

    def __init__(self, histogram=None):
        self.durations: List[float] = []
        self.histogram = histogram
        self._start: Optional[float] = None

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        if self._start is None:
            raise RuntimeError("StepTimer.stop() without start()")
        dur = time.perf_counter() - self._start
        self.durations.append(dur)
        self._start = None
        if self.histogram is not None:
            self.histogram.observe(dur)

    @contextlib.contextmanager
    def measure(self):
        self.start()
        try:
            yield
        finally:
            self.stop()

    @property
    def count(self) -> int:
        return len(self.durations)

    @property
    def mean(self) -> float:
        return sum(self.durations) / max(len(self.durations), 1)

    @property
    def p50(self) -> float:
        return percentile(self.durations, 50)

    @property
    def p90(self) -> float:
        return percentile(self.durations, 90)

    @property
    def p99(self) -> float:
        return percentile(self.durations, 99)

    @property
    def best(self) -> float:
        return min(self.durations) if self.durations else 0.0

    def summary(self) -> dict:
        return {"count": self.count, "mean_s": self.mean,
                "p50_s": self.p50, "p90_s": self.p90, "p99_s": self.p99,
                "best_s": self.best}


@contextlib.contextmanager
def trace(log_dir: str):
    """jax.profiler trace scope; view with TensorBoard or xprof."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


annotate = jax.profiler.TraceAnnotation
