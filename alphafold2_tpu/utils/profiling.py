"""Tracing / profiling utilities.

Net-new vs the reference, which has no profiler hooks at all (SURVEY.md
§5.1 — ad-hoc time.time() in a notebook is all it offers). Step time IS
the benchmark metric (BASELINE.json), so the timer is first-class:

- `StepTimer`: wall-clock accumulator with mean/p50/min stats, used by
  `train.fit(step_timer=...)` and bench.py;
- `trace`: context manager around `jax.profiler` emitting a TensorBoard-
  loadable trace directory;
- `annotate`: named-scope annotation that shows up in profiler timelines.
"""

from __future__ import annotations

import contextlib
import time
from typing import List, Optional

import jax


class StepTimer:
    """Accumulates wall-clock step durations (seconds)."""

    def __init__(self):
        self.durations: List[float] = []
        self._start: Optional[float] = None

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        if self._start is None:
            raise RuntimeError("StepTimer.stop() without start()")
        self.durations.append(time.perf_counter() - self._start)
        self._start = None

    @contextlib.contextmanager
    def measure(self):
        self.start()
        try:
            yield
        finally:
            self.stop()

    @property
    def count(self) -> int:
        return len(self.durations)

    @property
    def mean(self) -> float:
        return sum(self.durations) / max(len(self.durations), 1)

    @property
    def p50(self) -> float:
        if not self.durations:
            return 0.0
        s = sorted(self.durations)
        return s[len(s) // 2]

    @property
    def best(self) -> float:
        return min(self.durations) if self.durations else 0.0

    def summary(self) -> dict:
        return {"count": self.count, "mean_s": self.mean,
                "p50_s": self.p50, "best_s": self.best}


@contextlib.contextmanager
def trace(log_dir: str):
    """jax.profiler trace scope; view with TensorBoard or xprof."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


annotate = jax.profiler.TraceAnnotation
