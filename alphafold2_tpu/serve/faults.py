"""Deterministic fault injection for the serving failure domain.

A `FaultPlan` is the one chaos object every resilient component accepts
behind a no-op default (`FoldExecutor(faults=)`, `FoldCache(faults=)`,
`fleet.PeerCacheClient(faults=)`), so one seeded plan drives a whole
chaos run — `tools/serve_loadtest.py --chaos` and serve_smoke.sh
phase 5 — and tests/test_resilience.py replays the same failures the
resilience layer (serve/resilience.py) must absorb:

- executor exceptions: each `executor.run` raises
  `TransientExecutorError` with probability `exec_error_rate` (the
  retry path) — injected BEFORE the device call, so an injected fault
  never wastes real accelerator time. The hook is STEP-AWARE
  (ISSUE 14): the executor passes the ExecKey variant
  ("fold"/"init"/"step"/"init_rows") and, for step executions, the
  recycle index, so `step_fail_at={recycle: rate}` can hit a SPECIFIC
  recycle depth mid-loop deterministically (the carry-checkpointing
  resume path), and `snapshot()` tags injection counts by variant;
- latency spikes: probability `exec_latency_rate` of sleeping
  `exec_latency_s` inside `executor.run` (the watchdog path);
- featurize faults: `FeaturePool(faults=)` calls `on_featurize` before
  each featurize execution — probability `featurize_error_rate` of
  raising (the error must fan out to every coalesced waiter without
  wedging the pool) and `featurize_latency_rate` of sleeping
  `featurize_latency_s` (the feature-deadline path);
- poison inputs: sequences registered via `add_poison(seq)` are
  recognized IN THE ASSEMBLED BATCH by content (padded row prefix +
  mask length), so the fault follows the request through batching,
  retries, and bisection exactly like a real degenerate input. Mode
  "raise" fails the whole batch deterministically (`FaultInjected` —
  the bisection path); mode "nan" lets the batch run and overwrites
  the poison rows' coords with NaN (the output-validation path);
- peer transport failures: `on_peer_fetch` raises with probability
  `peer_error_rate` (the markdown/recovery path);
- corrupt cache bytes: `corrupt_cache_bytes` flips bytes of a disk
  entry with probability `corrupt_rate` before validation (the
  quarantine path).

Determinism: every injection site draws from its own `random.Random`
stream derived from (seed, site), so e.g. enabling peer faults does not
perturb the executor fault sequence. Sites called from one thread (the
scheduler worker drives the executor) replay exactly; multi-threaded
sites (peer fetches) are deterministic in aggregate counts per draw
sequence, not in which caller sees which fault.

Plans start DISARMED so warmup/compile traffic runs clean; call
`arm()` when the measured window starts. Injection counts are exposed
via `snapshot()` and the `serve_faults_injected_total{kind=...}`
counter, so a chaos report can prove the run actually hurt.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

import numpy as np

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.serve.resilience import TransientExecutorError


class FaultInjected(RuntimeError):
    """A deliberately injected DETERMINISTIC failure (poison input):
    never classified transient, so it exercises the bisection path.
    When the injection site can attribute the failure to specific
    batch rows it sets `.rows` (a list of batch row indices) — the
    scheduler's per-row poison isolation (RetryPolicy(row_isolation))
    reads it to retire exactly those rows; failures without row
    attribution fall back to whole-batch bisection."""

    rows = None


class FaultPlan:
    """Seeded chaos configuration threaded through serving components."""

    KINDS = ("exec_error", "exec_latency", "step_fail", "poison_raise",
             "poison_nan", "peer_error", "cache_corrupt",
             "featurize_error", "featurize_latency", "preempt_notice")

    def __init__(self, seed: int = 0,
                 exec_error_rate: float = 0.0,
                 exec_latency_rate: float = 0.0,
                 exec_latency_s: float = 0.0,
                 peer_error_rate: float = 0.0,
                 corrupt_rate: float = 0.0,
                 step_fail_at: Optional[dict] = None,
                 featurize_error_rate: float = 0.0,
                 featurize_latency_rate: float = 0.0,
                 featurize_latency_s: float = 0.0,
                 preempt_notice_rate: float = 0.0,
                 registry: Optional[MetricsRegistry] = None):
        self.step_fail_at = {int(k): float(v)
                             for k, v in (step_fail_at or {}).items()}
        for name, rate in (("exec_error_rate", exec_error_rate),
                           ("exec_latency_rate", exec_latency_rate),
                           ("peer_error_rate", peer_error_rate),
                           ("corrupt_rate", corrupt_rate),
                           ("featurize_error_rate", featurize_error_rate),
                           ("featurize_latency_rate",
                            featurize_latency_rate),
                           ("preempt_notice_rate", preempt_notice_rate),
                           *((f"step_fail_at[{k}]", v)
                             for k, v in self.step_fail_at.items())):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {rate}")
        self.seed = int(seed)
        self.exec_error_rate = float(exec_error_rate)
        self.exec_latency_rate = float(exec_latency_rate)
        self.exec_latency_s = float(exec_latency_s)
        self.peer_error_rate = float(peer_error_rate)
        self.corrupt_rate = float(corrupt_rate)
        self.featurize_error_rate = float(featurize_error_rate)
        self.featurize_latency_rate = float(featurize_latency_rate)
        self.featurize_latency_s = float(featurize_latency_s)
        self.preempt_notice_rate = float(preempt_notice_rate)
        self._lock = threading.Lock()
        self._armed = False
        # one independent stream per site, seeded from (seed, site) so
        # sites never perturb each other's sequences
        self._rngs = {site: random.Random(f"{self.seed}:{site}")
                      for site in ("exec", "latency", "peer", "corrupt",
                                   "step", "featurize",
                                   "featurize_lat", "preempt")}
        self._poison: List[dict] = []    # {"seq": np1d, "mode": str}
        self.injected = {k: 0 for k in self.KINDS}
        # (kind, ExecKey variant) -> count: which executable the fault
        # actually hit — a mid-loop "step" injection and a formation
        # "init" injection recover through different machinery, and
        # the chaos report must be able to tell them apart (ISSUE 14)
        self.injected_by_variant: dict = {}
        self._m_injected = (registry or get_registry()).counter(
            "serve_faults_injected_total",
            "chaos-harness injections by kind", ("kind",))

    # -- lifecycle -------------------------------------------------------

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._armed

    def arm(self) -> "FaultPlan":
        """Start injecting (after warmup, when the window begins)."""
        with self._lock:
            self._armed = True
        return self

    def disarm(self) -> "FaultPlan":
        with self._lock:
            self._armed = False
        return self

    def add_poison(self, seq, mode: str = "raise") -> "FaultPlan":
        """Register a poison sequence. mode="raise": any batch holding
        it fails deterministically (bisection corners it); mode="nan":
        its output rows come back non-finite (validation catches it)."""
        if mode not in ("raise", "nan"):
            raise ValueError(f"poison mode must be raise|nan, got {mode!r}")
        seq = np.asarray(seq, dtype=np.int32).reshape(-1)
        if seq.size == 0:
            raise ValueError("poison seq must be non-empty")
        with self._lock:
            self._poison.append({"seq": seq, "mode": mode})
        return self

    # -- internals -------------------------------------------------------

    def _hit(self, site: str, rate: float) -> bool:
        if rate <= 0.0:
            return False
        with self._lock:
            if not self._armed:
                return False
            return self._rngs[site].random() < rate

    def _count(self, kind: str, n: int = 1,
               variant: Optional[str] = None):
        with self._lock:
            self.injected[kind] += n
            if variant is not None:
                per = self.injected_by_variant.setdefault(variant, {})
                per[kind] = per.get(kind, 0) + n
        self._m_injected.inc(n, kind=kind)

    def _poison_rows(self, batch: dict, mode: str) -> List[int]:
        """Batch rows whose REAL content equals a registered poison
        sequence (content-addressed, so the fault follows the request
        through retries and bisection)."""
        with self._lock:
            if not self._armed or not self._poison:
                return []
            poisons = [p for p in self._poison if p["mode"] == mode]
        if not poisons:
            return []
        seqs = np.asarray(batch["seq"])
        mask = np.asarray(batch["mask"])
        rows = []
        for i in range(seqs.shape[0]):
            n = int(mask[i].sum())
            if n == 0:
                continue                 # batch-fill row, never poison
            for p in poisons:
                pseq = p["seq"]
                if n == pseq.shape[0] \
                        and np.array_equal(seqs[i, :n], pseq):
                    rows.append(i)
                    break
        return rows

    # -- injection sites -------------------------------------------------

    def on_executor_run(self, batch: dict, variant: str = "fold",
                        recycle: Optional[int] = None):
        """Called by FoldExecutor before the device call. May sleep
        (latency spike) or raise (poison / transient fault). `variant`
        is the ExecKey variant actually executing ("fold", "init",
        "step", "init_rows" — step-mode executors pass it; legacy
        callers default to "fold") and `recycle` the step's iteration
        index, so `step_fail_at={recycle: rate}` can inject a
        transient fault at a SPECIFIC recycle depth mid-loop
        (ISSUE 14) and snapshot() tags counts by variant."""
        rows = self._poison_rows(batch, "raise")
        if rows:
            self._count("poison_raise", variant=variant)
            exc = FaultInjected(
                f"poison_input: injected deterministic failure for "
                f"batch rows {rows} in {variant!r}")
            # content-addressed chaos KNOWS the rows: attribute them so
            # per-row poison isolation can retire exactly the offenders
            exc.rows = list(rows)
            raise exc
        if self.step_fail_at and variant == "step" \
                and recycle is not None \
                and self._hit("step",
                              self.step_fail_at.get(int(recycle), 0.0)):
            self._count("step_fail", variant=variant)
            raise TransientExecutorError(
                f"injected mid-loop transient fault at recycle "
                f"{recycle}")
        if self._hit("latency", self.exec_latency_rate):
            self._count("exec_latency", variant=variant)
            time.sleep(self.exec_latency_s)
        if self._hit("exec", self.exec_error_rate):
            self._count("exec_error", variant=variant)
            raise TransientExecutorError(
                "injected transient executor fault")

    def on_featurize(self, key: Optional[str] = None):
        """Called by FeaturePool workers before each featurize
        execution (the CPU stage had zero chaos coverage before
        ISSUE 14). May sleep (featurize latency spike — the
        feature-deadline path) or raise (featurize failure — the pool
        must fan it out to every coalesced waiter without wedging)."""
        if self._hit("featurize_lat", self.featurize_latency_rate):
            self._count("featurize_latency")
            time.sleep(self.featurize_latency_s)
        if self._hit("featurize", self.featurize_error_rate):
            self._count("featurize_error")
            raise FaultInjected(
                f"injected featurize failure"
                + (f" for key {key[:16]}..." if key else ""))

    def mutate_result(self, batch: dict, result):
        """Called by FoldExecutor.run after the device call: NaN-mode
        poison rows get non-finite coords (the result object must
        support `._replace`, i.e. a NamedTuple like FoldResult)."""
        rows = self._poison_rows(batch, "nan")
        if not rows:
            return result
        self._count("poison_nan", len(rows))
        coords = np.array(result.coords, np.float32, copy=True)
        coords[rows] = np.nan
        return result._replace(coords=coords)

    def on_peer_fetch(self, peer_id: str):
        """Called by PeerCacheClient before the HTTP fetch; raising
        counts as a transport failure (feeds peer markdown)."""
        if self._hit("peer", self.peer_error_rate):
            self._count("peer_error")
            raise FaultInjected(
                f"injected peer transport failure to {peer_id}")

    def on_preempt_poll(self, replica_id: str = "") -> bool:
        """Preemption-notice site (ISSUE 20): called from a
        `serve.preemption` notice source's poll round; True = a
        synthetic spot reclaim fires for this replica NOW (the caller
        builds the PreemptionNotice — this site only rolls the seeded
        dice, exactly like every other site). The draw comes from its
        own stream, so arming preemption chaos never perturbs the
        executor/peer fault sequences."""
        if not self._hit("preempt", self.preempt_notice_rate):
            return False
        self._count("preempt_notice")
        return True

    def corrupt_cache_bytes(self, key: str, data: bytes) -> bytes:
        """Called by FoldCache on disk reads before validation."""
        if not self._hit("corrupt", self.corrupt_rate):
            return data
        self._count("cache_corrupt")
        flipped = bytearray(data)
        for i in range(0, len(flipped), 97):
            flipped[i] ^= 0xFF
        return bytes(flipped)

    # -- views -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {"armed": self._armed, "seed": self.seed,
                    "rates": {"exec_error": self.exec_error_rate,
                              "exec_latency": self.exec_latency_rate,
                              "peer_error": self.peer_error_rate,
                              "corrupt": self.corrupt_rate,
                              "featurize_error":
                                  self.featurize_error_rate,
                              "featurize_latency":
                                  self.featurize_latency_rate,
                              "preempt_notice":
                                  self.preempt_notice_rate},
                    "step_fail_at": dict(self.step_fail_at),
                    "poison_registered": len(self._poison),
                    "injected": dict(self.injected),
                    "injected_by_variant": {
                        v: dict(per) for v, per in
                        sorted(self.injected_by_variant.items())}}
