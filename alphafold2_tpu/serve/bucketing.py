"""Length-bucket policy: map ragged requests onto a closed set of shapes.

On TPU every distinct input shape is a distinct XLA compile (and a
distinct executable resident in HBM), so the server quantizes sequence
length to a small set of bucket edges — powers of two by default, or
config-driven for a known length distribution (FastFold's insight:
matching work shape to the accelerator is where serving throughput
lives). The trade is padding waste vs compile count: finer edges waste
fewer pad tokens per fold but compile (and cache) more executables.

`assemble()` turns a list of same-bucket requests into one fixed-shape
batch — the vectorized host-side form of `data.pad_to` + masks (one
zero-filled buffer and one device transfer per tensor; this runs on the
scheduler worker between every batch) — padding the batch axis too so
that a bucket always presents exactly one (batch, len) signature. Pass
`msa_depth` to pin the MSA axis as well: without it the batch's depth
is max over its members, and ragged-depth traffic would mint a fresh
compiled shape per observed depth.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.serve.request import FoldRequest


class BucketPolicy:
    """Sorted ascending bucket edges; a request of length n maps to the
    smallest edge >= n."""

    def __init__(self, edges: Sequence[int]):
        edges = sorted(set(int(e) for e in edges))
        if not edges or edges[0] <= 0:
            raise ValueError(f"bucket edges must be positive, got {edges}")
        self.edges: Tuple[int, ...] = tuple(edges)

    @classmethod
    def powers_of_two(cls, min_len: int = 32,
                      max_len: int = 512) -> "BucketPolicy":
        edges = []
        e = 1
        while e < max_len:
            e *= 2
            if e >= min_len:
                edges.append(min(e, max_len))
        if max_len not in edges:
            edges.append(max_len)
        return cls(edges)

    @property
    def num_buckets(self) -> int:
        return len(self.edges)

    @property
    def max_len(self) -> int:
        return self.edges[-1]

    def bucket_for(self, length: int) -> int:
        """Deterministic: same length always lands on the same edge."""
        if length <= 0:
            raise ValueError(f"length must be positive, got {length}")
        for e in self.edges:
            if length <= e:
                return e
        raise ValueError(
            f"length {length} exceeds max bucket {self.edges[-1]}; "
            "add a larger edge or reject upstream")

    def assemble(
        self,
        requests: List[FoldRequest],
        bucket_len: int,
        batch_size: int,
        msa_depth: Optional[int] = None,
    ) -> Tuple[dict, float]:
        """Pad `requests` (all <= bucket_len) into one fixed-shape batch.

        Returns (batch, padding_waste) where batch has seq (B, L),
        mask (B, L), and msa/msa_mask (B, M, L); padding_waste is the
        fraction of the (B, L) token grid that is padding (batch-fill
        rows count as waste — they are real FLOPs spent on nothing).

        msa_depth=None infers M as the max depth over the requests (no
        MSA tensor when none carry one) — fine for uniform-depth
        traffic, but every distinct observed depth is a distinct
        compiled shape. Pinning msa_depth keeps the shape set closed:
        shallower MSAs are zero-padded+masked, deeper ones keep their
        FIRST msa_depth rows (the query-first convention
        `featurize.subsample_msa` maintains); msa_depth=0 forces the
        MSA-free signature.
        """
        if not requests:
            raise ValueError("assemble() needs at least one request")
        if len(requests) > batch_size:
            raise ValueError(
                f"{len(requests)} requests > batch_size {batch_size}")
        lengths = [r.length for r in requests]
        if max(lengths) > bucket_len:
            raise ValueError(
                f"request length {max(lengths)} > bucket_len {bucket_len}")

        seq = np.zeros((batch_size, bucket_len), np.int32)
        mask = np.zeros((batch_size, bucket_len), bool)
        for i, r in enumerate(requests):
            seq[i, : r.length] = r.seq
            mask[i, : r.length] = True
        batch = {"seq": jnp.asarray(seq), "mask": jnp.asarray(mask),
                 "msa": None, "msa_mask": None}

        depth = msa_depth
        if depth is None:
            depths = [r.msa.shape[0] for r in requests
                      if r.msa is not None]
            depth = max(depths) if depths else 0
        if depth > 0:
            msa = np.zeros((batch_size, depth, bucket_len), np.int32)
            msa_mask = np.zeros((batch_size, depth, bucket_len), bool)
            for i, r in enumerate(requests):
                if r.msa is not None:
                    m = min(r.msa.shape[0], depth)
                    n = r.msa.shape[1]
                    msa[i, :m, :n] = r.msa[:m]
                    msa_mask[i, :m, :n] = True
            batch["msa"] = jnp.asarray(msa)
            batch["msa_mask"] = jnp.asarray(msa_mask)

        real = sum(lengths)
        waste = 1.0 - real / float(batch_size * bucket_len)
        return batch, waste


def msa_depth_of(batch: dict) -> int:
    """Shape-key helper: 0 when the batch carries no MSA."""
    return 0 if batch.get("msa") is None else int(batch["msa"].shape[1])


def default_policy(max_len: Optional[int] = None) -> BucketPolicy:
    """The serving default: powers of two from 32 up to max_len (512)."""
    return BucketPolicy.powers_of_two(32, max_len or 512)
