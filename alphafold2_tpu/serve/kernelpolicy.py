"""Per-bucket attention-kernel selection for serving (ISSUE 12).

Round 5's keep-or-kill measured the block-sparse Pallas kernel
(`ops/block_sparse.py`) beating the XLA dense path once the live block
fraction drops far enough (`tools/tpu_blocksparse.json`: ~1.15x at 29%
live blocks, parity around 50%, a loss above that — sparsity only pays
when there is enough of it), yet every serving fold still compiled the
dense path. `KernelPolicy` makes the kernel a first-class serving
decision, the same shape as PR 7's `MeshPolicy`:

- each length BUCKET maps to "dense" or "blocksparse". Short buckets
  stay dense (their banded pattern is mostly live — the kernel's grid
  overhead buys nothing); long buckets route onto the block-skipping
  kernel with a static banded+global first-pass mask;
- with `contact_priors=True`, a step-scheduled batch (RecyclePolicy —
  the loop the scheduler already owns) re-plans its mask after the
  first pass from the PAIR ACTIVATIONS the fold itself produced: the
  recycle-1 distogram gives per-target contact probabilities, blocks
  whose max contact probability clears the threshold stay live, and the
  remaining recycles run under a re-lowered step executable
  (`ops.block_sparse.contact_block_pattern` plans host-side,
  `plan_block_pattern` compresses; the ExecKey's kernel element makes
  the re-lower automatic and staleness impossible). A degenerate plan —
  nearly every block live — falls back to the DENSE kernel: masking
  95% live blocks pays kernel overhead for no FLOP savings;
- the choice is baked into the `FoldExecutor`'s ExecKey (8-tuple, see
  MIGRATING ISSUE-12) and pre-compiled by `Scheduler.warmup()`, so a
  policy flip or rollout can never serve a stale executable and the
  first sparse fold never pays a mid-serving compile.

`Scheduler(kernel_policy=None)` — the default — is byte-for-byte the
dense-only behavior (scrubbed serve_stats identity, like every prior
feature).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Mapping, Optional, Sequence

import numpy as np

from alphafold2_tpu.ops.block_sparse import (KernelSpec,
                                             contact_block_pattern,
                                             contact_probs_from_distogram)

DENSE = "dense"
BLOCKSPARSE = "blocksparse"


@dataclass
class KernelPolicy:
    """Bucket edge -> attention-kernel choice.

    table: {bucket_len: "dense" | "blocksparse"}. Buckets not in the
        table serve dense. A "blocksparse" bucket not divisible by
        `block` also serves dense (refuse-don't-crash; the snapshot
        says so).
    block: token block size of the sparse pattern. 128 matches the TPU
        lane width and the benched configs in tpu_blocksparse.json;
        tests use smaller blocks on tiny buckets.
    window / num_global: the static banded+global first-pass mask
        (same semantics as model.attention_variants
        block_sparse_block_pattern — +-window blocks of the diagonal
        plus num_global global blocks).
    backend: "auto" (Pallas kernel on TPU, masked-dense fallback on
        CPU), "pallas" (force; interpret off-TPU — tests/smoke
        numerics), "masked" (dense+mask everywhere — the numerics
        reference).
    contact_priors: re-plan each step-scheduled batch's mask from its
        own recycle-1 distogram (see module docstring). Requires the
        scheduler to run step mode (RecyclePolicy); opaque folds keep
        the static mask. Each distinct planned pattern is a distinct
        executable — expect one extra lowering per batch whose pattern
        is novel; off by default.
    contact_cutoff: contact distance (Angstrom) for
        P(d < cutoff) from the distogram.
    contact_threshold: a block stays live when its max cell contact
        probability clears this.
    contact_live_frac: alternatively, keep the top fraction of blocks
        by contact score (a data-independent FLOP budget); overrides
        contact_threshold when set.
    dense_fallback_frac: a planned pattern whose live fraction is >=
        this serves the DENSE kernel instead (degenerate all-dense
        pattern — sparse overhead for no savings). Applies to the
        static mask too.
    """

    table: Mapping[int, str] = field(default_factory=dict)
    block: int = 128
    window: int = 1
    num_global: int = 1
    backend: str = "auto"
    contact_priors: bool = False
    contact_cutoff: float = 8.0
    contact_threshold: float = 0.5
    contact_live_frac: Optional[float] = None
    dense_fallback_frac: float = 0.95

    def __post_init__(self):
        self.table = {int(k): str(v) for k, v in dict(self.table).items()}
        for edge, kind in self.table.items():
            if kind not in (DENSE, BLOCKSPARSE):
                raise ValueError(
                    f"bucket {edge}: unknown kernel {kind!r} "
                    f"(want '{DENSE}' or '{BLOCKSPARSE}')")
        if self.block < 1:
            raise ValueError(f"block must be >= 1, got {self.block}")
        self._specs: Dict[int, Optional[KernelSpec]] = {}

    # -- construction -----------------------------------------------------

    @classmethod
    def from_buckets(cls, edges: Sequence[int],
                     min_sparse_len: Optional[int] = None,
                     sparse_live_frac: Optional[float] = None,
                     **kw) -> "KernelPolicy":
        """The auto policy: route a bucket blocksparse when its STATIC
        banded+global pattern is sparse enough to win — live fraction
        <= `sparse_live_frac` (default 0.5: tpu_blocksparse.json shows
        ~parity at 53% live and a clear win at 29%, so at or below half
        live the kernel is at worst free and strictly better as length
        grows). `min_sparse_len` instead pins a simple length floor."""
        pol = cls(**kw)
        if sparse_live_frac is None:
            sparse_live_frac = 0.5
        table = {}
        for edge in edges:
            edge = int(edge)
            if min_sparse_len is not None:
                table[edge] = BLOCKSPARSE if edge >= min_sparse_len \
                    else DENSE
                continue
            if edge % pol.block:
                table[edge] = DENSE
                continue
            spec = KernelSpec.banded(edge, pol.block, pol.window,
                                     pol.num_global, backend=pol.backend)
            table[edge] = BLOCKSPARSE \
                if spec.live_fraction <= sparse_live_frac else DENSE
        pol.table = table
        return pol

    @classmethod
    def parse(cls, spec: str, edges: Sequence[int], block: int = 128,
              sparse_live_frac: Optional[float] = None,
              backend: str = "auto", window: int = 1,
              num_global: int = 1,
              contact_priors: bool = False) -> Optional["KernelPolicy"]:
        """The shared CLI surface (`serve_loadtest --kernel-policy`):

        - ""            -> None (feature off, byte-identical serving)
        - "dense"       -> a policy routing every bucket dense (the
                           machinery runs — kernel stats, ExecKey
                           labels — but every fold compiles dense)
        - "blocksparse" -> every divisible bucket sparse
        - "auto"        -> from_buckets(sparse_live_frac=...)
        - "64=dense,512=blocksparse" -> explicit per-bucket pins
        """
        spec = (spec or "").strip()
        if not spec:
            return None
        kw = dict(block=block, backend=backend, window=window,
                  num_global=num_global, contact_priors=contact_priors)
        if spec == "auto":
            return cls.from_buckets(edges,
                                    sparse_live_frac=sparse_live_frac,
                                    **kw)
        if spec in (DENSE, BLOCKSPARSE):
            return cls(table={int(e): spec for e in edges}, **kw)
        table = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            edge, _, kind = part.partition("=")
            kind = kind.strip() or BLOCKSPARSE
            if kind in ("sparse", "bs"):
                kind = BLOCKSPARSE
            table[int(edge)] = kind
        return cls(table=table, **kw)

    @classmethod
    def from_model_config(cls, model_config, edges: Sequence[int],
                          **kw) -> "KernelPolicy":
        """Thread the one set of sparse knobs the config tree already
        has (config.ModelConfig.sparse_kwargs — the same block/global/
        window the model-level sparse_self_attn menu uses) into the
        serving policy, so the two layers cannot drift."""
        sk = model_config.sparse_kwargs()
        kw.setdefault("block", sk["block"])
        kw.setdefault("num_global", sk["num_global"])
        kw.setdefault("window", sk["window"])
        return cls.from_buckets(edges, **kw)

    # -- selection --------------------------------------------------------

    def kernel_for(self, bucket_len: int) -> str:
        """"dense" | "blocksparse" — what this bucket actually serves
        (a blocksparse entry the block size cannot tile, or whose
        static pattern is degenerately dense, answers "dense")."""
        return DENSE if self.spec_for(bucket_len) is None else BLOCKSPARSE

    def spec_for(self, bucket_len: int) -> Optional[KernelSpec]:
        """The static first-pass KernelSpec for a bucket (memoized), or
        None for dense."""
        bucket_len = int(bucket_len)
        if bucket_len in self._specs:
            return self._specs[bucket_len]
        spec = None
        if self.table.get(bucket_len) == BLOCKSPARSE \
                and bucket_len % self.block == 0:
            cand = KernelSpec.banded(bucket_len, self.block, self.window,
                                     self.num_global,
                                     backend=self.backend)
            if cand.live_fraction < self.dense_fallback_frac:
                spec = cand
        self._specs[bucket_len] = spec
        return spec

    def contact_spec_for(self, bucket_len: int,
                         distogram: np.ndarray,
                         lengths=None) -> Optional[KernelSpec]:
        """Plan a per-target contact-prior KernelSpec from recycle-1
        distogram logits ((b, n, n, buckets) — the batch shares one
        executable, so the plan keeps any block ANY element needs).
        None = run the remaining recycles DENSE: the bucket is not
        sparse-routed, or the planned pattern is degenerately live
        (the all-dense fallback — sparse overhead for no savings).
        `lengths` (one per batch row; 0 = unoccupied) zeroes each
        row's contribution beyond its real residues before planning,
        so a continuously admitted shorter fold's padding region
        (ISSUE 13) — and any dead row's garbage — plans as dead blocks
        instead of DMA-ing pair-bias garbage through the kernel."""
        if self.spec_for(bucket_len) is None:
            return None
        contacts = contact_probs_from_distogram(
            np.asarray(distogram), cutoff=self.contact_cutoff,
            lengths=lengths)
        pattern = contact_block_pattern(
            contacts, self.block, threshold=self.contact_threshold,
            live_frac=self.contact_live_frac, window=self.window,
            num_global=self.num_global)
        if pattern.mean() >= self.dense_fallback_frac:
            return None
        return KernelSpec.from_pattern(pattern, self.block,
                                       backend=self.backend,
                                       source="contact")

    # -- views ------------------------------------------------------------

    def snapshot(self) -> dict:
        live = {}
        for edge in sorted(self.table):
            spec = self.spec_for(edge)
            live[str(edge)] = {
                "kernel": DENSE if spec is None else BLOCKSPARSE,
                "live_frac": (None if spec is None
                              else round(spec.live_fraction, 4)),
                "label": None if spec is None else spec.label,
            }
        return {"block": self.block, "window": self.window,
                "num_global": self.num_global, "backend": self.backend,
                "contact_priors": self.contact_priors,
                "buckets": live}
