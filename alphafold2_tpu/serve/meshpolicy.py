"""Per-bucket mesh policy: match each length bucket to a device slice.

The serving insight (ParaFold: match each workload to the pool shape
that fits it; FastFold DAP: shard the O(L^2) pair representation at
inference) is that one executor topology cannot serve both ends of the
length distribution well:

- SHORT buckets saturate a single chip at batch 1 — spreading them over
  a mesh buys nothing and costs collective latency, so they stay on a
  1-chip slice (and, with several 1-chip slices free, fold CONCURRENTLY
  instead of queueing behind each other);
- LONG/flagship buckets are HBM-bound: the pair track is O(L^2) in
  activations, so past the single-chip ceiling the fold must 2-D shard
  the pair axes (`parallel.mesh` i x j) across a multi-chip slice or it
  simply cannot be served.

`MeshPolicy` is the bucket -> slice-shape map the `serve.Scheduler`
consults. Built explicitly (`MeshPolicy({64: 1, 512: 4})`) or derived
(`MeshPolicy.from_model`) from an analytic HBM footprint
(`FoldMemoryModel`) that picks the smallest power-of-two slice whose
per-device bytes fit — and marks buckets no configured slice can hold,
which the scheduler's admission guard rejects as status "too_large"
instead of dying in an XLA OOM mid-batch.

`DeviceSliceAllocator` hands out DISJOINT aligned device groups
(`SliceLease`) so batches on different slices execute concurrently;
the scheduler holds one lease per in-flight batch.

Everything here is policy + bookkeeping: no jax computation happens in
this module beyond enumerating devices, and a scheduler constructed
with `mesh_policy=None` never touches it.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Sequence, Tuple, Union

MeshShape = Tuple[int, int]          # (i, j) pair-axis factorization


def factor_chips(n: int) -> MeshShape:
    """Canonical (i, j) factorization of an n-chip slice: both powers of
    two, i <= j, i * j == n — the squarest face, so ring collectives
    over the sharded pair axes stay short on an ICI torus."""
    if n < 1 or n & (n - 1):
        raise ValueError(f"slice size must be a power of two, got {n}")
    i = 1
    while (i * 2) * (i * 2) <= n:
        i *= 2
    return (i, n // i)


def normalize_shape(shape: Union[int, Sequence[int]]) -> MeshShape:
    """Accept an int chip count or an explicit (i, j) pair."""
    if isinstance(shape, int):
        return factor_chips(shape)
    i, j = (int(x) for x in shape)
    if i < 1 or j < 1:
        raise ValueError(f"mesh shape must be positive, got {(i, j)}")
    return (i, j)


def mesh_label(shape: MeshShape) -> str:
    """Stable human/metric label: (2, 4) -> '2x4'."""
    return f"{shape[0]}x{shape[1]}"


def chips_of(shape: MeshShape) -> int:
    return shape[0] * shape[1]


@dataclass
class FoldMemoryModel:
    """Analytic per-device HBM footprint of one fold batch.

    Deliberately a handful of named terms, not a compiler: the point is
    a monotone, explainable admission signal (BENCH_r05 showed the real
    flagship at 15.63/16 GB — the terms below are the ones that put it
    there), cross-checkable against `tools/memory_probe.py`'s XLA
    memory analysis.

    Terms, for a (B, L, M) batch on a `chips`-device slice:

    - params: replicated per device (tensor-parallel placement shards
      some projections, but counting them full keeps the guard
      conservative);
    - pair track: B * L^2 * (dim + heads) * dtype_bytes * pair_live —
      activations plus attention logits; `pair_live` is the scan+remat
      live-set coefficient (residual + recyclables + workspace), NOT
      depth — remat keeps the live set O(1) in depth. 2-D sharded over
      the slice, so divided by `chips`;
    - msa track: B * M * L * dim * dtype_bytes * msa_live, sharded over
      the i axis ONLY (msa_spec/fold_input_specs place nothing on j),
      so it divides by the slice's i factor, not the chip count;
    - distogram head: B * L^2 * distogram_buckets * 4, counted
      replicated — it is the output the host gathers;
    - recycle carry (step-mode scheduling only, `carry_recyclables=`):
      the scheduler-owned recycle loop holds the PREVIOUS step's
      `Recyclables` (pairwise repr + single row + coords) live across
      the step executable's execution — the opaque `lax.scan` fold
      keeps that carry inside one program where the pair_live
      coefficient already prices it, but step mode double-buffers it
      at the seam (prev state alive while the next computes), so a
      step-scheduled bucket pays `recycle_carry_live` extra copies of
      the pairwise term (sharded like the pair track) plus the
      unsharded single-row/coords terms.
    """

    param_bytes: int
    dim: int
    heads: int = 8
    dtype_bytes: int = 4
    pair_live: float = 6.0
    msa_live: float = 4.0
    recycle_carry_live: float = 2.0
    distogram_buckets: int = 37
    hbm_bytes_per_device: int = 16 << 30

    @classmethod
    def from_model(cls, model, params, hbm_gb: float = 16.0,
                   **overrides) -> "FoldMemoryModel":
        import jax
        import jax.numpy as jnp

        param_bytes = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(params)
            if hasattr(leaf, "size"))
        dtype = getattr(model, "dtype", None)
        dtype_bytes = 2 if dtype == jnp.bfloat16 else 4
        kwargs = dict(param_bytes=int(param_bytes), dim=int(model.dim),
                      heads=int(getattr(model, "heads", 8)),
                      dtype_bytes=dtype_bytes,
                      hbm_bytes_per_device=int(hbm_gb * (1 << 30)))
        kwargs.update(overrides)
        return cls(**kwargs)

    def fold_bytes(self, bucket_len: int, batch_size: int,
                   msa_depth: int, chips: int = 1,
                   shape: Optional[MeshShape] = None,
                   carry_recyclables: bool = False,
                   continuous: bool = False) -> int:
        """Estimated peak per-device bytes for one fold batch. Pass the
        actual slice `shape` when known (admits() does) — the MSA track
        divides by its i factor only; a bare `chips` count prices the
        canonical squarest factorization. `carry_recyclables` adds the
        step-mode recycle carry (the scheduler passes it iff a
        RecyclePolicy drives the loop). `continuous` (implies step
        mode) additionally prices the row-admission seam (ISSUE 11):
        `fold_init_rows` holds the full-batch fresh init output live
        alongside the old carried state while the per-row select
        builds the merged state, one extra single-buffered copy of the
        carry on top of `recycle_carry_live`'s double-buffering."""
        L, B, M = int(bucket_len), int(batch_size), int(msa_depth)
        if shape is not None:
            i = max(int(shape[0]), 1)
            chips = max(int(shape[0]) * int(shape[1]), 1)
        else:
            chips = max(int(chips), 1)
            try:
                i = factor_chips(chips)[0]
            except ValueError:
                i = 1
        pair = B * L * L * (self.dim + self.heads) * self.dtype_bytes \
            * self.pair_live
        msa = B * max(M, 1) * L * self.dim * self.dtype_bytes \
            * self.msa_live
        dist = B * L * L * self.distogram_buckets * 4
        total = self.param_bytes + dist + pair / chips + msa / i
        if carry_recyclables or continuous:
            total += self.carry_bytes(L, B, chips=chips)
        if continuous:
            # the admission seam's extra live copy (one, not the
            # carry's recycle_carry_live-many)
            total += self.carry_bytes(L, B, chips=chips) \
                / max(self.recycle_carry_live, 1.0)
        return int(total)

    def carry_bytes(self, bucket_len: int, batch_size: int,
                    chips: int = 1) -> int:
        """Per-device bytes of the step loop's carried `Recyclables`
        ALONE (pairwise repr sharded over the slice + unsharded single
        row/coords, double-buffered per `recycle_carry_live`). This is
        what a SUSPENDED step loop keeps HBM-resident across a
        preemption yield — the concurrent-peak term the memory-aware
        preemption admission prices on top of the urgent batch's
        `fold_bytes` (ISSUE 10)."""
        L, B = int(bucket_len), int(batch_size)
        chips = max(int(chips), 1)
        carry_pair = B * L * L * self.dim * self.dtype_bytes / chips
        carry_rest = B * L * (self.dim + 3) * self.dtype_bytes
        return int(self.recycle_carry_live * (carry_pair + carry_rest))

    def fits(self, bucket_len: int, batch_size: int, msa_depth: int,
             chips: int = 1,
             shape: Optional[MeshShape] = None,
             carry_recyclables: bool = False,
             continuous: bool = False) -> bool:
        return self.fold_bytes(
            bucket_len, batch_size, msa_depth, chips, shape,
            carry_recyclables=carry_recyclables,
            continuous=continuous) \
            <= self.hbm_bytes_per_device


@dataclass
class AdmissionDecision:
    """One priced cross-bucket admission verdict (ISSUE 13)."""

    admit: bool
    reason: str            # "pad_frac" | "deadline" | "native_imminent"
    #                        | "priced" | "padded_cost"
    pad_frac: float
    excess_s: float        # padding-share compute the admit would waste
    native_delay_s: float  # projected wait for a native-bucket fold


@dataclass
class AdmissionPricer:
    """Prices the padding-vs-dead-row trade of CROSS-BUCKET row
    admission (ISSUE 13): may a pending request from a shorter bucket
    ride a freed row of a longer host batch, padded to the host edge?

    The trade, made explicit instead of unconditional:

    - a freed row is FREE compute for as long as the host loop runs
      anyway (a step costs the same whether a row is live or dead), so
      a candidate whose remaining recycles fit inside the host loop's
      remaining steps rides at zero marginal cost — strictly better
      than a dead row plus a separate native-bucket batch formation
      (the ParaFold keep-the-accelerator-busy thesis at iteration
      level);
    - a candidate that EXTENDS the loop pays O(L_host^2) per extension
      step where a native fold would have paid O(L_native^2) — only
      the padding share of those extension steps is waste, and it is
      priced against the candidate's projected native-bucket queue
      delay (the latency it buys);
    - deadline urgency is the tiebreak: a candidate that would MISS
      its deadline waiting for a native batch admits regardless of
      cost;
    - `max_pad_frac` is the hard guard: past it, no queue delay
      justifies the padding (a 12-residue fold in a 512 host row).

    memory: optional FoldMemoryModel whose pair/MSA terms weight the
        relative step cost; None prices with representative dim/heads
        (the RATIO of host to native cost is what matters, and it is
        dominated by the O(L^2) term either way).
    max_pad_frac: see above; the scheduler threads
        `RecyclePolicy.cross_bucket_max_pad_frac` here.
    """

    memory: Optional[FoldMemoryModel] = None
    max_pad_frac: float = 0.75

    def step_cost(self, bucket_len: int, batch_size: int,
                  msa_depth: int) -> float:
        """Relative per-step compute of one (B, L, M) batch: the
        O(L^2) pair + MSA terms of the memory model as a FLOP proxy
        (the same terms the HBM guard prices — bytes and FLOPs share
        the activation shapes)."""
        dim = self.memory.dim if self.memory is not None else 64
        heads = self.memory.heads if self.memory is not None else 8
        L, B, M = int(bucket_len), int(batch_size), int(msa_depth)
        return float(B * L * L * (dim + heads)
                     + B * max(M, 1) * L * dim)

    def price(self, *, native_len: int, host_len: int, length: int,
              batch_size: int, msa_depth: int,
              candidate_steps: int, remaining_host_steps: int,
              native_delay_s: float,
              deadline_slack_s: Optional[float],
              host_step_s: float) -> AdmissionDecision:
        """Decide one candidate.

        native_len/host_len: the candidate's own bucket edge and the
            host batch's edge; `length` is its real residue count (pad
            fraction is priced at the host edge).
        candidate_steps: recycles the candidate will run after its
            row-masked init (its full depth — it enters at age 0).
        remaining_host_steps: steps the host loop runs regardless
            (max over surviving rows' remaining depth); the candidate
            rides these for free, and only the excess extends the
            loop.
        native_delay_s: the scheduler's projection of how long this
            candidate would wait for a native-bucket fold (batch
            formation window + worker/slice availability). <= 0 means
            a native batch can form RIGHT NOW — stealing its member
            for padded compute buys nothing.
        deadline_slack_s: seconds until the candidate's deadline
            (None = no deadline).
        host_step_s: measured per-step latency of the host bucket
            (EWMA; 0.0 before the first measurement prices extension
            as free, so a cold loop leans toward admitting).
        """
        pad_frac = 1.0 - float(length) / float(host_len)
        if pad_frac > self.max_pad_frac:
            return AdmissionDecision(False, "pad_frac", pad_frac,
                                     0.0, native_delay_s)
        # deadline urgency tiebreak: waiting for the native bucket
        # would miss the deadline outright — admit whatever the cost
        if deadline_slack_s is not None \
                and deadline_slack_s < native_delay_s:
            return AdmissionDecision(True, "deadline", pad_frac,
                                     0.0, native_delay_s)
        if native_delay_s <= 0.0:
            return AdmissionDecision(False, "native_imminent", pad_frac,
                                     0.0, native_delay_s)
        extension = max(0, int(candidate_steps)
                        - int(remaining_host_steps))
        cost_ratio = self.step_cost(native_len, batch_size, msa_depth) \
            / max(self.step_cost(host_len, batch_size, msa_depth), 1.0)
        excess_s = extension * max(host_step_s, 0.0) \
            * (1.0 - min(cost_ratio, 1.0))
        if excess_s <= native_delay_s:
            return AdmissionDecision(True, "priced", pad_frac,
                                     excess_s, native_delay_s)
        return AdmissionDecision(False, "padded_cost", pad_frac,
                                 excess_s, native_delay_s)

    def snapshot(self) -> dict:
        return {"max_pad_frac": self.max_pad_frac,
                "memory": self.memory is not None}


@dataclass
class SliceLease:
    """One acquired device slice; hold it for the duration of a batch.

    `held` tracks whether THIS lease currently owns its span (the
    allocator flips it on acquire/release): release() is idempotent
    against it, so an exception between a preemption yield's release
    and its re-acquire can never make a failure-path release free a
    span that another batch has since leased (ISSUE 14 — every
    failure path releases exactly what it holds, nothing more)."""

    devices: List[object]
    shape: MeshShape
    start: int                       # first device index in the pool
    held: bool = True

    @property
    def label(self) -> str:
        return mesh_label(self.shape)


class DeviceSliceAllocator:
    """Disjoint, aligned device slices over one device pool.

    Slices of size n start at multiples of n (aligned), so the same
    slice identities recur under low load and compiled executables
    (bound to concrete devices) are reused instead of re-minted per
    acquire. Thread-safe; `acquire` is non-blocking (the scheduler
    worker only forms batches it can place), `acquire_blocking` exists
    for warmup.
    """

    def __init__(self, devices: Sequence[object]):
        self.devices = list(devices)
        if not self.devices:
            raise ValueError("allocator needs at least one device")
        self._busy = [False] * len(self.devices)
        self._cond = threading.Condition()

    @property
    def total_devices(self) -> int:
        return len(self.devices)

    @property
    def busy_devices(self) -> int:
        with self._cond:
            return sum(self._busy)

    def _find(self, size: int) -> Optional[int]:
        """Caller holds self._cond: first free aligned group, or None."""
        for start in range(0, len(self.devices) - size + 1, size):
            if not any(self._busy[start:start + size]):
                return start
        return None

    def can_allocate(self, shape: MeshShape) -> bool:
        size = chips_of(shape)
        if size > len(self.devices):
            return False
        with self._cond:
            return self._find(size) is not None

    def slices(self, shape: MeshShape) -> List[List[object]]:
        """Every aligned device group this shape can ever be leased —
        the set warmup must precompile, because an executable is bound
        to its concrete devices and a batch that lands on a cold slice
        pays a fresh XLA compile mid-serving."""
        size = chips_of(shape)
        if size > len(self.devices):
            return []
        return [self.devices[start:start + size]
                for start in range(0, len(self.devices) - size + 1,
                                   size)]

    def acquire(self, shape: MeshShape) -> Optional[SliceLease]:
        size = chips_of(shape)
        if size > len(self.devices):
            return None
        with self._cond:
            start = self._find(size)
            if start is None:
                return None
            for k in range(start, start + size):
                self._busy[k] = True
        return SliceLease(self.devices[start:start + size], shape, start)

    def acquire_blocking(self, shape: MeshShape,
                         timeout_s: Optional[float] = None) -> SliceLease:
        size = chips_of(shape)
        if size > len(self.devices):
            raise ValueError(
                f"slice of {size} devices > pool of {len(self.devices)}")
        with self._cond:
            while True:
                start = self._find(size)
                if start is not None:
                    for k in range(start, start + size):
                        self._busy[k] = True
                    return SliceLease(self.devices[start:start + size],
                                      shape, start)
                if not self._cond.wait(timeout=timeout_s):
                    raise TimeoutError(
                        f"no free {mesh_label(shape)} slice within "
                        f"{timeout_s}s")

    def acquire_span(self, lease: SliceLease) -> SliceLease:
        """Blocking re-acquire of the EXACT device span of a released
        lease (step-mode preemption: the loop's carried state and its
        compiled executables are bound to those devices, so after
        yielding the slice for a preemption gap it must come back to
        the same chips). Waits indefinitely — the holder released
        everything before waiting, so there is no cycle to deadlock
        on, and whoever borrowed the span releases it after a bounded
        batch. Returns the SAME lease object re-armed (`held` flips
        back on), so every reference a caller's finally-block holds
        releases the span that is actually leased — a new object here
        would leave the original reference pointing at a dead lease
        and strand the re-acquired span on any later failure path
        (ISSUE 14)."""
        size = chips_of(lease.shape)
        with self._cond:
            while any(self._busy[lease.start:lease.start + size]):
                self._cond.wait()
            for k in range(lease.start, lease.start + size):
                self._busy[k] = True
            lease.held = True
        return lease

    def release(self, lease: SliceLease):
        """Idempotent: releasing a lease that is not currently held
        (already released for a preemption yield, or double-released
        by racing failure paths) is a no-op — it must never free a
        span another batch has since acquired."""
        size = chips_of(lease.shape)
        with self._cond:
            if not lease.held:
                return
            lease.held = False
            for k in range(lease.start, lease.start + size):
                self._busy[k] = False
            self._cond.notify_all()

    def snapshot(self) -> dict:
        with self._cond:
            busy = sum(self._busy)
        return {"total_devices": len(self.devices), "busy_devices": busy}


class MeshPolicy:
    """bucket_len -> device-slice shape, plus the HBM admission model.

    shapes: mapping of bucket edge -> slice (an int chip count or an
        explicit (i, j) pair). Buckets absent from the map default to a
        single chip. Shapes larger than the device pool are CLAMPED to
        the largest power-of-two slice the pool holds (recorded in
        `clamped` and the snapshot) so a policy written for an 8-chip
        host degrades cleanly on a 1-device CI runner.
    devices: the device pool to slice (default: jax.devices()).
    memory: optional FoldMemoryModel backing `admits()`; None admits
        everything (the guard is opt-in like everything else here).
    """

    def __init__(self, shapes: Mapping[int, Union[int, Sequence[int]]],
                 devices: Optional[Sequence[object]] = None,
                 memory: Optional[FoldMemoryModel] = None):
        if devices is None:
            import jax
            devices = jax.devices()
        self.devices = list(devices)
        n_dev = len(self.devices)
        cap = 1
        while cap * 2 <= n_dev:
            cap *= 2
        self.shapes: Dict[int, MeshShape] = {}
        self.clamped: Dict[int, str] = {}
        for bucket, s in shapes.items():
            shape = normalize_shape(s)
            if chips_of(shape) > n_dev:
                self.clamped[int(bucket)] = mesh_label(shape)
                shape = factor_chips(cap)
            self.shapes[int(bucket)] = shape
        self.memory = memory

    @classmethod
    def from_model(cls, model, params, buckets: Sequence[int],
                   max_batch: int = 1, msa_depth: int = 0,
                   hbm_gb: float = 16.0,
                   devices: Optional[Sequence[object]] = None,
                   max_chips: Optional[int] = None,
                   carry_recyclables: bool = False,
                   continuous: bool = False,
                   **memory_overrides) -> "MeshPolicy":
        """Derive the policy analytically: for each bucket edge, the
        smallest power-of-two slice whose estimated per-device footprint
        fits `hbm_gb`. A bucket that does not fit even the largest slice
        still gets that slice in the map but fails `admits()` — the
        scheduler rejects it at submit as "too_large".

        carry_recyclables: size slices for STEP-MODE serving (a
        RecyclePolicy will drive the loop): the fitting loop then
        prices the carried Recyclables exactly like the admission
        guard will, so a bucket whose opaque fold just fits an n-chip
        slice is assigned the bigger slice it actually needs instead
        of being auto-sized into a guaranteed "too_large".
        `continuous` does the same for the continuous batcher's
        row-admission seam (ISSUE 11)."""
        if devices is None:
            import jax
            devices = jax.devices()
        edges = getattr(buckets, "edges", buckets)
        memory = FoldMemoryModel.from_model(model, params, hbm_gb=hbm_gb,
                                            **memory_overrides)
        cap = min(max_chips or len(devices), len(devices))
        shapes: Dict[int, int] = {}
        for edge in edges:
            n = 1
            while not memory.fits(edge, max_batch, msa_depth, n,
                                  carry_recyclables=carry_recyclables,
                                  continuous=continuous) \
                    and n * 2 <= cap:
                n *= 2
            shapes[int(edge)] = n
        return cls(shapes, devices=devices, memory=memory)

    @classmethod
    def parse(cls, spec: str, model=None, params=None, buckets=None,
              max_batch: int = 1, msa_depth: int = 0,
              hbm_gb: float = 16.0,
              devices: Optional[Sequence[object]] = None,
              carry_recyclables: bool = False,
              continuous: bool = False,
              **memory_overrides) -> Optional["MeshPolicy"]:
        """The ONE parser for every `--mesh-policy` surface (the
        loadtest CLI, `fleet.ProcFleet` replica configs,
        `fleet.procfleet.replica_main`): "" -> None (single-chip,
        today's behavior), "auto" -> `from_model` with the analytic
        HBM budget (requires model/params/buckets), or an explicit
        "BUCKET=CHIPS,..." map, e.g. "32=1,128=4". Raises ValueError
        on a malformed spec — a typo'd policy must fail loudly at
        boot, not silently serve single-chip."""
        spec = (spec or "").strip()
        if not spec:
            return None
        if spec == "auto":
            if model is None or params is None or buckets is None:
                raise ValueError(
                    "--mesh-policy auto needs model/params/buckets")
            return cls.from_model(model, params, buckets,
                                  max_batch=max_batch,
                                  msa_depth=msa_depth, hbm_gb=hbm_gb,
                                  devices=devices,
                                  carry_recyclables=carry_recyclables,
                                  continuous=continuous,
                                  **memory_overrides)
        shapes = {}
        for kv in spec.split(","):
            try:
                bucket, chips = kv.split("=")
                shapes[int(bucket)] = int(chips)
            except ValueError:
                raise ValueError(
                    f"bad --mesh-policy entry {kv!r} "
                    f"(want BUCKET=CHIPS, e.g. 32=1,128=4)")
        return cls(shapes, devices=devices)

    def shape_for(self, bucket_len: int) -> MeshShape:
        return self.shapes.get(int(bucket_len), (1, 1))

    def chips_for(self, bucket_len: int) -> int:
        return chips_of(self.shape_for(bucket_len))

    def admits(self, bucket_len: int, batch_size: int, msa_depth: int,
               carry_recyclables: bool = False,
               continuous: bool = False) -> bool:
        """False when the bucket's configured slice — already the
        largest one the policy was willing/able to assign — cannot hold
        the batch's analytic footprint. The scheduler maps False to
        status "too_large" at submit, and passes `carry_recyclables`
        iff a RecyclePolicy makes it run the step loop (whose carried
        Recyclables are extra live bytes the opaque fold never
        double-buffers) and `continuous` iff that policy also admits
        rows mid-loop (the row-masked init's select seam holds one
        more live copy of the carry — the guard must refuse a bucket
        that fits the plain step loop but would OOM on its first
        admission)."""
        if self.memory is None:
            return True
        return self.memory.fits(bucket_len, batch_size, msa_depth,
                                shape=self.shape_for(bucket_len),
                                carry_recyclables=carry_recyclables,
                                continuous=continuous)

    def allocator(self) -> DeviceSliceAllocator:
        return DeviceSliceAllocator(self.devices)

    def snapshot(self) -> dict:
        snap = {
            "devices": len(self.devices),
            "policy": {str(b): mesh_label(s)
                       for b, s in sorted(self.shapes.items())},
        }
        if self.clamped:
            snap["clamped"] = {str(b): lbl
                               for b, lbl in sorted(self.clamped.items())}
        if self.memory is not None:
            snap["hbm_bytes_per_device"] = self.memory.hbm_bytes_per_device
        return snap
