"""Thread-based dynamic batcher: the serving front door.

Requests enqueue from any thread; one worker drains them into per-bucket
batches under a `max_batch_size` / `max_wait_ms` policy (ParaFold-style:
throughput comes from scheduling, not the model). Three QoS behaviors:

- deadline shedding: a request whose deadline expires while queued is
  resolved `status="shed"` without touching the accelerator — folding
  dead work is the most expensive way to miss a deadline;
- bounded-queue backpressure: `queue_limit` caps in-flight requests;
  `full_policy="reject"` raises QueueFullError at submit (shed at the
  door), `"block"` makes submit wait for capacity;
- priority: when a backlog exceeds one batch, higher-priority requests
  fold first (FIFO within a priority level).

With a `cache` (alphafold2_tpu.cache.FoldCache — OFF by default),
submit() never enqueues redundant work: a content-addressed key over
(seq, effective MSA, fold config, model_tag) is checked against the
result store (hit → the ticket resolves immediately, source="cache"),
then against the in-flight registry (duplicate of a queued/running
fold → the ticket parks as a FOLLOWER of that leader, source=
"coalesced"). Only a genuinely novel fold enqueues. Every terminal
leader state — ok, executor error, deadline shed, cancellation, worker
crash — fans out to its followers, so coalesced tickets can never
deadlock; on success the store is populated before followers settle,
closing the attach/settle race. Parked followers count against
`queue_limit` at attach time, so a duplicate storm is bounded like
unique traffic (worst-case transient residency is < 2x queue_limit:
a leader gates its own enqueue on queue depth alone — counting its own
parked followers there would be a circular wait).

With a `router` (fleet.ConsistentHashRouter — OFF by default), a novel
fold whose key hashes to another healthy replica takes one bounded
forwarding hop to that owner at submit, so duplicate traffic coalesces
fleet-wide on one leader instead of once per process; any forwarding
trouble falls back to folding locally. A leader that is shed (or
rejected at submit) no longer sheds its parked followers: the
tightest-deadline survivor is PROMOTED to leader and enqueued, the
rest stay parked behind it (`coalesce_leader_promotions_total`).

Unlike a leader, a parked follower DOES get its own deadline enforced:
if it expires while waiting on the leader, the follower is shed with
its own terminal state (`status="shed"`, reason
`follower_deadline_exceeded`) instead of inheriting the leader's
timing — a tight-deadline duplicate must not silently wait out a
slow leader.

With a `tracer` (alphafold2_tpu.obs.Tracer — NULL_TRACER by default,
zero-cost no-ops), every submission carries a request-scoped trace
from submit to its terminal state: `submit` (cache lookup, coalescing,
backpressure wait), `queue`, `batch_form`, executor `compile`/`fold`
(batch-level spans fanned out to each member), and `writeback` spans,
plus cache hit/miss/quarantine and coalescing events; followers link
to their leader's trace. Completed traces emit as JSONL and the K
slowest are exposed via `serve_stats()["traces"]`
(tools/obs_report.py renders the waterfall).

With a `retry` (serve.resilience.RetryPolicy — OFF by default, and
with it off this scheduler behaves exactly as before the resilience
layer existed), failure becomes a first-class domain instead of a
single error path: batches failed by TRANSIENT executor trouble are
re-enqueued with bounded exponential backoff instead of error-resolving
their whole cohort; a batch that fails DETERMINISTICALLY is bisected —
split in half, each half retried as its own isolation group — so one
poison input is cornered in <= log2(batch) extra executions and
quarantined (status "poisoned"; its key fails fast forever, covering
coalesced followers and future duplicates); non-finite coords or
confidence never leave as "ok" (`nonfinite_output`, counting toward
poison detection); an optional per-batch WATCHDOG deadline bounds
executor.run, rebuilding the executor on expiry; and an optional
CIRCUIT BREAKER flips the scheduler into degraded mode after
consecutive systemic failures — novel submits fast-shed with status
"degraded" while cache/coalesce hits keep serving, then a half-open
probe batch closes the breaker when the device recovers.

With a `mesh_policy` (serve.meshpolicy.MeshPolicy — OFF by default,
and with it off this scheduler is byte-for-byte the single-chip
behavior), serving becomes mesh-aware end to end: each bucket maps to
a device-slice shape (1 chip for short buckets, a 2/4/8-chip
pair-sharded mesh for long ones, chosen by an analytic HBM model), a
DeviceSliceAllocator hands each formed batch a DISJOINT slice so short
traffic no longer queues behind a flagship fold (batches on different
slices execute concurrently on a small pool of dispatch threads), the
executor lowers long-bucket folds under `parallel.mesh` with params
sharded once per slice, and submits whose analytic footprint exceeds
even the largest configured slice resolve status "too_large"
(`serve_too_large_total`) instead of dying in an XLA OOM mid-batch.
`serve_stats()["mesh"]` reports the policy, per-shape fold counts, and
allocator occupancy; fold spans are tagged with their mesh label and a
`shard` span prices params/input placement in the waterfall.

With a `recycle_policy` (serve.recycle.RecyclePolicy — OFF by default,
and with it off this scheduler is byte-for-byte the opaque-fold
behavior), the SCHEDULER owns the recycle loop instead of `lax.scan`:
each batch runs the embed+first-pass executable then one single-recycle
step executable per iteration (`FoldExecutor.run_init`/`run_step` —
the scan body as its own program, so full-recycle numerics match the
opaque path exactly), and between steps the scheduler retires
converged elements early (per-element coordinate/confidence delta
below `converge_tol`; the survivor batch is re-packed and a fully
converged batch skips its remaining recycles —
`serve_recycles_skipped_total`), lets tight-deadline pending work
PREEMPT the gap (`serve_preemptions_total`), and streams per-recycle
progressive results to each FoldTicket (`RecyclePolicy(stream=True)`).
A result-affecting policy (converge_tol > 0) keys the cache under
distinct `fold_key` extras, so an early-exited result is never served
to a caller demanding fixed full recycles.

Cache-aware admission (`SchedulerConfig.parked_bytes_budget` > 0): an
in-flight duplicate costs ~0 — it parks as a follower and never
touches the accelerator — so submit() admits coalescing followers PAST
a "full" queue, bounded by the budget on their parked request bytes
(`serve_parked_admits_total`). Novel work still honors `queue_limit`
exactly as before; the budget only widens the door for work that is
already being done.

Batches are always padded to `max_batch_size` (bucketing.assemble), so
the compiled-shape set is closed: one executable per (bucket,
num_recycles), never one per observed batch size.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from alphafold2_tpu.cache import FoldCache, InflightRegistry, fold_key
from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import (MultiTrace, NULL_TRACE, NULL_TRACER,
                                      Tracer)
from alphafold2_tpu.serve.bucketing import BucketPolicy
from alphafold2_tpu.serve.confidence import (
    distogram_entropy as _distogram_entropy, score_response)
from alphafold2_tpu.serve.executor import FoldExecutor
from alphafold2_tpu.serve.meshpolicy import (AdmissionPricer, MeshPolicy,
                                             SliceLease, chips_of)
from alphafold2_tpu.serve.metrics import ServeMetrics
from alphafold2_tpu.serve.recycle import (RecyclePolicy, element_deltas,
                                          repack_batch, repack_rows,
                                          steps_saved)
from alphafold2_tpu.serve.request import (FoldProgress, FoldRequest,
                                          FoldResponse, FoldTicket)
from alphafold2_tpu.serve.resilience import (CircuitBreaker, Quarantine,
                                             RetryPolicy, WatchdogTimeout,
                                             run_with_watchdog)


class QueueFullError(RuntimeError):
    """submit() refused: queue at queue_limit and full_policy='reject'."""


class DrainingError(QueueFullError):
    """submit() refused: the scheduler is draining (graceful shutdown —
    in-flight work finishes, new work must go to another replica).
    Subclasses QueueFullError so callers that already handle the
    rejected-at-the-door case treat a draining replica the same way:
    retry elsewhere, nothing was lost."""


@dataclass
class SchedulerConfig:
    max_batch_size: int = 4
    max_wait_ms: float = 50.0      # oldest request age that forces a batch
    queue_limit: int = 256         # in-flight cap (queued, not yet folded)
    num_recycles: int = 1
    full_policy: str = "reject"    # "reject" | "block"
    poll_ms: float = 5.0           # worker wakeup granularity
    # Serving MSA depth. None = per-batch max over members — ONLY safe
    # when every request carries the same depth; ragged-depth traffic
    # then mints one compiled shape per observed depth and defeats the
    # closed-shape guarantee. Pin it (bucketing.assemble semantics:
    # pad shallow, keep the first msa_depth rows of deeper MSAs) for
    # production traffic; 0 serves MSA-free.
    msa_depth: Optional[int] = None
    # Cache-aware admission: bytes of parked duplicate-request arrays
    # submit() may admit as coalescing followers PAST a full queue
    # (an in-flight duplicate costs ~0 to serve). 0 (default) = off:
    # duplicates respect queue_limit exactly like novel work.
    parked_bytes_budget: int = 0
    # Summarize the distogram head at batch finish (ISSUE 19): each ok
    # response carries its mean normalized distogram entropy
    # (FoldResponse.distogram_entropy) so a cascade confidence gate can
    # read global uncertainty, not just pointwise pLDDT. Opaque-fold
    # path only (the step loop discards per-step distograms); off by
    # default — responses stay byte-identical.
    confidence_summary: bool = False

    def __post_init__(self):
        if self.full_policy not in ("reject", "block"):
            raise ValueError(f"full_policy must be 'reject' or 'block', "
                             f"got {self.full_policy!r}")
        if self.max_batch_size < 1 or self.queue_limit < 1:
            raise ValueError("max_batch_size and queue_limit must be >= 1")
        if self.parked_bytes_budget < 0:
            raise ValueError("parked_bytes_budget must be >= 0")


class _Entry:
    __slots__ = ("request", "ticket", "bucket_len", "enqueued_at",
                 "deadline", "cache_key", "store_key", "trace", "route",
                 "attempts", "not_before", "group",
                 "parked_admit_bytes", "cross_refused")

    def __init__(self, request: FoldRequest, bucket_len: int):
        self.request = request
        self.ticket = FoldTicket(request.request_id)
        self.bucket_len = bucket_len
        self.cache_key: Optional[str] = None   # set only on cache leaders
        # set when the key is known but the entry is NOT a leader (the
        # saturated block-mode fall-through): its successful fold still
        # populates the store, it just has no followers to settle
        self.store_key: Optional[str] = None
        self.trace = NULL_TRACE                # set by submit()
        self.route = None       # fleet RouteDecision, computed at most once
        self.attempts = 0       # executor batch executions participated in
        self.not_before = 0.0   # retry backoff gate (monotonic)
        # bisection isolation group: entries sharing a group id batch
        # ONLY with each other, so a failing cohort stays cornered
        self.group: Optional[int] = None
        # bytes this entry holds of the cache-aware admission budget
        # (nonzero only for followers admitted past a full queue)
        self.parked_admit_bytes = 0
        # the cross-bucket pricer refused this entry at least once
        # (ISSUE 13): the inline admission gate then treats it as
        # admission-can't-serve-it, so the loop drains and normal
        # batch formation takes over — max_wait stays a bounded
        # fallback even under pricer refusals
        self.cross_refused = False
        self.mark_enqueued()

    def resolve(self, response: FoldResponse):
        """THE terminal seam: resolve the caller's ticket and finish the
        request trace in one place, so every terminal path — ok, cache
        hit, coalesced, shed, error, cancelled, crash — yields exactly
        one completed trace. Trace.finish is idempotent; racing
        resolvers can't double-emit."""
        self.ticket._resolve(response)
        self.trace.finish(status=response.status, source=response.source,
                          error=response.error)

    def mark_enqueued(self):
        """(Re)start the latency/deadline clock — called again right
        before the entry actually enters the queue so time blocked on a
        full queue (full_policy='block') doesn't eat the deadline."""
        self.enqueued_at = time.monotonic()
        self.deadline = (None if self.request.deadline_s is None
                         else self.enqueued_at + self.request.deadline_s)


class _StepCheckpoint:
    """Host-side snapshot of a running step loop (ISSUE 14): the
    FoldStepState carry (predict.snapshot_step_state form), a COPY of
    the batch tensors' host mirror, and the loop membership (entries +
    position->row map + per-row ages) at loop step `step`. Everything
    is host memory owned by this object alone — it survives executor
    rebuilds and later admission rounds mutating the live mirror — so
    a transient failure or watchdog fire can re-upload it and resume
    the survivors at their checkpointed ages instead of requeueing the
    loop to recycle 0."""

    __slots__ = ("state", "host", "rows", "ages", "active", "step",
                 "kernel")

    def __init__(self, state, host, rows, ages, active, step, kernel):
        self.state = state
        self.host = host
        self.rows = rows
        self.ages = ages
        self.active = active
        self.step = step
        self.kernel = kernel


class Scheduler:
    """Dynamic batching fold server over one FoldExecutor.

    cache: optional FoldCache enabling result caching AND in-flight
        coalescing (both off when None — the default). model_tag
        namespaces cache keys by model identity; REQUIRED to be
        meaningful whenever the cache outlives one (model, params),
        e.g. any disk-backed store shared across restarts. Reassigning
        `model_tag` (a weight rollout — fleet.RolloutState subscribers
        do this) atomically re-keys every subsequent submit; old-tag
        entries become unreachable by construction.
    tracer: optional obs.Tracer for request-scoped traces (None — the
        default — is the zero-cost NULL_TRACER).
    registry: obs.MetricsRegistry the coalescing/follower-deadline
        counters report into (None = process default).
    router: optional fleet.ConsistentHashRouter (OFF when None — the
        default). When set, a request whose fold_key hashes to another
        healthy replica is FORWARDED there (one hop, bounded by
        FoldRequest.forwarded) so duplicate traffic coalesces fleet-wide
        on the key's owner; any forwarding trouble — owner down, no
        transport, remote backpressure — falls back to folding locally
        (fleet state can cost efficiency, never availability). The
        remote result resolves the local ticket via a done-callback and
        populates the local store on the way, so repeat traffic for the
        key turns into local cache hits.
    retry: optional serve.resilience.RetryPolicy (OFF when None — the
        default, which byte-for-byte preserves pre-resilience
        behavior). Enables transient-batch retry with backoff, poison
        isolation by bisection + keyed quarantine, non-finite output
        validation, the executor watchdog (retry.watchdog_s) and the
        degraded-mode circuit breaker (retry.breaker_threshold).
    executor_factory: zero-arg callable building a replacement executor
        after a watchdog fire; None falls back to `executor.rebuild()`
        when the executor provides it (FoldExecutor does), else the
        hung executor is kept (better a slow server than none).
    quarantine_path: optional JSONL file persisting the poison
        quarantine across restarts (only meaningful with `retry=`):
        keys quarantined in a previous process fail fast as
        "poisoned" from the first submit — a restarted replica never
        re-pays the bisection executions for a known poison. Put it
        next to the cache dir; the keys are the same content digests.
    mesh_policy: optional serve.meshpolicy.MeshPolicy (OFF when None —
        the default, which byte-for-byte preserves single-chip
        behavior). Requires a mesh-capable executor (FoldExecutor is).
        Buckets route to their policy slice, disjoint slices fold
        concurrently, and the analytic HBM admission guard rejects
        folds no configured slice can hold (status "too_large").
    recycle_policy: optional serve.recycle.RecyclePolicy (OFF when
        None — the default, which byte-for-byte preserves the opaque
        `lax.scan` fold behavior). Requires a step-capable executor
        (FoldExecutor is; an executor without run_init/run_step keeps
        the opaque path). The scheduler then drives the recycle loop
        one step at a time: early-exit on convergence, preemption
        between recycles, progressive results — see the module
        docstring and serve/recycle.py.
    kernel_policy: optional serve.kernelpolicy.KernelPolicy (OFF when
        None — the default, byte-for-byte the dense-only serving
        path). Per-bucket attention-kernel routing (ISSUE 12): short
        buckets compile the dense path, long buckets the block-sparse
        Pallas kernel with a static banded+global mask; with
        `contact_priors=True` under a recycle policy, each batch
        re-plans its mask from its own recycle-1 distogram and the
        remaining recycles run the re-lowered step executable. The
        kernel choice is an ExecKey element, so a policy flip can
        never serve a stale executable, and warmup() pre-compiles each
        bucket's chosen kernel.
    slo: optional obs.slo.SLOEngine (OFF when None — the default,
        which keeps serve_stats() keys and the registry metric-name
        set byte-identical). Declarative per-QoS-class objectives
        (latency percentile targets per bucket, availability over
        terminal statuses) computed as windowed error budgets + burn
        rates from the registry's own histograms/counters;
        serve_stats()["slo"] carries the report and slo_* gauges ride
        every /metrics scrape (ISSUE 15).
    cascade: optional serve.cascade.CascadePolicy (OFF when None — the
        default, byte-for-byte PR-18 behavior pinned by scrubbed-stats
        and metric-name-set identity tests). Interactive submits fold
        on the policy's DRAFT scheduler first; a confidence gate
        (serve/confidence.py) accepts the draft result (tier="draft")
        or escalates to this flagship through the ordinary submit seam
        (tier="flagship", escalated=True) with a priority boost and
        the remaining deadline. The two tiers share a FoldCache under
        distinct model_tags; a key collision is counted in
        serve_cascade_cross_tier_hits_total (pinned to 0) and
        escalated instead of served (ISSUE 19).
    """

    def __init__(self, executor: FoldExecutor, buckets: BucketPolicy,
                 config: Optional[SchedulerConfig] = None,
                 metrics: Optional[ServeMetrics] = None,
                 cache: Optional[FoldCache] = None,
                 model_tag: str = "",
                 tracer: Optional[Tracer] = None,
                 registry: Optional[MetricsRegistry] = None,
                 router=None,
                 retry: Optional[RetryPolicy] = None,
                 executor_factory: Optional[Callable[[], object]] = None,
                 quarantine_path: Optional[str] = None,
                 mesh_policy: Optional[MeshPolicy] = None,
                 recycle_policy: Optional[RecyclePolicy] = None,
                 feature_pool=None,
                 kernel_policy=None,
                 slo=None,
                 key_log=None,
                 bulk=None,
                 cascade=None):
        self.executor = executor
        # optional serve.metrics.KeyFrequencyLog (OFF when None — the
        # default, byte-identical): ingress submits (forwarded hops
        # excluded) are aggregated into a cache_warm-format profile so
        # the control plane can warm from SERVED traffic (ISSUE 16)
        self.key_log = key_log
        # optional obs.slo.SLOEngine (OFF when None — the default,
        # which keeps serve_stats() and the registry's metric-name set
        # byte-identical): declarative per-QoS-class latency/
        # availability objectives computed over the registry's own
        # histograms/counters, reported as serve_stats()["slo"] and
        # exported as slo_* gauges — the signal surface the future
        # autoscaler (and /metrics scrapes) consume (ISSUE 15)
        self.slo = slo
        # two-stage pipeline front (serve.features.FeaturePool — OFF
        # when None, the default, which keeps submit_raw featurizing
        # inline and serve_stats() byte-for-byte today's)
        self.feature_pool = feature_pool
        self.buckets = buckets
        self.config = config or SchedulerConfig()
        self.metrics = metrics or ServeMetrics()
        self.cache = cache
        self.model_tag = model_tag
        self.router = router
        self.retry = retry
        self.executor_factory = executor_factory
        self.tracer = tracer or NULL_TRACER
        reg = registry or get_registry()
        self._c_follower_deadline = reg.counter(
            "serve_follower_deadline_exceeded_total",
            "parked followers shed on their own expired deadline")
        self._quarantine: Optional[Quarantine] = None
        self._breaker: Optional[CircuitBreaker] = None
        # lifetime resilience counters (worker-thread writes; racy reads
        # from serve_stats are fine for a health view)
        self._n_retries = 0
        self._n_bisections = 0
        self._n_watchdog_fires = 0
        self._n_rebuilds = 0
        self._n_nonfinite = 0
        self._n_failovers = 0
        self._n_drains = 0
        if retry is not None:
            self._quarantine = Quarantine(registry=registry,
                                          path=quarantine_path)
            # worker-owned jitter stream: a RetryPolicy shared across
            # schedulers must not race N workers on one RNG. Callers
            # that fan one policy out across replicas give each copy
            # its own seed (fleet.InProcessFleet does) so replicas
            # don't back off in lockstep after a correlated transient
            # episode — identical streams would defeat the
            # thundering-herd jitter
            self._retry_rng = random.Random(retry.seed)
            if retry.breaker_threshold:
                self._breaker = CircuitBreaker(
                    retry.breaker_threshold, retry.breaker_cooldown_s,
                    registry=registry)
            self._group_counter = itertools.count(1)
            self._c_retries = reg.counter(
                "serve_retries_total",
                "requests re-enqueued after a transient batch failure")
            self._c_bisections = reg.counter(
                "serve_poison_bisections_total",
                "failing batches split for poison isolation")
            self._c_watchdog = reg.counter(
                "serve_watchdog_fires_total",
                "batches killed by the executor watchdog deadline")
            self._c_rebuilds = reg.counter(
                "serve_executor_rebuilds_total",
                "executors rebuilt after a watchdog fire")
            self._c_nonfinite = reg.counter(
                "serve_nonfinite_outputs_total",
                "fold outputs rejected by non-finite validation")
        # step-loop fault domains (ISSUE 14): carry checkpointing +
        # per-row poison isolation. Counters minted only when a knob is
        # on, so `retry=` without them stays byte-for-byte PR-5 —
        # including the registry's metric-name set
        self._n_checkpoints = 0
        self._n_ckpt_resumes = 0
        self._n_recycles_lost = 0
        self._n_row_isolations = 0
        if retry is not None and (getattr(retry, "checkpoint_every", 0)
                                  or getattr(retry, "row_isolation",
                                             False)):
            self._c_ckpt_resumes = reg.counter(
                "serve_checkpoint_resumes_total",
                "step loops resumed at their checkpointed ages after a "
                "transient failure or watchdog fire")
            self._c_recycles_lost = reg.counter(
                "serve_recycles_lost_total",
                "recycle steps re-executed because they landed between "
                "the last checkpoint and a failure (the bounded "
                "progress loss of checkpoint recovery)")
            self._c_row_isolations = reg.counter(
                "serve_row_poison_isolations_total",
                "batch rows retired alone by per-row poison isolation "
                "(non-finite scan or row-attributed deterministic "
                "failure) while their batch mates kept folding")
        # durable checkpoint spill (ISSUE 18): per-row mid-loop
        # checkpoints outlive the process in a cache.checkpoints
        # CheckpointStore so a restarted replica (or a failover peer
        # reached through the store's backend/peer tiers) resumes
        # survivors at their checkpointed ages instead of refolding.
        # OFF unless RetryPolicy.checkpoint_spill names a directory —
        # the store (and its counters) is never built otherwise,
        # keeping scrubbed serve_stats() and the registry metric-name
        # set byte-identical
        self._ckpt_store = None
        self._n_spill_resumes = 0
        self._boot_survivors = 0
        spill_dir = "" if retry is None else getattr(
            retry, "checkpoint_spill", "")
        if spill_dir:
            from alphafold2_tpu.cache.checkpoints import CheckpointStore
            self._ckpt_store = CheckpointStore(
                spill_dir, model_tag=model_tag, registry=registry)
            self._c_spill_resumes = reg.counter(
                "serve_spill_resumes_total",
                "fold rows resumed mid-loop from a durable spilled "
                "checkpoint (local disk, object store, or peer)")
            try:
                self._boot_survivors = sum(
                    1 for _ in self._ckpt_store.survivors())
            except Exception:
                self._boot_survivors = 0
        # bulk tier (ISSUE 18): lowest-QoS sweep work admitted only by
        # work-stealing through the continuous-admission front, gated
        # by online burn rate. OFF when None — byte-identical stats
        self.bulk = bulk
        self._bulk_queue = None
        self._n_bulk_admits = 0
        self._n_bulk_yields = 0
        self._n_bulk_rejected = 0
        self._bulk_gated_flag = False
        self._bulk_last_check = 0.0
        if bulk is not None:
            from alphafold2_tpu.serve.bulk import BulkQueue
            self._bulk_queue = BulkQueue()
            self._c_bulk_admits = reg.counter(
                "serve_bulk_admits_total",
                "bulk-QoS requests admitted into fold batches (stolen "
                "freed rows or idle-founded batches)")
            self._c_bulk_yields = reg.counter(
                "serve_bulk_yields_total",
                "in-flight bulk rows that checkpointed-and-yielded at "
                "an admission gap because online burn crossed "
                "BulkPolicy.max_burn")
            self._g_bulk_gated = reg.gauge(
                "serve_bulk_gated",
                "1 while bulk admission is gated by online burn rate")
        # speculative cascade (ISSUE 19): draft-first folding with a
        # confidence gate, escalation through this very submit seam.
        # OFF when None — the default, byte-identical stats and
        # registry metric-name set (the identity tests pin it)
        self.cascade = cascade
        self._n_draft_accepted = 0
        self._n_escalated = 0
        self._n_draft_errors = 0
        self._n_cross_tier_hits = 0
        self._confidence_sum = 0.0        # over gate-scored drafts
        self._confidence_n = 0
        if cascade is not None:
            if getattr(cascade.draft, "model_tag", "") == model_tag:
                raise ValueError(
                    f"cascade draft model_tag {model_tag!r} collides "
                    f"with the flagship's — the shared FoldCache keys "
                    f"tiers apart by tag, so they MUST differ")
            self._c_cascade = reg.counter(
                "serve_cascade_requests_total",
                "cascaded submits by tier and gate outcome",
                ("tier", "outcome"))
            self._c_cross_tier = reg.counter(
                "serve_cascade_cross_tier_hits_total",
                "cascaded submits whose draft and flagship cache keys "
                "collided (MUST stay 0: fold_key embeds model_tag; a "
                "nonzero value means a keying regression could serve "
                "draft structures to flagship callers)")
        # express QoS lane (ISSUE 19): counters minted LAZILY on the
        # first express submit so a scheduler that never sees express
        # traffic keeps the registry metric-name set byte-identical
        self._registry = reg
        self._c_express = None
        self._h_express = None
        self._express_counts: Dict[str, int] = {}
        # step-mode recycle scheduling (before the mesh block: the LRU
        # autosizing below must know whether each (bucket, slice) needs
        # one executable or the init+step pair)
        self.recycle_policy = recycle_policy
        self._step_capable = hasattr(executor, "run_init") \
            and hasattr(executor, "run_step")
        self._n_recycles_exec = 0       # batch-level step executions
        self._n_recycles_skipped = 0    # batch-level steps early-exited
        self._n_preemptions = 0
        self._n_preempt_hbm_refusals = 0   # leased yields refused: the
        #   urgent batch + the suspended loop's resident carry would
        #   exceed per-device HBM (memory-aware preemption admission)
        self._n_retired_early = 0       # elements resolved before the
        self._n_parked_admits = 0       # last configured recycle
        # continuous batching (ISSUE 11): row-level occupancy ledger.
        # live/total accumulate per executed step; their ratio is the
        # rows-occupied fraction the smoke gates on; dead steps are the
        # padded row-steps continuous admission exists to eliminate
        self._n_row_admissions = 0
        self._n_rows_dead_steps = 0
        self._row_steps_live = 0
        self._row_steps_total = 0
        # cross-bucket admission (ISSUE 13): freed rows serving shorter
        # buckets' pending work at the host shape, priced per admit
        self._n_cross_admissions = 0
        self._n_cross_refusals = 0
        # per-bucket EWMA of measured step-executable seconds — what
        # the AdmissionPricer converts loop extension into wall time
        # with (worker/pool-thread writes, racy reads are fine for a
        # pricing heuristic)
        self._step_ewma: Dict[int, float] = {}
        # "a preemptor never preempts": per-thread reentrancy guard for
        # the between-recycles preemption window
        self._preempting = threading.local()
        if recycle_policy is not None:
            self._c_recycles = reg.counter(
                "serve_recycles_total",
                "recycle step executions by the step-mode scheduler")
            self._c_recycles_skipped = reg.counter(
                "serve_recycles_skipped_total",
                "recycle steps skipped because every batch element "
                "converged early")
            self._c_preemptions = reg.counter(
                "serve_preemptions_total",
                "batches preempted between recycles by tighter-deadline "
                "pending work")
            self._c_preempt_hbm_refusals = reg.counter(
                "serve_preempt_hbm_refusals_total",
                "leased preemption yields refused because the urgent "
                "batch plus the suspended loop's HBM-resident carry "
                "would exceed the per-device budget")
            self._c_row_admissions = reg.counter(
                "serve_row_admissions_total",
                "pending requests admitted into freed batch rows "
                "mid-recycle by the continuous batcher")
            self._c_rows_dead_steps = reg.counter(
                "serve_rows_dead_steps_total",
                "row-steps executed on dead (unoccupied) batch rows — "
                "the padding waste continuous admission eliminates")
            self._g_rows_occupied = reg.gauge(
                "serve_rows_occupied_fraction",
                "live rows / batch rows of the step executed last, "
                "sampled per recycle step")
            self._c_cross_admissions = reg.counter(
                "serve_cross_bucket_admissions_total",
                "pending requests from a shorter bucket admitted into "
                "a longer host batch's freed rows at the host shape "
                "(cross-bucket continuous batching)",
                ("host_bucket", "native_bucket"))
            # step mode needs TWO executables per (bucket, slice) —
            # init + step (THREE with continuous batching: + the
            # row-masked init_rows admission program); grow the LRU so
            # warmup's set is not self-evicting (the mesh block below
            # multiplies its own sizing the same way)
            per_bucket = 3 if recycle_policy.continuous else 2
            if self._step_capable and hasattr(executor, "max_entries"):
                executor.max_entries = max(
                    executor.max_entries,
                    per_bucket * len(self.buckets.edges))
        # per-bucket attention-kernel routing (ISSUE 12) — nothing below
        # touches the serving path when the policy is None
        self.kernel_policy = kernel_policy
        self._kernel_served: Dict[Tuple[str, int], int] = {}
        self._kernel_batches: Dict[Tuple[str, int], int] = {}
        if kernel_policy is not None:
            self._c_kernel_folds = reg.counter(
                "serve_kernel_folds_total",
                "requests served, by attention kernel and bucket",
                ("kernel", "bucket"))
            self._c_kernel_replans = reg.counter(
                "serve_kernel_contact_replans_total",
                "step loops whose block mask was re-planned from "
                "recycle-1 contact priors (re-lowered step executable)")
        if self.config.parked_bytes_budget > 0 or cache is not None:
            self._c_parked_admits = reg.counter(
                "serve_parked_admits_total",
                "coalescing followers admitted past a full queue under "
                "the parked-bytes budget")
        self._parked_admit_bytes = 0     # guarded by _cond
        # best-effort preemption signal for leased step loops: the
        # tightest deadline currently pending, refreshed by the worker
        # each loop pass (pool threads read it under _cond)
        self._pending_tightest: Optional[float] = None
        self._pending_tightest_chips: Optional[int] = None
        self._pending_tightest_bucket: Optional[int] = None
        self._pending_tightest_msa: Optional[int] = None
        self.mesh_policy = mesh_policy
        self._allocator = None
        self._mesh_pool: Optional[ThreadPoolExecutor] = None
        self._inflight_execs = 0        # guarded by _cond (mesh only)
        self._mesh_batches: Dict[str, int] = {}   # label -> batch count
        self._mesh_served: Dict[str, int] = {}    # label -> served reqs
        if mesh_policy is not None:
            self._allocator = mesh_policy.allocator()
            # read-busy + set-gauge must be one atomic step: two pool
            # threads releasing concurrently could otherwise publish a
            # stale nonzero occupancy that sticks until the next lease
            self._gauge_lock = threading.Lock()
            # one executable per (bucket, aligned slice) must fit the
            # LRU or warmup evicts its own work and serving pays the
            # cold mid-batch compile anyway — the scheduler knows the
            # policy and the allocator, so the sizing lives here, not
            # in every caller
            if hasattr(executor, "max_entries"):
                needed = sum(
                    len(self._allocator.slices(
                        mesh_policy.shape_for(edge)))
                    for edge in self.buckets.edges)
                if recycle_policy is not None and self._step_capable:
                    # init + step pair per slice (+ init_rows when the
                    # continuous batcher admits rows mid-loop)
                    needed *= 3 if recycle_policy.continuous else 2
                executor.max_entries = max(executor.max_entries, needed)
            self._c_mesh_folds = reg.counter(
                "serve_mesh_folds_total",
                "fold batches executed, by mesh shape", ("mesh",))
            self._g_mesh_busy = reg.gauge(
                "serve_mesh_busy_devices",
                "devices currently leased to in-flight fold batches")
            self._c_too_large = reg.counter(
                "serve_too_large_total",
                "folds rejected by the HBM admission guard: footprint "
                "exceeds the largest configured mesh slice")
        # cross-bucket admission pricer (ISSUE 13): built after the
        # mesh block so it shares the HBM model's pair/MSA cost terms
        # when one is configured; None whenever the policy never asks
        # for cross-bucket admission
        self._admission_pricer: Optional[AdmissionPricer] = None
        if recycle_policy is not None and recycle_policy.cross_bucket:
            self._admission_pricer = AdmissionPricer(
                memory=(None if mesh_policy is None
                        else mesh_policy.memory),
                max_pad_frac=recycle_policy.cross_bucket_max_pad_frac)
        self._c_drains = reg.counter(
            "serve_drains_total", "graceful drains started")
        self._c_failovers = reg.counter(
            "fleet_failovers_total",
            "forwarded tickets whose owner's transport died, "
            "re-folded locally")
        self._inflight = InflightRegistry(registry=registry)
        self._cond = threading.Condition()
        self._incoming: deque = deque()
        self._pending: Dict[int, List[_Entry]] = {}
        self._depth = 0            # incoming + pending, guarded by _cond
        self._running = False
        self._drain = True
        self._draining = False     # graceful drain: admitting stopped
        # preemption reclaim (ISSUE 20): a spot notice flips the
        # scheduler into reclaim mode — a drain variant that stops
        # founding batches and admitting rows, and spills in-flight
        # loops whose remaining recycles cannot fit the grace window.
        # Counters are minted LAZILY on the first notice so a
        # never-preempted scheduler's registry metric-name set and
        # scrubbed stats stay byte-identical (the identity pin).
        self._reclaiming = False
        self._reclaim_deadline: Optional[float] = None
        self._reclaim_source = ""
        self._n_preempt_notices = 0
        self._n_preempt_spills = 0
        self._c_preempt_notices = None
        self._c_preempt_spills = None
        self._outstanding_forwards = 0   # guarded by _cond
        self._worker: Optional[threading.Thread] = None

    # -- model identity ---------------------------------------------------

    @property
    def model_tag(self) -> str:
        return self._model_tag

    @model_tag.setter
    def model_tag(self, tag: str):
        """Reassigning the tag (a weight rollout — fleet.RolloutState
        subscribers do exactly this) re-keys every subsequent cache
        submit AND re-tags the executor, whose ExecKeys carry the tag:
        a rolled scheduler can never serve an executable compiled under
        the previous weights' identity (ISSUE 7 staleness fix)."""
        self._model_tag = tag
        ex = getattr(self, "executor", None)
        if ex is not None and hasattr(ex, "model_tag"):
            ex.model_tag = tag
        # re-tag the checkpoint spill store too: a rolled scheduler
        # must never resume a carry computed under the previous
        # weights' identity (the store discards stale-tag survivors)
        cs = getattr(self, "_ckpt_store", None)
        if cs is not None:
            cs.model_tag = tag

    @property
    def checkpoint_store(self):
        """The durable checkpoint spill store, or None when the
        `RetryPolicy(checkpoint_spill=)` knob is off. Harnesses wire
        its fleet tiers post-construction (`.peer`, `.backend`) and
        hand it to `fleet.PeerCacheServer.checkpoint_source` so peers
        can fetch this replica's spilled carries (ISSUE 18)."""
        return self._ckpt_store

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Scheduler":
        with self._cond:
            if self._running:
                return self
            self._running = True
            self._drain = True
            self._draining = False
        # the cascade is one serving unit: the draft tier comes up with
        # the flagship (unless its lifecycle is owned elsewhere)
        if self.cascade is not None and self.cascade.manage_draft:
            self.cascade.draft.start()
        if self._allocator is not None and self._mesh_pool is None:
            self._mesh_pool = ThreadPoolExecutor(
                max_workers=max(1, self._allocator.total_devices),
                thread_name_prefix="serve-mesh")
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="serve-scheduler")
        self._worker.start()
        return self

    def stop(self, drain: bool = True):
        """Stop the worker. drain=True folds everything already queued
        (expired deadlines still shed); drain=False resolves queued
        requests as status='cancelled'."""
        with self._cond:
            self._running = False
            self._drain = drain
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        # stop the draft AFTER the flagship worker: in-flight cascade
        # callbacks may still escalate into (or resolve off) the draft
        # until the flagship queue drained
        if self.cascade is not None and self.cascade.manage_draft:
            self.cascade.draft.stop(drain=drain)
        if self.key_log is not None:
            self.key_log.flush()   # profile durable across restarts
        if self._mesh_pool is not None:
            # the worker already waited out in-flight mesh executions,
            # so this is a fast thread teardown; start() re-creates it
            self._mesh_pool.shutdown(wait=True)
            self._mesh_pool = None

    def drain(self, timeout_s: float = 30.0,
              grace_s: Optional[float] = None) -> bool:
        """Graceful drain — THE process-level shutdown path (wire it to
        SIGTERM): stop admitting (new submits raise DrainingError — a
        fleet front door maps that to 503 so callers retry elsewhere),
        wait for outstanding FORWARDED tickets to resolve or fail over
        (bounded by timeout_s; the transport's own poll budget
        guarantees they terminate), then fold everything queued
        (expired deadlines still shed) and fan terminal states out to
        parked followers via the normal settlement machinery. Every
        entry pending at drain start carries a `drain` span from drain
        start to its terminal state, so the waterfall prices what a
        rolling restart costs requests. Returns True when the drain
        fully completed (False = the forwarded-ticket wait timed out;
        local work still resolved). Idempotent; safe from a signal-
        handler-fed thread.

        grace_s (ISSUE 20): GRACE-BUDGETED drain for a preemption
        reclaim — the process dies in `grace_s` seconds no matter
        what, so finishing folds is conditional: in-flight step loops
        whose remaining recycles FIT the window run to completion;
        loops that cannot fit checkpoint-spill every row at the next
        gap and resolve them "preempted" (the checkpoint survives for
        adoption — see `CheckpointStore.publish_manifest`); queued
        work that never founded resolves "preempted" immediately
        instead of being folded. None (the default) is byte-for-byte
        the finish-everything drain above."""
        if grace_s is None:
            with self._cond:
                if not self._running and not self._draining:
                    return True        # never started / already stopped
                first = not self._draining
                self._draining = True
                if first:
                    for e in itertools.chain(self._incoming,
                                             *self._pending.values()):
                        e.trace.begin("drain")
                    # wake submitters blocked on a full queue NOW: they
                    # must raise DrainingError immediately, not wait out
                    # the forwarded-ticket grace below
                    self._cond.notify_all()
            if first:
                self._n_drains += 1
                self._c_drains.inc()
            complete = True
            deadline = time.monotonic() + timeout_s
            with self._cond:
                while self._outstanding_forwards > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        complete = False
                        break
                    self._cond.wait(timeout=remaining)
            self.stop(drain=True)
            return complete
        # grace-budgeted reclaim drain
        with self._cond:
            if not self._running and not self._draining:
                return True
        self.preempt_notice(grace_s)
        complete = True
        deadline = self._reclaim_deadline or \
            (time.monotonic() + float(grace_s))
        with self._cond:
            while self._outstanding_forwards > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    complete = False
                    break
                self._cond.wait(timeout=remaining)
        # stop WITHOUT the finish-everything drain: queued entries
        # resolve "preempted" via _cancel_remaining (the reclaim flag
        # switches its status), in-flight loops exit through the gap
        # fit-test (finish when it fits, spill when it cannot) before
        # the worker join / mesh-pool shutdown below return
        self.stop(drain=False)
        return complete

    def preempt_notice(self, grace_s: float, source: str = ""):
        """Reclaim mode (ISSUE 20): this process has `grace_s` seconds
        to live. Stops founding batches and admitting rows (bulk
        included), 503s new submits (`_draining` — the front door
        advertises `preempting` so clients mark this replica down
        immediately), makes every recycle gap checkpoint, and arms the
        gap-time fit test that spills loops the window cannot finish.
        Idempotent — a later duplicate notice only tightens the
        deadline, never extends it. Safe from any thread (the
        PreemptionWatcher's poll thread calls it). Does NOT stop the
        scheduler: the caller owns the actual drain
        (`drain(grace_s=)`) and exit."""
        now = time.monotonic()
        deadline = now + float(grace_s)
        with self._cond:
            first = not self._reclaiming
            self._reclaiming = True
            if self._reclaim_deadline is None \
                    or deadline < self._reclaim_deadline:
                self._reclaim_deadline = deadline
            if source:
                self._reclaim_source = source
            if first:
                self._draining = True
                for e in itertools.chain(self._incoming,
                                         *self._pending.values()):
                    e.trace.begin("preempt")
                self._cond.notify_all()
        if first:
            self._n_preempt_notices += 1
            if self._c_preempt_notices is None:
                # lazy mint: the first notice ever is when the metric
                # family appears (identity discipline)
                self._c_preempt_notices = self._registry.counter(
                    "serve_preempt_notices_total",
                    "preemption notices that flipped the scheduler "
                    "into reclaim mode")
                self._c_preempt_spills = self._registry.counter(
                    "serve_preempt_drain_spills_total",
                    "in-flight step-loop rows checkpoint-spilled by a "
                    "grace-budgeted reclaim drain (resolved "
                    "'preempted' for controller adoption)")
            self._c_preempt_notices.inc()

    @property
    def preempting(self) -> bool:
        """True once a preemption notice flipped this scheduler into
        reclaim mode — the health/503 payloads advertise it so peers
        and clients mark the replica down without a count-up."""
        return self._reclaiming

    def health(self) -> dict:
        """The one health payload every probe shares (the front door's
        /healthz, the peer cache server's, the router's health walk):
        liveness, drain state, queue depth, breaker state. A replica
        with `breaker == "open"` is up but NOT serving novel folds —
        recovery probes must treat it as still-down."""
        with self._cond:
            depth = self._depth
            running = self._running
            draining = self._draining
            reclaiming = self._reclaiming
        payload = {"running": running,
                   "draining": draining,
                   "queue_depth": depth,
                   "breaker": (None if self._breaker is None
                               else self._breaker.state),
                   "model_tag": self.model_tag}
        if reclaiming:
            # only under reclaim: the healthy payload stays
            # byte-identical, and probes treat `preempting` as an
            # immediate mark-down (no consecutive-failure count-up)
            payload["preempting"] = True
        if self._allocator is not None:
            # mesh occupancy rides the one health payload every probe
            # shares, so the fleet front door / peer probes see it free
            payload["mesh"] = self._allocator.snapshot()
        return payload

    def __enter__(self) -> "Scheduler":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def warmup(self, msa_depth: Optional[int] = None) -> int:
        """Precompile every bucket at the serving signature so the first
        real request pays queueing, not XLA. Returns fresh compiles.
        Defaults to the config's pinned msa_depth; the guarantee only
        holds when serving shapes are pinned to match (config.msa_depth,
        or uniform-depth traffic equal to this depth). With a mesh
        policy, each bucket warms on EVERY aligned slice of its shape:
        executables are bound to concrete devices, so a batch dispatched
        to a cold slice would pay a fresh XLA compile mid-serving —
        exactly the unlucky-first-request cost warmup exists to
        pre-pay. (Run warmup before start(); it touches slices without
        leasing them.)"""
        if msa_depth is None:
            msa_depth = self.config.msa_depth or 0
        keys = [(edge, self.config.max_batch_size, msa_depth,
                 self.config.num_recycles) for edge in self.buckets.edges]
        # with a recycle policy the serving path runs the init+step
        # executable pair (plus the row-masked init_rows admission
        # program when continuous), never the opaque fold — warm what
        # will run so a mid-loop row admission never compiles mid-serve
        step_mode = self._use_step_loop()
        continuous = self._use_continuous()
        if self._allocator is None:
            if self.kernel_policy is None:
                return self.executor.warmup(keys, step_mode=step_mode,
                                            continuous=continuous)
            # per-bucket kernel routing (ISSUE 12): warm the executable
            # each bucket will ACTUALLY serve — a sparse-routed bucket
            # compiled dense here would still pay its kernel compile on
            # the first real request
            return sum(self.executor.warmup(
                [key], step_mode=step_mode, continuous=continuous,
                kernel=self._kernel_spec_for(key[0])) for key in keys)
        fresh = 0
        for key in keys:
            if not self.mesh_policy.admits(
                    key[0], key[1], key[2],
                    carry_recyclables=step_mode,
                    continuous=continuous):
                continue     # the guard rejects this bucket at submit;
                #              compiling it would be the OOM we prevent
            shape = self.mesh_policy.shape_for(key[0])
            k_kw = {} if self.kernel_policy is None else \
                {"kernel": self._kernel_spec_for(key[0])}
            for devices in self._allocator.slices(shape):
                fresh += self.executor.warmup(
                    [key], devices=devices, mesh_shape=shape,
                    step_mode=step_mode, continuous=continuous, **k_kw)
        return fresh

    def _use_step_loop(self) -> bool:
        return self.recycle_policy is not None and self._step_capable

    def _use_continuous(self) -> bool:
        """True when the step loop will ADMIT rows mid-recycle
        (continuous batching, ISSUE 11): a step-capable executor that
        also speaks the row-masked init variant, under a policy that
        asked for it."""
        return self._use_step_loop() and self.recycle_policy.continuous \
            and hasattr(self.executor, "run_init_rows")

    def _use_cross_bucket(self) -> bool:
        """True when freed rows may additionally admit pending work
        from SHORTER buckets at the host shape (cross-bucket continuous
        batching, ISSUE 13) — the continuous machinery plus a policy
        that asked for it (the pricer exists iff it did)."""
        return self._use_continuous() and self.recycle_policy.cross_bucket

    def _eager_form_on(self) -> bool:
        """Admission-aware batch formation (ISSUE 13): form an
        under-filled batch immediately instead of waiting out max_wait,
        counting on mid-loop row admission to top it up. Only
        meaningful when admission can actually run."""
        return self._use_continuous() and self.recycle_policy.eager_form

    # -- kernel selection (ISSUE 12) -------------------------------------

    def _kernel_spec_for(self, bucket_len: int):
        """The static first-pass KernelSpec this bucket serves under
        the kernel policy (None = dense / policy off)."""
        if self.kernel_policy is None:
            return None
        return self.kernel_policy.spec_for(bucket_len)

    def _record_kernel_batch(self, bucket_len: int, spec, n_served: int,
                             contact: bool = False):
        """Per-(kernel, bucket) accounting for one executed batch.
        No-op without a policy — `serve_stats()` stays byte-identical."""
        if self.kernel_policy is None:
            return
        kind = "dense" if spec is None else "blocksparse"
        if contact:
            kind += "-contact"
        key = (kind, bucket_len)
        with self._cond:
            self._kernel_served[key] = \
                self._kernel_served.get(key, 0) + n_served
            self._kernel_batches[key] = \
                self._kernel_batches.get(key, 0) + 1
        self._c_kernel_folds.inc(n_served, kernel=kind,
                                 bucket=str(bucket_len))

    # -- submission ------------------------------------------------------

    def _raise_unless_running(self, entry: _Entry):
        """Lifecycle gate for submit()'s early-exit paths (quarantine,
        cache/forward, breaker): a stopped/unstarted scheduler raises
        for every request — lifecycle wins over content and breaker
        state, it never tells the caller to wait out a recovery that
        will never come."""
        with self._cond:
            if not self._running:
                entry.trace.finish("error", error="submit before start")
                raise RuntimeError("Scheduler.submit() before start()")

    def submit(self, request: FoldRequest,
               trace=None, _escalation: bool = False) -> FoldTicket:
        """trace: an already-started obs.Trace to continue instead of
        minting a fresh one — the feature pool passes the raw job's
        trace so its `featurize` span and the fold stages land in ONE
        record. None (the default, every pre-pipeline caller) is
        byte-for-byte the old behavior.

        _escalation (private): this submit IS a cascade escalation —
        skip the cascade branch and ride the ordinary flagship path,
        so an escalated request can never recurse into a second draft
        attempt."""
        bucket_len = self.buckets.bucket_for(request.length)  # fail fast
        entry = _Entry(request, bucket_len)
        entry.trace = (trace if trace is not None
                       else self.tracer.start_trace(request.request_id))
        entry.trace.begin("submit")
        # express lane accounting (ISSUE 19): every terminal outcome of
        # an express-QoS request lands in its own metric class, armed
        # here so each of submit()'s many terminal paths is covered
        # uniformly. Lazy mint: no express traffic, no express metrics.
        if getattr(request, "qos", "online") == "express" \
                and not _escalation:
            self._arm_express(entry)
        # draining beats everything, cache hits included: a replica
        # being rolled must shrink to empty, and its caller must take
        # the work to a peer that will still be alive to serve it
        if self._draining:
            entry.trace.finish("rejected", error="draining")
            raise DrainingError(
                "Scheduler draining: not admitting new requests")
        # key-frequency telemetry at INGRESS only: a forwarded hop is
        # the same user request already counted where it arrived
        if self.key_log is not None and not request.forwarded:
            self.key_log.observe(request.seq, request.msa)
        # HBM admission guard: a fold whose analytic footprint exceeds
        # even the largest configured mesh slice would die in an XLA
        # OOM mid-batch, taking its whole cohort with it — reject it at
        # the door instead. An unpinned msa_depth (None) prices the
        # REQUEST's own depth: assemble pads the batch to its members'
        # max, so each member is priced at (at least) what it brings.
        # A store hit still serves (mirroring degraded mode — a cached
        # result costs no device memory); only coalescing/forwarding is
        # pointless for work this process can never execute.
        if self.mesh_policy is not None:
            guard_msa = self.config.msa_depth
            if guard_msa is None:
                guard_msa = 0 if request.msa is None \
                    else int(request.msa.shape[0])
            if not self.mesh_policy.admits(
                    bucket_len, self.config.max_batch_size, guard_msa,
                    carry_recyclables=self._use_step_loop(),
                    continuous=self._use_continuous()):
                self._raise_unless_running(entry)
                if not self._serve_too_large_from_cache(entry):
                    self._too_large_shed(entry)
                return entry.ticket
        # quarantined poison fails fast BEFORE cache/coalesce/forward:
        # a known-bad key must not re-fold, park followers, or burn a
        # forwarding hop
        if self._quarantine is not None and len(self._quarantine):
            self._raise_unless_running(entry)
            if self._fail_fast_quarantined(entry):
                return entry.ticket
        # bulk tier (ISSUE 18): lowest-QoS sweep work takes its own
        # queue. A store hit still serves (campaign re-runs are
        # idempotent), but bulk never coalesces or forwards — a bulk
        # LEADER could park online duplicates behind work the burn
        # gate may starve indefinitely, and a forwarded hop would
        # spend an online transport slot on background work
        if self.bulk is not None and \
                getattr(request, "qos", "online") == "bulk":
            self._raise_unless_running(entry)
            if self._serve_bulk_from_cache(entry):
                return entry.ticket
            if self._breaker is not None \
                    and not self._breaker.allow_submit():
                self._degraded_shed(entry)
                return entry.ticket
            return self._submit_bulk(entry)
        # speculative cascade (ISSUE 19): interactive classes fold on
        # the draft tier first; the confidence gate accepts or
        # escalates back through this seam (_escalation=True). This
        # sits BEFORE the cache/coalesce block: a cascaded entry must
        # not become a flagship coalescing LEADER — a draft-accepted
        # leader would settle its flagship-keyed followers with a
        # draft result. Bulk never cascades (background work has no
        # latency to speculate for, and a draft+flagship double fold
        # would cost MORE accelerator-seconds, the one thing bulk
        # optimizes).
        if self.cascade is not None and not _escalation \
                and getattr(request, "qos", "online") != "bulk":
            self._raise_unless_running(entry)
            return self._submit_cascade(entry)
        if self.cache is not None or self.router is not None:
            self._raise_unless_running(entry)
            if self.cache is not None \
                    and self._serve_from_cache_or_coalesce(entry):
                return entry.ticket
            if self._maybe_forward(entry):
                return entry.ticket
        # degraded mode: the breaker is open, so a NOVEL fold would only
        # queue behind a failing executor — fast-shed it. Cache hits and
        # coalesce attaches were already served above; forwarding to a
        # healthy owner also beats shedding, so this sits after both.
        if self._breaker is not None and not self._breaker.allow_submit():
            self._raise_unless_running(entry)
            self._degraded_shed(entry)
            return entry.ticket
        try:
            with self._cond:
                if not self._running:
                    raise RuntimeError("Scheduler.submit() before start()")
                # queued entries AND parked followers occupy the bound
                # (waiting() is 0 with no cache); follower settlement
                # notifies _cond so block-mode waiters see the shrink.
                # A LEADER gates on depth alone: its own parked
                # followers can only settle after it enqueues and
                # folds, so counting them here would be a circular
                # wait — leader parked forever on capacity that only
                # its own settlement frees. Follower growth is bounded
                # at attach time instead.
                while self._depth + (
                        self._inflight.waiting()
                        if entry.cache_key is None else 0) \
                        >= self.config.queue_limit:
                    if self.config.full_policy == "reject":
                        self.metrics.record_rejected()
                        raise QueueFullError(
                            f"queue at limit {self.config.queue_limit}")
                    self._cond.wait()
                    if not self._running:
                        raise RuntimeError("Scheduler stopped while "
                                           "blocked on a full queue")
                    if self._draining:
                        raise DrainingError(
                            "Scheduler started draining while blocked "
                            "on a full queue")
                entry.mark_enqueued()
                entry.trace.end("submit")
                entry.trace.begin("queue")
                self._incoming.append(entry)
                self._depth += 1
                depth = self._depth
                self._cond.notify_all()
        except BaseException as exc:
            # a leader that never made it into the queue still owes its
            # followers an exit: on a queue-full rejection, promote the
            # tightest-deadline survivor to leader (its siblings stay
            # parked behind it) — a rejected leader must not turn N
            # viable duplicates into N errors; on anything else (the
            # scheduler stopped mid-submit) error out the group
            rejected = isinstance(exc, QueueFullError)
            entry.trace.finish("rejected" if rejected else "error",
                               error=str(exc))
            if not (rejected and self._promote_follower(entry)):
                self._settle_followers(entry, FoldResponse(
                    request_id=request.request_id, status="error",
                    bucket_len=bucket_len,
                    error="coalescing leader rejected at submit "
                          "(queue full or scheduler stopped)"))
            raise
        self.metrics.record_enqueued(depth)
        return entry.ticket

    def submit_raw(self, raw, trace=None) -> FoldTicket:
        """Accept one RAW job (serve.features.RawFoldRequest: an AA
        string or untokenized array plus raw MSA). With a
        `feature_pool` attached, featurization runs off the hot path on
        the pool's workers — feature cache, in-flight featurize
        coalescing, feature-key routing and the `featurize` trace span
        all apply (the two-stage pipeline, ISSUE 10). Without one
        (the default), featurize runs inline right here and the result
        goes through the ordinary submit() — exactly what callers
        hand-rolled before this method existed, so the off switch is
        byte-for-byte today's behavior. Returns the same FoldTicket
        either way.

        trace: an already-started obs.Trace to continue (the front
        door passes a remote hop's continued trace, ISSUE 15); None —
        the default — mints one exactly as before."""
        from alphafold2_tpu.serve.features import featurize_raw
        if self.feature_pool is not None:
            return self.feature_pool.submit_raw(raw, self, trace=trace)
        if getattr(raw, "qos", "online") == "express":
            # the express lane IS the MSA-bypass featurizer — without a
            # FeaturePool carrying one, "express" would silently serve
            # the full prep path under an express deadline it can't meet
            raise ValueError(
                "qos='express' needs a FeaturePool with an express "
                "featurizer (Scheduler(feature_pool=FeaturePool("
                "express=...)))")
        feats = featurize_raw(raw)
        return self.submit(FoldRequest(
            seq=feats.seq, msa=feats.msa, request_id=raw.request_id,
            priority=raw.priority, deadline_s=raw.deadline_s,
            forwarded=raw.forwarded,
            qos=getattr(raw, "qos", "online")), trace=trace)

    # -- cache / coalescing ----------------------------------------------

    def _cache_key_for(self, request: FoldRequest) -> str:
        # a result-affecting recycle policy (converge_tol > 0 can serve
        # an early-exited fold) keys under distinct extras; tol-0 /
        # policy-off schedulers keep the bare key and stay
        # cache-compatible with each other and with offline
        # fold_and_write callers — an early-exited result must NEVER be
        # served to a caller demanding fixed full recycles (ISSUE 9)
        extras = None
        if self.recycle_policy is not None:
            extras = self.recycle_policy.key_extras()
        return fold_key(request.seq, request.msa,
                        msa_depth=self.config.msa_depth,
                        num_recycles=self.config.num_recycles,
                        model_tag=self.model_tag, extras=extras)

    def _serve_from_cache_or_coalesce(self, entry: _Entry) -> bool:
        """submit() fast path: True when the entry was fully handled
        (resolved from the store, or parked behind the in-flight
        leader). Cache trouble of any kind degrades to a miss — a
        broken cache must cost a recompute, never fail a submit."""
        try:
            # store_key holds the digest when the quarantine check
            # already paid for it this submit
            key = entry.store_key or self._cache_key_for(entry.request)
            # route BEFORE the cache lookup: a key this replica is
            # about to forward must not pay a guaranteed-miss peer
            # fetch to the very owner the request is going to (worst
            # case a full peer timeout when the owner is down, ahead
            # of a forward that would also fail) — the memory/disk
            # tiers still answer, only the network tier is skipped
            will_forward = self._route(entry, key)
            cached = self.cache.get(key, trace=entry.trace,
                                    peer=not will_forward)
        except Exception:                     # get() never raises; keying
            self.metrics.record_cache_miss()  # trouble degrades to a miss
            return False
        if cached is not None:
            self.metrics.record_cache_hit()
            entry.resolve(FoldResponse(
                request_id=entry.request.request_id, status="ok",
                coords=cached.coords.copy(),
                confidence=cached.confidence.copy(),
                bucket_len=entry.bucket_len,
                latency_s=time.monotonic() - entry.enqueued_at,
                source="cache"))
            return True
        self.metrics.record_cache_miss()
        # parked followers hold real memory (their request arrays), so
        # the bounded-queue invariant must cover them too: a duplicate
        # storm on one hot key must not grow the registry unboundedly
        # where pre-cache behavior would have hit queue_limit. Check
        # and attach under ONE lock — a window between them would let
        # concurrent duplicates all pass the check and overshoot the
        # limit. (Lock order _cond -> registry lock; no path takes them
        # in the other order.)
        def _trace_parked(leader):
            # runs under the registry lock: settlement cannot have
            # resolved (and emitted) this trace yet, so the leader
            # link is guaranteed to make it into the record
            if leader is not None:
                entry.trace.link(leader.trace.trace_id)
            entry.trace.event("coalesced")
            entry.trace.end("submit")
            entry.trace.begin("parked")

        with self._cond:
            if (self._depth + self._inflight.waiting()
                    >= self.config.queue_limit):
                # cache-aware admission (ISSUE 9): an in-flight
                # duplicate costs ~0 — it parks behind the leader and
                # never touches the accelerator — so a "full" queue may
                # still admit it as a FOLLOWER, bounded by the
                # parked-bytes budget on its request arrays. Only an
                # EXISTING leader qualifies (attach_follower refuses
                # otherwise): a novel key would enqueue exactly the
                # real work the bound just refused.
                budget = self.config.parked_bytes_budget
                if budget > 0:
                    nbytes = entry.request.seq.nbytes + (
                        0 if entry.request.msa is None
                        else entry.request.msa.nbytes)

                    def _trace_parked_admit(leader):
                        entry.trace.event("parked_admit", bytes=nbytes)
                        _trace_parked(leader)

                    if self._parked_admit_bytes + nbytes <= budget \
                            and self._inflight.attach_follower(
                                key, entry,
                                on_follower=_trace_parked_admit):
                        entry.parked_admit_bytes = nbytes
                        self._parked_admit_bytes += nbytes
                        self._n_parked_admits += 1
                        self._c_parked_admits.inc()
                        self.metrics.record_coalesced()
                        return True
                if self.config.full_policy == "reject":
                    self.metrics.record_rejected()
                    entry.trace.finish("rejected",
                                       error="queue + followers at limit")
                    raise QueueFullError(
                        f"queue + coalesced followers at limit "
                        f"{self.config.queue_limit}")
                # "block": fall through to the normal enqueue path,
                # which waits for capacity and folds this duplicate —
                # bounded beats deduped when the queue is saturated
                # (the fold still populates the store via store_key)
                entry.store_key = key
                return False
            is_leader, _ = self._inflight.attach_with_leader(
                key, entry, on_follower=_trace_parked)
        if not is_leader:
            self.metrics.record_coalesced()
            return True                       # follower: leader settles us
        entry.cache_key = key                 # leader: enqueue + settle
        return False

    # -- resilience: submit side -----------------------------------------

    def _entry_key(self, entry: _Entry) -> Optional[str]:
        """Best-effort content key for quarantine bookkeeping. Works
        without a cache attached (fold_key needs no store); keying
        trouble returns None — an unkeyable request can neither be
        quarantined nor fail fast, it just folds. The computed digest is
        memoized on the entry (store_key) so the cache/coalesce path
        never hashes the same seq+MSA twice."""
        if entry.cache_key is not None:
            return entry.cache_key
        if entry.store_key is not None:
            return entry.store_key
        try:
            entry.store_key = self._cache_key_for(entry.request)
            return entry.store_key
        except Exception:
            return None

    def _fail_fast_quarantined(self, entry: _Entry) -> bool:
        """True when the entry's key is quarantined poison: resolved
        status "poisoned" without touching queue, cache, or fleet."""
        key = self._entry_key(entry)
        if key is None or key not in self._quarantine:
            return False
        self.metrics.record_poisoned()
        entry.trace.event("quarantine_fastfail")
        entry.resolve(FoldResponse(
            request_id=entry.request.request_id, status="poisoned",
            bucket_len=entry.bucket_len,
            latency_s=time.monotonic() - entry.enqueued_at,
            error=f"request key quarantined as poison "
                  f"({self._quarantine.reason(key)}); failing fast"))
        return True

    def _serve_too_large_from_cache(self, entry: _Entry) -> bool:
        """Store-only lookup for a fold the admission guard would
        reject: a result computed elsewhere (a peer with bigger slices,
        an offline warm, this replica before a policy change) serves at
        zero device cost. No coalescing — there is no in-flight leader
        to park behind for work this process can never execute."""
        if self.cache is None:
            return False
        try:
            key = self._entry_key(entry)
            if key is None:
                return False
            cached = self.cache.get(key, trace=entry.trace)
        except Exception:
            return False
        if cached is None:
            return False
        self.metrics.record_cache_hit()
        entry.resolve(FoldResponse(
            request_id=entry.request.request_id, status="ok",
            coords=cached.coords.copy(),
            confidence=cached.confidence.copy(),
            bucket_len=entry.bucket_len,
            latency_s=time.monotonic() - entry.enqueued_at,
            source="cache"))
        return True

    def _too_large_shed(self, entry: _Entry):
        """HBM admission guard fast path: resolve a fold no configured
        mesh slice can hold as status "too_large" without enqueueing."""
        self.metrics.record_too_large()
        self._c_too_large.inc()
        entry.trace.event("too_large")
        chips = self.mesh_policy.chips_for(entry.bucket_len)
        entry.resolve(FoldResponse(
            request_id=entry.request.request_id, status="too_large",
            bucket_len=entry.bucket_len,
            latency_s=time.monotonic() - entry.enqueued_at,
            error=f"analytic HBM footprint of bucket {entry.bucket_len} "
                  f"exceeds the largest configured mesh slice "
                  f"({chips} chips); rejected by the admission guard"))

    def _degraded_shed(self, entry: _Entry):
        """Breaker-open fast path: resolve a novel submit as
        status "degraded" without enqueueing."""
        self.metrics.record_degraded()
        entry.trace.event("degraded_shed")
        resp = FoldResponse(
            request_id=entry.request.request_id, status="degraded",
            bucket_len=entry.bucket_len,
            latency_s=time.monotonic() - entry.enqueued_at,
            error="circuit breaker open: scheduler in degraded mode, "
                  "novel folds shed at the door")
        entry.resolve(resp)
        # followers that attached in the window between this entry
        # becoming leader and the breaker check inherit the same state
        # (no-op for non-leaders)
        self._settle_followers(entry, resp)

    # -- speculative cascade + express lane (ISSUE 19) --------------------

    def _arm_express(self, entry: _Entry):
        """Route every terminal outcome of an express-QoS request into
        the express metric class (counter by outcome, latency histogram
        by bucket) via a ticket done-callback — one hook covers all of
        submit()'s terminal paths uniformly. Metrics are minted on the
        FIRST express submit: a scheduler that never sees express
        traffic keeps the registry metric-name set byte-identical."""
        if self._c_express is None:
            self._c_express = self._registry.counter(
                "serve_express_requests_total",
                "terminal outcomes of express-QoS requests",
                ("outcome",))
            self._h_express = self._registry.histogram(
                "serve_express_latency_seconds",
                "submit-to-resolve latency of served express requests",
                ("bucket_len",))

        def _done(resp, entry=entry):
            outcome = "served" if resp.ok else resp.status
            self._express_counts[outcome] = \
                self._express_counts.get(outcome, 0) + 1
            self._c_express.inc(outcome=outcome)
            if resp.ok and resp.latency_s is not None:
                self._h_express.observe(
                    resp.latency_s,
                    bucket_len=(resp.bucket_len
                                if resp.bucket_len is not None
                                else entry.bucket_len))

        entry.ticket.add_done_callback(_done)

    def _submit_cascade(self, entry: _Entry) -> FoldTicket:
        """Draft-first fold: speculate on the cheap tier, gate on its
        own confidence, escalate losers to the flagship through the
        ordinary submit seam. The caller's ticket resolves exactly once
        on every path (accept, escalate, draft refusal, expired
        deadline, gate crash)."""
        policy = self.cascade
        request = entry.request
        entry.trace.event("cascade")
        # a flagship store hit short-circuits the draft: the
        # full-quality result is free, speculating would only add a
        # draft fold on top of it
        flagship_key = None
        cached = None
        if self.cache is not None:
            try:
                flagship_key = self._cache_key_for(request)
                cached = self.cache.get(flagship_key, trace=entry.trace)
            except Exception:
                flagship_key, cached = None, None
        if cached is not None:
            self.metrics.record_cache_hit()
            self._c_cascade.inc(tier="flagship", outcome="cache_hit")
            entry.resolve(FoldResponse(
                request_id=request.request_id, status="ok",
                coords=cached.coords.copy(),
                confidence=cached.confidence.copy(),
                bucket_len=entry.bucket_len,
                latency_s=time.monotonic() - entry.enqueued_at,
                source="cache", tier="flagship"))
            return entry.ticket
        # cross-tier tripwire: the shared FoldCache keys tiers apart by
        # model_tag ALONE, so equal keys mean a keying regression that
        # could serve draft structures under a flagship key. Never
        # speculate across it — escalate straight to the flagship.
        if flagship_key is not None:
            try:
                draft_key = policy.draft._cache_key_for(request)
            except Exception:
                draft_key = None
            if draft_key is not None and draft_key == flagship_key:
                self._n_cross_tier_hits += 1
                self._c_cross_tier.inc()
                entry.trace.event("cascade_cross_tier_key")
                self._escalate_cascade(entry, None, "cross_tier_key")
                return entry.ticket
        remaining = None if entry.deadline is None else \
            max(entry.deadline - time.monotonic(), 0.0)
        draft_req = FoldRequest(
            seq=request.seq, msa=request.msa,
            request_id=request.request_id, priority=request.priority,
            deadline_s=policy.draft_deadline(remaining))
        entry.trace.begin("draft")
        try:
            inner = policy.draft.submit(draft_req)
        except Exception as exc:
            # a refusing draft (full queue, draining, stopped) costs
            # the caller nothing but this failed speculation — the
            # flagship still owes the fold
            self._n_draft_errors += 1
            self._c_cascade.inc(tier="draft", outcome="refused")
            entry.trace.end("draft")
            entry.trace.event("draft_refused", error=repr(exc))
            self._escalate_cascade(entry, None, "draft_refused")
            return entry.ticket

        def _on_draft(resp, entry=entry):
            # runs on the draft's resolving thread; done-callbacks
            # swallow exceptions, so everything that can throw is
            # guarded — the caller's ticket must terminate regardless
            try:
                entry.trace.end("draft")
                if not resp.ok:
                    self._n_draft_errors += 1
                    self._c_cascade.inc(tier="draft", outcome=resp.status)
                    self._escalate_cascade(entry, None,
                                           f"draft_{resp.status}")
                    return
                score = score_response(resp)
                self._confidence_sum += score.score
                self._confidence_n += 1
                if not policy.gate.accepts(score):
                    self._c_cascade.inc(tier="draft", outcome="rejected")
                    self._escalate_cascade(entry, score,
                                           "low_confidence")
                    return
                self._n_draft_accepted += 1
                self._c_cascade.inc(tier="draft", outcome="accepted")
                latency = time.monotonic() - entry.enqueued_at
                self.metrics.record_served(entry.bucket_len, latency)
                entry.trace.event("draft_accepted",
                                  confidence=round(score.score, 4))
                entry.resolve(FoldResponse(
                    request_id=entry.request.request_id, status="ok",
                    coords=resp.coords, confidence=resp.confidence,
                    bucket_len=entry.bucket_len, latency_s=latency,
                    source=resp.source, attempts=resp.attempts,
                    recycles=resp.recycles, tier="draft",
                    confidence_score=score.score,
                    distogram_entropy=resp.distogram_entropy))
            except Exception as exc:
                try:
                    self.metrics.record_error()
                    entry.resolve(FoldResponse(
                        request_id=entry.request.request_id,
                        status="error", bucket_len=entry.bucket_len,
                        error=f"cascade gate failed: {exc!r}",
                        tier="draft"))
                except Exception:
                    pass

        inner.add_progress_callback(entry.ticket._publish_progress)
        inner.add_done_callback(_on_draft)
        return entry.ticket

    def _escalate_cascade(self, entry: _Entry, score, reason: str):
        """Hand a cascaded entry to the flagship tier: re-enter
        submit() with the escalation flag, priority boosted, deadline
        re-anchored to what remains of the CALLER's budget (the draft
        attempt already spent some of it). Called from submit()'s
        thread (cross-tier / draft-refused) or the draft's resolving
        thread (gate reject, draft error) — never raises; every
        failure resolves the caller's ticket."""
        self._n_escalated += 1
        self._c_cascade.inc(tier="flagship", outcome="escalated")
        entry.trace.event("escalated", reason=reason)
        request = entry.request
        remaining = None
        if entry.deadline is not None:
            remaining = entry.deadline - time.monotonic()
            if remaining <= 0:
                # the draft ate the whole budget: shed, exactly as the
                # queue would have — folding dead work helps nobody
                self.metrics.record_shed()
                entry.resolve(FoldResponse(
                    request_id=request.request_id, status="shed",
                    bucket_len=entry.bucket_len,
                    latency_s=time.monotonic() - entry.enqueued_at,
                    error=f"deadline exhausted before escalation "
                          f"({reason})",
                    tier="flagship", escalated=True,
                    confidence_score=(None if score is None
                                      else score.score)))
                return
        esc = FoldRequest(
            seq=request.seq, msa=request.msa,
            request_id=request.request_id,
            priority=request.priority + self.cascade.escalation_priority,
            deadline_s=remaining, forwarded=request.forwarded,
            qos=request.qos)
        try:
            inner = self.submit(esc, trace=entry.trace, _escalation=True)
        except Exception as exc:
            # the inner submit already finished the (shared) trace and
            # recorded its rejection; the outer ticket still owes the
            # caller a terminal state
            self.metrics.record_error()
            entry.resolve(FoldResponse(
                request_id=request.request_id, status="error",
                bucket_len=entry.bucket_len,
                latency_s=time.monotonic() - entry.enqueued_at,
                error=f"escalation refused: {exc!r}",
                tier="flagship", escalated=True))
            return

        def _on_flagship(resp, entry=entry, score=score):
            try:
                entry.resolve(dataclasses.replace(
                    resp,
                    latency_s=time.monotonic() - entry.enqueued_at,
                    tier="flagship", escalated=True,
                    confidence_score=(None if score is None
                                      else score.score)))
            except Exception:
                try:
                    entry.resolve(resp)
                except Exception:
                    pass

        inner.add_progress_callback(entry.ticket._publish_progress)
        inner.add_done_callback(_on_flagship)

    # -- bulk tier (ISSUE 18) --------------------------------------------

    def _serve_bulk_from_cache(self, entry: _Entry) -> bool:
        """Store-only lookup for a bulk submit (no coalescing — see
        submit()); sets store_key either way so the eventual fold
        writes back and the NEXT campaign run hits."""
        if self.cache is None:
            return False
        key = self._entry_key(entry)
        if key is None:
            return False
        try:
            cached = self.cache.get(key, trace=entry.trace)
        except Exception:
            return False
        if cached is None:
            self.metrics.record_cache_miss()
            return False
        self.metrics.record_cache_hit()
        entry.resolve(FoldResponse(
            request_id=entry.request.request_id, status="ok",
            coords=cached.coords.copy(),
            confidence=cached.confidence.copy(),
            bucket_len=entry.bucket_len,
            latency_s=time.monotonic() - entry.enqueued_at,
            source="cache"))
        return True

    def _submit_bulk(self, entry: _Entry) -> FoldTicket:
        """Enqueue into the bulk queue — its own bound, kept OUT of
        `_depth` so background backlog can never push the online queue
        into its full policy."""
        q = self._bulk_queue
        if len(q) >= self.bulk.max_pending:
            self._n_bulk_rejected += 1
            self.metrics.record_rejected()
            entry.trace.finish(
                "rejected", error="bulk queue at limit")
            raise QueueFullError(
                f"bulk queue at limit {self.bulk.max_pending}")
        entry.mark_enqueued()
        entry.trace.end("submit")
        entry.trace.begin("bulk")
        with self._cond:
            q.push(entry.bucket_len, entry)
            self._cond.notify_all()
        return entry.ticket

    def _bulk_gated(self) -> bool:
        """True while online burn rate exceeds BulkPolicy.max_burn —
        the SLO engine's own report throttles the bulk tier. The
        report is cached for check_interval_s (it walks registry
        histograms); racy reads of the cached flag are fine. Without
        an SLO engine there is no burn signal and bulk is never
        gated."""
        if self.bulk is None or self.slo is None:
            return False
        now = time.monotonic()
        if now - self._bulk_last_check < self.bulk.check_interval_s:
            return self._bulk_gated_flag
        self._bulk_last_check = now
        burn = 0.0
        try:
            report = self.slo.report()
            for cls in report.get("classes", {}).values():
                b = (cls.get("latency") or {}).get("burn_rate")
                if b is not None:
                    burn = max(burn, float(b))
        except Exception:
            burn = 0.0             # a broken report must not gate bulk
        gated = burn > self.bulk.max_burn
        if gated != self._bulk_gated_flag:
            self._bulk_gated_flag = gated
            self._g_bulk_gated.set(1 if gated else 0)
        return gated

    def _take_bulk_candidate(self, bucket_len: int,
                             batch_msa_depth: int) -> Optional[_Entry]:
        """Work-stealing admission: one bulk entry for a freed row of
        `bucket_len`'s host batch — called only after every online
        take (same-bucket and cross-bucket) came up empty, and only
        while the burn gate is open. Expired deadlines shed here, at
        take time (bulk entries never ride the online shed sweep);
        an unpinned-msa_depth head deeper than the running batch's
        compiled depth goes back to the head (same rule as online
        admission — truncating it would serve different content)."""
        q = self._bulk_queue
        if q is None or not len(q) or self._bulk_gated():
            return None
        now = time.monotonic()
        while True:
            e = q.take(bucket_len)
            if e is None:
                return None
            if e.deadline is not None and now >= e.deadline:
                self._shed_bulk(e)
                continue
            if self.config.msa_depth is None \
                    and e.request.msa is not None \
                    and int(e.request.msa.shape[0]) > batch_msa_depth:
                q.push_front(bucket_len, e)
                return None
            e.trace.end("bulk")
            e.trace.event("bulk_stolen", bucket=bucket_len)
            self._count_bulk_admits(1)
            return e

    def _form_bulk_batch(self, stopping: bool):
        """Idle founding: bulk work founds a batch ONLY when no online
        work is pending anywhere (the caller checked, under _cond) —
        and even then not while the burn gate is closed, except during
        a draining stop, where terminal resolution beats throttling."""
        q = self._bulk_queue
        if q is None or not len(q):
            return None
        if not stopping and self._bulk_gated():
            return None
        now = time.monotonic()
        for bucket_len in q.buckets():
            if self._allocator is not None and not self._allocator \
                    .can_allocate(self.mesh_policy.shape_for(bucket_len)):
                continue
            take: List[_Entry] = []
            while len(take) < self.config.max_batch_size:
                e = q.take(bucket_len)
                if e is None:
                    break
                if e.deadline is not None and now >= e.deadline:
                    self._shed_bulk(e)
                    continue
                e.trace.end("bulk")
                take.append(e)
            if take:
                self._count_bulk_admits(len(take))
                return bucket_len, take
        return None

    def _count_bulk_admits(self, n: int):
        self._n_bulk_admits += n
        self._c_bulk_admits.inc(n)

    def _shed_bulk(self, e: _Entry):
        self.metrics.record_shed()
        e.trace.event("deadline_shed")
        self._resolve_entry(e, FoldResponse(
            request_id=e.request.request_id, status="shed",
            bucket_len=e.bucket_len,
            latency_s=time.monotonic() - e.enqueued_at,
            error="deadline expired while queued (bulk)"))

    def _yield_bulk_rows(self, state, active, rows, ages,
                         all_members) -> int:
        """Checkpoint-and-yield (ISSUE 18): under online burn, spill
        every bulk row's carry to the durable store and requeue its
        entry as resumable — the freed rows go to online admission at
        this very gap. Requires the spill store: without one a yield
        would refold from zero, so bulk rows run to completion
        instead. Returns the number of rows freed."""
        store = self._ckpt_store
        if store is None or self._bulk_queue is None:
            return 0
        idx = [i for i, e in enumerate(active)
               if getattr(e.request, "qos", "online") == "bulk"]
        if not idx:
            return 0
        from alphafold2_tpu.cache.checkpoints import row_checkpoint
        from alphafold2_tpu.predict import snapshot_step_state
        try:
            snap = snapshot_step_state(state)
        except Exception:
            return 0
        yielded = []
        for i in idx:
            e = active[i]
            key = self._entry_key(e)
            if key is None:
                continue
            try:
                ck = row_checkpoint(
                    snap, rows[i], fold_key=key,
                    model_tag=self.model_tag, age=ages[i],
                    seq=e.request.seq, msa=e.request.msa)
            except ValueError:
                continue       # unspillable carry: the row keeps folding
            if store.put_row(ck) is None:
                continue
            yielded.append(i)
        if not yielded:
            return 0
        gone = set(yielded)
        requeued = [active[i] for i in yielded]
        active[:] = [e for i, e in enumerate(active) if i not in gone]
        rows[:] = [r for i, r in enumerate(rows) if i not in gone]
        ages[:] = [a for i, a in enumerate(ages) if i not in gone]
        # a yielded entry now lives in the bulk queue, not this loop:
        # it must leave the batch's failure/orphan bookkeeping too, or
        # a later batch failure would double-resolve it
        gone_ids = {id(e) for e in requeued}
        all_members[:] = [e for e in all_members
                          if id(e) not in gone_ids]
        with self._cond:
            for e in requeued:
                e.trace.event("bulk_yielded")
                e.trace.begin("bulk")
                self._bulk_queue.push_front(e.bucket_len, e)
            self._n_bulk_yields += len(requeued)
            self._c_bulk_yields.inc(len(requeued))
            self._cond.notify_all()
        return len(requeued)

    # -- preemption reclaim (ISSUE 20) -----------------------------------

    def _reclaim_fits(self, bucket_len: int, ages: List[int],
                      num_recycles: int) -> bool:
        """Can this loop's remaining recycles finish inside the grace
        window? Priced with the bucket's measured step-seconds EWMA at
        a 2x safety margin — the window must also pay for the final
        fetch, the manifest publish, and the process exit, and
        finishing 'probably' is not worth losing the spill. An unknown
        EWMA (no step measured yet) says NO: spilling loses at most
        `checkpoint_every` recycles, overrunning the window loses the
        whole fold."""
        deadline = self._reclaim_deadline
        if deadline is None:
            return False
        ewma = self._step_ewma.get(bucket_len)
        if ewma is None:
            return False
        remaining = max(num_recycles - a for a in ages)
        return remaining * ewma * 2.0 <= deadline - time.monotonic()

    def _preempt_spill_loop(self, bucket_len: int, state,
                            active: List[_Entry], rows: List[int],
                            ages: List[int],
                            all_members: List[_Entry]) -> int:
        """Grace-budgeted hand-off of one in-flight step loop: spill
        every row's carry to the durable store (where one is
        configured and the carry slices), then resolve EVERY row
        "preempted" — the ticket must never outlive the process, and
        the "preempted" terminal keeps its checkpoint so the adopting
        survivor resumes at this exact age. Unspillable rows (no
        store, unkeyable, unsliceable) still resolve "preempted":
        their callers re-fold from zero on a survivor — work lost,
        tickets never. Returns the number of rows spilled."""
        store = self._ckpt_store
        snap = None
        if store is not None and active:
            from alphafold2_tpu.cache.checkpoints import row_checkpoint
            from alphafold2_tpu.predict import snapshot_step_state
            try:
                snap = snapshot_step_state(state)
            except Exception:
                snap = None
        spilled = 0
        now = time.monotonic()
        members = list(active)
        member_rows = list(rows)
        member_ages = list(ages)
        for i, e in enumerate(members):
            wrote = False
            if snap is not None:
                key = self._entry_key(e)
                if key is not None:
                    try:
                        ck = row_checkpoint(
                            snap, member_rows[i], fold_key=key,
                            model_tag=self.model_tag,
                            age=member_ages[i],
                            seq=e.request.seq, msa=e.request.msa)
                        wrote = store.put_row(ck) is not None
                    except ValueError:
                        wrote = False
            if wrote:
                spilled += 1
            e.trace.begin("preempt")
            e.trace.event("preempt_spilled" if wrote
                          else "preempt_dropped",
                          recycle=member_ages[i])
            self.metrics.record_preempted()
            self._resolve_entry(e, FoldResponse(
                request_id=e.request.request_id, status="preempted",
                bucket_len=e.bucket_len, attempts=e.attempts,
                latency_s=now - e.enqueued_at,
                recycles=member_ages[i],
                error=("replica preempted mid-loop; checkpoint "
                       "spilled for adoption" if wrote else
                       "replica preempted mid-loop; carry not "
                       "spillable — refold on a survivor")))
        gone_ids = {id(e) for e in members}
        active[:] = []
        rows[:] = []
        ages[:] = []
        all_members[:] = [e for e in all_members
                          if id(e) not in gone_ids]
        self._n_preempt_spills += spilled
        if spilled and self._c_preempt_spills is not None:
            self._c_preempt_spills.inc(spilled)
        return spilled

    # -- fleet routing ---------------------------------------------------

    def _route(self, entry: _Entry, key: str) -> bool:
        """Compute (once) and remember the routing decision for `key`;
        True iff the plan is to forward. Routing trouble of any kind
        means 'serve locally'."""
        if self.router is None or entry.request.forwarded:
            return False
        try:
            entry.route = self.router.route(key)
        except Exception:
            return False
        return not entry.route.is_local

    def _maybe_forward(self, entry: _Entry) -> bool:
        """submit() fleet hop: True when the entry was handed to its
        consistent-hash owner (the remote ticket resolves ours via a
        done-callback). False — fold locally — when routing is off, the
        request already took its one hop, the key hashes home, or
        ANYTHING about forwarding fails: fleet state degrades to
        single-host behavior, it never degrades availability."""
        if self.router is None or entry.request.forwarded:
            return False
        key = entry.cache_key or entry.store_key
        if key is None:               # router without cache still routes
            try:
                key = self._cache_key_for(entry.request)
            except Exception:
                return False
        if entry.route is None:      # not computed by the cache fast path
            self._route(entry, key)
        decision = entry.route
        if decision is None:         # routing trouble: serve locally
            return False
        if decision.is_local:
            if decision.reason != "local_owner":
                entry.trace.event("routed", owner=decision.owner_id or "",
                                  reason=decision.reason)
            return False
        owner = decision.owner_id
        entry.trace.event("routed", owner=owner, reason=decision.reason)
        entry.trace.begin("forward")
        try:
            remote = self.router.forward(
                owner, dataclasses.replace(entry.request, forwarded=True),
                trace=entry.trace)
        except Exception:
            # owner vanished / transport error / remote backpressure:
            # local fallback (the fold is still correct, just not
            # fleet-deduplicated)
            self.router.note_fallback("forward_error")
            entry.trace.end("forward", failed=True)
            return False
        entry.trace.end("submit")
        with self._cond:
            # drain() waits on this: a forwarded ticket is in-flight
            # work this replica still owes its caller a terminal for
            self._outstanding_forwards += 1

        def _on_remote(resp: FoldResponse):
            try:
                self._handle_remote(entry, owner, resp)
            finally:
                with self._cond:
                    self._outstanding_forwards -= 1
                    self._cond.notify_all()

        remote.add_done_callback(_on_remote)
        return True

    def _handle_remote(self, entry: _Entry, owner: str,
                       resp: FoldResponse):
        """Terminal handling for one forwarded ticket: adapt the remote
        response onto the local entry — or, when the response carries
        the transport-failure marker (the owner died, partitioned, or
        restarted mid-fold; fleet.rpc.HttpTransport stamps it), FAIL
        OVER to folding locally: the work is still viable, only the
        owner is gone, and the caller must never pay for fleet
        topology with an error."""
        now = time.monotonic()
        entry.trace.end("forward", owner=owner)
        # the marker string is fleet.rpc.RPC_TRANSPORT_MARKER; spelled
        # literally here because serve must not import fleet (fleet
        # already imports serve)
        if (resp is not None and resp.status == "error" and resp.error
                and "rpc_transport" in resp.error
                and self._failover_local(entry, owner)):
            return
        try:
            local = FoldResponse(
                request_id=entry.request.request_id,
                status=resp.status,
                coords=(None if resp.coords is None
                        else np.array(resp.coords, np.float32,
                                      copy=True)),
                confidence=(None if resp.confidence is None
                            else np.array(resp.confidence, np.float32,
                                          copy=True)),
                bucket_len=(resp.bucket_len
                            if resp.bucket_len is not None
                            else entry.bucket_len),
                latency_s=now - entry.enqueued_at,
                # "forwarded", not the remote's source: THIS replica
                # did not fold it, and the trace checker's
                # fold-span-required rule keys off source == "fold"
                error=resp.error, source="forwarded",
                # the owner's retry/bisection cost travels with the
                # result (getattr: a pre-resilience peer's response
                # has no attempts field)
                attempts=getattr(resp, "attempts", 1))
        except Exception as exc:   # e.g. MemoryError on the copies
            local = FoldResponse(
                request_id=entry.request.request_id, status="error",
                bucket_len=entry.bucket_len,
                error=f"forwarded response adaptation failed: "
                      f"{exc!r}")
        try:
            # populates the local store (repeat traffic for this key
            # becomes a local hit) and settles local followers
            self._resolve_entry(entry, local)
        except Exception:
            entry.resolve(local)   # never orphan the caller's ticket

    def _failover_local(self, entry: _Entry, owner: str) -> bool:
        """Re-enqueue a transport-failed forwarded entry for a LOCAL
        fold. False when the scheduler can no longer fold (stopped) —
        the caller then resolves the transport error as terminal. The
        entry skips the submit fast paths (cache/route already ran) and
        keeps its original deadline clock: the time lost to the dead
        owner counts against the request, exactly like a retry."""
        with self._cond:
            if not self._running:
                return False
            entry.trace.event("failover_local", owner=owner)
            entry.trace.begin("queue")
            self._incoming.append(entry)
            self._depth += 1
            depth = self._depth
            self._cond.notify_all()
        self._n_failovers += 1
        self._c_failovers.inc()
        try:
            self.router.note_fallback("remote_failover")
        except Exception:
            pass
        self.metrics.record_enqueued(depth)
        return True

    def _promote_follower(self, entry: _Entry) -> bool:
        """A coalescing leader dropped out without a result (shed while
        queued, rejected at submit): crown its tightest-deadline parked
        follower as the new leader and enqueue it; the remaining
        followers stay parked behind the new leader. Returns False when
        there is nothing to promote (not a leader, no followers, or the
        scheduler is no longer running — the caller then settles the
        group with the old leader's terminal state)."""
        if entry.cache_key is None:
            return False

        def _tightest(followers: List[_Entry]) -> _Entry:
            # min absolute deadline first; deadline-free followers have
            # infinite slack and go last
            return min(followers,
                       key=lambda f: (f.deadline is None,
                                      f.deadline if f.deadline is not None
                                      else 0.0))

        with self._cond:
            if not self._running:
                return False
            # lock order _cond -> registry lock, same as the attach path
            promoted = self._inflight.promote(entry.cache_key, _tightest)
            if promoted is None:
                return False
            promoted.cache_key = entry.cache_key
            promoted.trace.event("leader_promoted",
                                 from_trace=entry.trace.trace_id)
            # a budget-admitted follower that becomes leader now
            # occupies real queue depth, not parked-budget bytes
            nbytes, promoted.parked_admit_bytes = \
                promoted.parked_admit_bytes, 0
            self._parked_admit_bytes -= nbytes
            promoted.trace.end("parked")
            promoted.trace.begin("queue")
            # parked -> queued conversion: waiting() shrank by one as
            # _depth grows by one, so the bounded-queue invariant
            # (depth + waiting <= limit) is preserved, not re-checked
            self._incoming.append(promoted)
            self._depth += 1
            depth = self._depth
            self._cond.notify_all()
        self.metrics.record_enqueued(depth)
        return True

    def _release_parked_admit(self, entry: _Entry):
        """Return a budget-admitted follower's bytes to the parked
        admission budget. Called from every path a follower leaves the
        registry (settle fan-out, own-deadline eviction, promotion);
        no-op for normally admitted entries."""
        nbytes = entry.parked_admit_bytes
        if not nbytes:
            return
        entry.parked_admit_bytes = 0
        with self._cond:
            self._parked_admit_bytes -= nbytes
            self._cond.notify_all()

    def _settle_followers(self, entry: _Entry, response: FoldResponse):
        """Fan the leader's terminal response out to its followers.
        Called from EVERY path that resolves a leader ticket, success or
        failure, so a coalesced ticket can never be left hanging."""
        if entry.cache_key is None:
            return
        followers: List[_Entry] = self._inflight.settle(entry.cache_key)
        for f in followers:
            self._release_parked_admit(f)
        if followers:
            # parked followers counted against queue_limit: their
            # release frees capacity block-mode submitters wait on
            with self._cond:
                self._cond.notify_all()
        now = time.monotonic()
        for f in followers:
            if response.status == "ok":
                try:
                    resp = FoldResponse(
                        request_id=f.request.request_id, status="ok",
                        coords=response.coords.copy(),
                        confidence=response.confidence.copy(),
                        bucket_len=response.bucket_len,
                        latency_s=now - f.enqueued_at, source="coalesced")
                except Exception as exc:  # e.g. MemoryError on the copy:
                    resp = FoldResponse(  # never orphan the remaining fan-out
                        request_id=f.request.request_id, status="error",
                        bucket_len=f.bucket_len, source="coalesced",
                        error=f"coalesced fan-out failed: {exc!r}")
                f.resolve(resp)
            else:
                f.resolve(FoldResponse(
                    request_id=f.request.request_id,
                    status=response.status, bucket_len=f.bucket_len,
                    latency_s=now - f.enqueued_at, source="coalesced",
                    error=f"coalesced onto leader "
                          f"{response.request_id}: "
                          f"{response.error or response.status}"))

    def _resolve_entry(self, entry: _Entry, response: FoldResponse):
        """Terminal state for one queued entry: populate the store (ok
        only, BEFORE followers settle so late duplicates hit the cache),
        resolve the leader ticket, fan out to followers — except a SHED
        leader, whose surviving followers get a promoted leader instead
        of inheriting the shed (the group's work is still viable; only
        this request's deadline died)."""
        put_key = entry.cache_key or entry.store_key
        if response.status == "ok" and self.cache is not None \
                and put_key is not None:
            with entry.trace.span("writeback"):
                try:
                    self.cache.put(put_key, response.coords,
                                   response.confidence)
                except Exception:
                    pass              # a full/broken store never blocks
        entry.resolve(response)
        # a terminal state means the spilled checkpoint must not
        # outlive the work (ISSUE 18): resumable survivors exist only
        # for folds some ticket still waits on. Requeue/bisection/
        # resume paths never come through here, so their checkpoints
        # survive for the retry to consume. "preempted" is the one
        # terminal that KEEPS its checkpoint (ISSUE 20): the fold is
        # not done, it is migrating — the orphan manifest hands it to
        # an adopting survivor that resumes from exactly these bytes.
        if self._ckpt_store is not None \
                and response.status != "preempted":
            key = self._entry_key(entry)
            if key is not None:
                try:
                    self._ckpt_store.discard(key)
                except Exception:
                    pass
        if response.status == "shed" and self._promote_follower(entry):
            return
        self._settle_followers(entry, response)

    def serve_stats(self) -> dict:
        """Health-check snapshot: serving counters + executor cache +
        result-cache section ("cache": submit-side counters always;
        "store"/"inflight" sub-views only when a cache is attached)."""
        stats = self.metrics.snapshot()
        stats["executor"] = self.executor.stats()
        stats["bucket_edges"] = list(self.buckets.edges)
        # slowest completed request traces (empty without a tracer)
        stats["traces"] = self.tracer.slowest()
        if self.cache is not None:
            stats["cache"]["store"] = self.cache.snapshot()
            stats["cache"]["inflight"] = self._inflight.snapshot()
            stats["cache"]["parked_admits"] = self._n_parked_admits
            with self._cond:
                stats["cache"]["parked_admit_bytes"] = \
                    self._parked_admit_bytes
        if self.router is not None:
            stats["router"] = self.router.snapshot()
        if self.retry is not None:
            stats["resilience"] = {
                "retries": self._n_retries,
                "bisections": self._n_bisections,
                "watchdog_fires": self._n_watchdog_fires,
                "executor_rebuilds": self._n_rebuilds,
                "nonfinite_outputs": self._n_nonfinite,
                "quarantine": self._quarantine.snapshot(),
                "breaker": (None if self._breaker is None
                            else self._breaker.snapshot()),
                "watchdog_s": self.retry.watchdog_s,
                "max_attempts": self.retry.max_attempts,
            }
            # ISSUE-14 keys appear only when a step-loop fault-domain
            # knob is on: `retry=` without them keeps the PR-5
            # resilience section byte-identical
            if getattr(self.retry, "checkpoint_every", 0) \
                    or getattr(self.retry, "row_isolation", False):
                stats["resilience"].update({
                    "checkpoint_every":
                        getattr(self.retry, "checkpoint_every", 0),
                    "row_isolation":
                        bool(getattr(self.retry, "row_isolation",
                                     False)),
                    "checkpoints": self._n_checkpoints,
                    "checkpoint_resumes": self._n_ckpt_resumes,
                    "recycles_lost": self._n_recycles_lost,
                    "row_poison_isolations": self._n_row_isolations,
                })
            # durable spill (ISSUE 18): keys appear only when the
            # checkpoint_spill knob names a directory — same identity
            # discipline as the ISSUE-14 block above
            if self._ckpt_store is not None:
                stats["resilience"]["checkpoint_spill"] = dict(
                    self._ckpt_store.snapshot(),
                    spill_resumes=self._n_spill_resumes,
                    survivors_at_boot=self._boot_survivors)
        if self.bulk is not None:
            stats["bulk"] = {
                "pending": len(self._bulk_queue),
                "admits": self._n_bulk_admits,
                "yields": self._n_bulk_yields,
                "rejected": self._n_bulk_rejected,
                "gated": self._bulk_gated_flag,
                "max_burn": self.bulk.max_burn,
            }
        if self.cascade is not None:
            decided = self._n_draft_accepted + self._n_escalated
            stats["cascade"] = {
                "draft_tag": getattr(self.cascade.draft, "model_tag",
                                     ""),
                "draft_accepted": self._n_draft_accepted,
                "escalated": self._n_escalated,
                "draft_errors": self._n_draft_errors,
                "cross_tier_hits": self._n_cross_tier_hits,
                "accept_rate": (self._n_draft_accepted / decided
                                if decided else 0.0),
                "mean_confidence": (self._confidence_sum
                                    / self._confidence_n
                                    if self._confidence_n else None),
                "accept_plddt": self.cascade.gate.accept_plddt,
                "max_entropy": self.cascade.gate.max_entropy,
            }
            draft_stats = getattr(self.cascade.draft, "serve_stats",
                                  None)
            if draft_stats is not None:
                try:
                    d = draft_stats()
                    stats["cascade"]["draft"] = {
                        "served": d.get("served", 0),
                        "errors": d.get("errors", 0),
                        "shed": d.get("shed", 0),
                        "queue_depth": d.get("queue_depth", 0),
                        "batches": d.get("batches", 0),
                    }
                except Exception:
                    pass       # obs must never fail stats
        # express section only once express traffic minted its metrics
        # (keeps the no-express snapshot byte-identical)
        if self._c_express is not None:
            stats["express"] = dict(self._express_counts)
        if self.mesh_policy is not None:
            with self._cond:
                folds = {label: {"batches": self._mesh_batches[label],
                                 "served": self._mesh_served.get(label, 0)}
                         for label in sorted(self._mesh_batches)}
                inflight = self._inflight_execs
            stats["mesh"] = dict(self.mesh_policy.snapshot(),
                                 allocator=self._allocator.snapshot(),
                                 inflight_batches=inflight,
                                 folds=folds)
        if self.recycle_policy is not None:
            row_steps = self._row_steps_total
            stats["recycle"] = dict(
                self.recycle_policy.snapshot(),
                step_mode=self._use_step_loop(),
                recycles_executed=self._n_recycles_exec,
                recycles_skipped=self._n_recycles_skipped,
                preemptions=self._n_preemptions,
                preempt_hbm_refusals=self._n_preempt_hbm_refusals,
                retired_early=self._n_retired_early,
                # row-level occupancy over every executed step: the
                # number continuous batching exists to drive to 1.0
                # (identical keys with continuous off, so the loadtest
                # baseline comparison reads the same stat)
                row_admissions=self._n_row_admissions,
                rows_dead_steps=self._n_rows_dead_steps,
                rows_occupied_fraction=(
                    self._row_steps_live / row_steps if row_steps
                    else 0.0),
                # cross-bucket admission (ISSUE 13; zero/off keys kept
                # when the feature is off so baselines compare)
                cross_bucket_admissions=self._n_cross_admissions,
                cross_bucket_refusals=self._n_cross_refusals)
        if self.kernel_policy is not None:
            with self._cond:
                folds = {f"{kind}:{bucket}":
                         {"batches": self._kernel_batches.get(
                             (kind, bucket), 0),
                          "served": served}
                         for (kind, bucket), served
                         in sorted(self._kernel_served.items())}
            stats["kernel"] = dict(self.kernel_policy.snapshot(),
                                   folds=folds)
        if self.feature_pool is not None:
            stats["featurize"] = self.feature_pool.snapshot()
        if self.key_log is not None:
            stats["key_log"] = self.key_log.snapshot()
        if self.slo is not None:
            # report() also refreshes the slo_* gauges, so a stats
            # poll and a Prometheus scrape read the same window
            try:
                stats["slo"] = self.slo.report()
            except Exception as exc:      # obs must never fail stats
                stats["slo"] = {"error": repr(exc)}
        with self._cond:
            stats["running"] = self._running
            stats["draining"] = self._draining
            stats["outstanding_forwards"] = self._outstanding_forwards
        stats["failovers"] = self._n_failovers
        stats["drains"] = self._n_drains
        if self._n_preempt_notices:
            # preemption reclaim (ISSUE 20): key absent until a notice
            # lands, so scrubbed stats stay identical with the feature
            # unexercised
            with self._cond:
                deadline = self._reclaim_deadline
                stats["preemption"] = {
                    "reclaiming": self._reclaiming,
                    "source": self._reclaim_source,
                    "notices": self._n_preempt_notices,
                    "drain_spills": self._n_preempt_spills,
                    "grace_remaining_s": (
                        max(0.0, deadline - time.monotonic())
                        if deadline is not None else 0.0),
                }
        return stats

    # -- worker ----------------------------------------------------------

    def _run(self):
        try:
            self._run_inner()
        except Exception as exc:   # worker must never die silently:
            self._fail_outstanding(repr(exc))
            return
        if not self._drain:
            self._cancel_remaining()

    def _run_inner(self):
        poll_s = self.config.poll_ms / 1000.0
        just_executed = False   # a ready batch may already be waiting
        while True:
            with self._cond:
                if not just_executed and not self._incoming \
                        and self._running:
                    # timed wait only while entries pend (max_wait_ms /
                    # deadline bookkeeping needs the clock); a fully
                    # idle scheduler parks until submit()/stop() notify.
                    # Pending BULK work also forces the timed wait: the
                    # burn gate reopens on its own (no notify), so a
                    # parked worker would never found the gated backlog
                    if any(self._pending.values()) \
                            or (self._bulk_queue is not None
                                and len(self._bulk_queue)):
                        self._cond.wait(timeout=poll_s)
                    else:
                        self._cond.wait()
                while self._incoming:
                    entry = self._incoming.popleft()
                    self._pending.setdefault(entry.bucket_len,
                                             []).append(entry)
                if self.recycle_policy is not None \
                        and self.recycle_policy.preempt \
                        and self._allocator is not None:
                    # the ONLY reader is the leased preemption path,
                    # so the scan is skipped entirely when no pool
                    # thread could ever consult it. Eligibility is
                    # _urgent_eligible — the same predicate the
                    # preemption take uses, so the worker never
                    # advertises a deadline the take would refuse.
                    # The tightest entry's slice size rides along so
                    # a leased loop can tell whether yielding even
                    # COULD place it.
                    now_p = time.monotonic()
                    tightest, t_bucket, t_entry = None, None, None
                    for b_len, pend in self._pending.items():
                        for e in pend:
                            if not self._urgent_eligible(e, now_p):
                                continue
                            if tightest is None or e.deadline < tightest:
                                tightest, t_bucket, t_entry = \
                                    e.deadline, b_len, e
                    self._pending_tightest = tightest
                    self._pending_tightest_bucket = (
                        None if tightest is None else t_bucket)
                    # the entry's OWN MSA depth rides along: with an
                    # unpinned config (msa_depth=None) the HBM pricing
                    # of a preemption yield must cover what this batch
                    # will actually carry, not a zero-depth lowball
                    self._pending_tightest_msa = (
                        None if t_entry is None
                        or t_entry.request.msa is None
                        else int(t_entry.request.msa.shape[0]))
                    self._pending_tightest_chips = (
                        None if tightest is None
                        or self.mesh_policy is None
                        else chips_of(
                            self.mesh_policy.shape_for(t_bucket)))
                stopping = not self._running
                drain = self._drain
            if stopping and not drain:
                break
            self._shed_expired()
            batch = self._form_batch(stopping)
            just_executed = batch is not None
            if batch is not None:
                self._dispatch(*batch)
                continue
            if stopping:
                with self._cond:
                    if self._incoming or any(self._pending.values()) \
                            or (self._bulk_queue is not None
                                and len(self._bulk_queue)):
                        if self._allocator is not None:
                            # every eligible slice is busy: wait for a
                            # completion to free one, don't hot-spin
                            self._cond.wait(timeout=poll_s)
                        continue
                    if self._inflight_execs > 0:
                        # mesh batches still running on the dispatch
                        # pool: a drained stop means every ticket
                        # resolved, so wait them out (they may also
                        # requeue retries — re-check from the top)
                        self._cond.wait(timeout=poll_s)
                        continue
                break

    def _resolve_removed(self, entries: List[_Entry]):
        """Entries left the queue: update depth, wake blocked submitters."""
        if not entries:
            return
        with self._cond:
            self._depth -= len(entries)
            self._cond.notify_all()

    def _shed_expired(self):
        now = time.monotonic()
        shed: List[_Entry] = []
        # under _cond: continuous row admission takes from _pending on
        # dispatch-pool threads (ISSUE 11), so every _pending mutation
        # is lock-guarded now (the Condition's RLock nests fine)
        with self._cond:
            for bucket_len, entries in self._pending.items():
                keep = []
                for e in entries:
                    if e.deadline is not None and now > e.deadline:
                        shed.append(e)
                    else:
                        keep.append(e)
                self._pending[bucket_len] = keep
        self._resolve_removed(shed)
        for e in shed:
            self.metrics.record_shed()
            self._resolve_entry(e, FoldResponse(
                request_id=e.request.request_id, status="shed",
                bucket_len=e.bucket_len,
                latency_s=now - e.enqueued_at,
                attempts=e.attempts or 1,   # deadline may die mid-backoff
                error="deadline expired before folding"))
        self._shed_expired_followers(now)

    def _shed_expired_followers(self, now: float):
        """Enforce parked followers' OWN deadlines: a coalesced follower
        whose deadline passes while waiting on its leader is shed with
        its own terminal state instead of inheriting the leader's
        timing. The leader keeps folding — only the waiter gives up."""
        if self.cache is None:
            return
        expired = self._inflight.evict_followers(
            lambda f: f.deadline is not None and now > f.deadline)
        if not expired:
            return
        for f in expired:
            self._release_parked_admit(f)
        with self._cond:
            self._cond.notify_all()   # waiting() shrank: wake blocked
        for f in expired:             # submitters before resolving
            self.metrics.record_shed()
            self._c_follower_deadline.inc()
            f.trace.event("follower_deadline_exceeded")
            f.resolve(FoldResponse(
                request_id=f.request.request_id, status="shed",
                bucket_len=f.bucket_len,
                latency_s=now - f.enqueued_at, source="coalesced",
                error="follower deadline expired while parked on an "
                      "in-flight leader (follower_deadline_exceeded)"))

    def _form_batch(self, stopping: bool):
        """Pick the bucket whose oldest entry has waited longest, if any
        bucket is ready (full batch, max_wait exceeded, or draining).
        With a retry policy: backoff-gated entries are not ready yet,
        bisection isolation groups batch only with each other, and an
        open circuit breaker pauses execution entirely (drain on stop
        still executes — a stopping scheduler owes every ticket a
        terminal state and retries are disabled while stopping)."""
        cfg = self.config
        now = time.monotonic()
        if self._reclaiming:
            # reclaim mode (ISSUE 20): a preempted process must never
            # FOUND a batch — work it starts now it cannot finish, and
            # queued entries resolve "preempted" at stop so their
            # callers re-fold on a survivor instead
            return None
        if not stopping and self._breaker is not None \
                and not self._breaker.allow_execute():
            return None
        best = None                      # (oldest, bucket_len, take)
        # under _cond: continuous row admission (pool threads) also
        # takes from _pending, so candidate selection + removal must be
        # one atomic step against it
        with self._cond:
            for bucket_len, entries in self._pending.items():
                if not entries:
                    continue
                # mesh: a bucket whose slice shape has no free devices
                # is not ready — forming its batch would just park it;
                # other buckets' slices may be free right now
                if self._allocator is not None and not \
                        self._allocator.can_allocate(
                            self.mesh_policy.shape_for(bucket_len)):
                    continue
                cand = self._bucket_candidate(entries, stopping, now)
                if cand is not None and (best is None
                                         or cand[0] < best[0]):
                    best = (cand[0], bucket_len, cand[1])
            if best is None:
                # bulk founding (ISSUE 18) is legal only when NO online
                # work is pending anywhere — checked under the same
                # lock that admits online work, so a racing submit
                # either lands before this check (and wins the batch)
                # or after (and waits exactly one bulk loop, the same
                # as any work behind a running batch)
                online_idle = (self._bulk_queue is not None
                               and not self._incoming
                               and not any(self._pending.values()))
            else:
                # selection + removal stay ONE atomic step against
                # pool-thread row admission takes
                _, bucket_len, take = best
                taken = {id(e) for e in take}
                self._pending[bucket_len] = [
                    e for e in self._pending[bucket_len]
                    if id(e) not in taken]
        if best is None:
            if online_idle:
                return self._form_bulk_batch(stopping)
            return None
        if self._breaker is not None:
            self._breaker.begin_probe()  # no-op unless half-open
        self._resolve_removed(take)
        return bucket_len, take

    def _bucket_candidate(self, entries: List[_Entry], stopping: bool,
                          now: float) -> Optional[Tuple[float,
                                                        List[_Entry]]]:
        """One bucket's best executable batch as (oldest_enqueued_at,
        entries), or None when nothing is ready."""
        if self.retry is None:
            return self._ready_take(entries, stopping, now)
        # retry-aware: backoff gates eligibility (ignored while
        # stopping — drain must terminate), isolation groups jump the
        # normal ready rules (their members already waited a full
        # batch's worth; re-bisection only ever shrinks them)
        eligible = entries if stopping else \
            [e for e in entries if e.not_before <= now]
        if not eligible:
            return None
        normal: List[_Entry] = []
        group_best = None
        groups: Dict[int, List[_Entry]] = {}
        for e in eligible:
            if e.group is None:
                normal.append(e)
            else:
                groups.setdefault(e.group, []).append(e)
        for members in groups.values():
            oldest = min(e.enqueued_at for e in members)
            if group_best is None or oldest < group_best[0]:
                group_best = (oldest, members)
        if group_best is not None:
            return group_best
        # normal is non-empty here: eligible was non-empty and every
        # grouped entry returned through group_best above
        return self._ready_take(normal, stopping, now)

    def _ready_take(self, entries: List[_Entry], stopping: bool,
                    now: float) -> Optional[Tuple[float, List[_Entry]]]:
        """max_batch/max_wait readiness over one non-empty entry list:
        (oldest_enqueued_at, take) or None when not ready yet. The one
        copy of the ready rule, shared by the retry-off and retry-on
        batching paths so they cannot drift."""
        cfg = self.config
        oldest = min(e.enqueued_at for e in entries)
        # eager formation (ISSUE 13): with mid-loop admission available
        # to top an under-filled batch up, any entry at all makes the
        # bucket ready — max_wait becomes a fallback, not a floor
        ready = (len(entries) >= cfg.max_batch_size
                 or (now - oldest) * 1000.0 >= cfg.max_wait_ms
                 or stopping
                 or self._eager_form_on())
        if not ready:
            return None
        # higher priority folds first; FIFO within a priority level
        take = sorted(entries, key=lambda e: (-e.request.priority,
                                              e.enqueued_at))
        return oldest, take[:cfg.max_batch_size]

    def _dispatch(self, bucket_len: int, entries: List[_Entry]):
        """Run one formed batch: inline (the classic single-chip path,
        byte-for-byte the old behavior) or, with a mesh policy, on a
        leased device slice via the dispatch pool — so batches holding
        DISJOINT slices execute concurrently and short traffic never
        queues behind a flagship fold."""
        if self._allocator is None:
            self._execute(bucket_len, entries)
            return
        lease = self._allocator.acquire(
            self.mesh_policy.shape_for(bucket_len))
        if lease is None:
            # _form_batch checked availability and the worker is the
            # only acquirer, so this is unreachable in practice — but a
            # policy/allocator bug must degrade to a serial fold on the
            # default device, never lose the batch
            self._execute(bucket_len, entries)
            return
        # EVERYTHING between acquire and the pool handoff is guarded
        # (ISSUE 14 audit): an exception from the gauge or the inflight
        # bookkeeping would otherwise strand the slice forever — the
        # lease must be released on every path that fails to hand it to
        # _execute_on_lease's try/finally
        counted = False
        try:
            self._set_busy_gauge()
            with self._cond:
                self._inflight_execs += 1
            counted = True
            self._mesh_pool.submit(self._execute_on_lease, bucket_len,
                                   entries, lease)
        except BaseException:
            # pool unavailable (shutdown race) or bookkeeping trouble:
            # fall back inline
            self._release_lease(lease)
            if counted:
                with self._cond:
                    self._inflight_execs -= 1
                    self._cond.notify_all()
            self._execute(bucket_len, entries)

    def _execute_on_lease(self, bucket_len: int, entries: List[_Entry],
                          lease: SliceLease):
        try:
            self._execute(bucket_len, entries, lease=lease)
        finally:
            self._release_lease(lease)
            with self._cond:
                self._inflight_execs -= 1
                self._cond.notify_all()

    def _release_lease(self, lease: SliceLease):
        self._allocator.release(lease)
        self._set_busy_gauge()

    def _set_busy_gauge(self):
        with self._gauge_lock:
            self._g_mesh_busy.set(self._allocator.busy_devices)

    def _execute(self, bucket_len: int, entries: List[_Entry],
                 lease: Optional[SliceLease] = None):
        if self._use_step_loop():
            self._execute_recycle(bucket_len, entries, lease)
            return
        cfg = self.config
        t0 = time.monotonic()
        if self.tracer.enabled:
            for e in entries:
                e.trace.end("queue", bucket_len=bucket_len)
                e.trace.end("retry")   # closes a retry-wait span; no-op
            #                            on a first execution
            # batch-level spans (assemble / compile / fold) are measured
            # once and fanned out to every member's trace
            batch_trace = MultiTrace([e.trace for e in entries])
        else:
            batch_trace = NULL_TRACE
        for e in entries:
            e.attempts += 1
        # the whole assemble -> run -> device-fetch window is guarded:
        # entries already left the queue, so an unresolved exception here
        # would orphan their tickets forever (resolve as error instead)
        try:
            with batch_trace.span("batch_form", bucket_len=bucket_len,
                                  n_real=len(entries)):
                batch, waste = self.buckets.assemble(
                    [e.request for e in entries], bucket_len,
                    cfg.max_batch_size, msa_depth=cfg.msa_depth)
            kspec = self._kernel_spec_for(bucket_len)
            result = self._run_executor(batch, batch_trace, lease,
                                        kernel=kspec)
            coords = np.asarray(result.coords)
            confidence = np.asarray(result.confidence)
            distogram = None
            if cfg.confidence_summary:
                dg = getattr(result, "distogram", None)
                if dg is not None:
                    distogram = np.asarray(dg)
        except Exception as exc:  # resolve/retry, never kill the worker
            if self._handle_batch_failure(bucket_len, entries, exc, t0):
                return            # retried, bisected, or quarantined
            self.metrics.record_error(len(entries))
            for e in entries:
                self._resolve_entry(e, FoldResponse(
                    request_id=e.request.request_id, status="error",
                    bucket_len=bucket_len, error=repr(exc),
                    attempts=e.attempts))
            return
        # output validation (retry-enabled only): non-finite coords/
        # confidence never leave as "ok" — they count toward poison
        # detection for this entry's key
        finite_ok = None
        if self.retry is not None:
            finite_ok = [bool(np.isfinite(coords[i, :e.request.length])
                              .all()
                              and np.isfinite(
                                  confidence[i, :e.request.length]).all())
                         for i, e in enumerate(entries)]
        if self._breaker is not None:
            # a batch with non-finite rows is device-suspect the same
            # way a transient failure is: a systemic NaN episode must
            # OPEN the breaker, not keep resetting it batch by batch
            (self._breaker.record_success
             if finite_ok is None or all(finite_ok)
             else self._breaker.record_failure)()
        now = time.monotonic()
        real_tokens = 0
        try:
            for i, e in enumerate(entries):
                n = e.request.length
                real_tokens += n
                if finite_ok is not None and not finite_ok[i]:
                    self._resolve_nonfinite(e, bucket_len)
                    continue
                latency = now - e.enqueued_at
                self.metrics.record_served(bucket_len, latency)
                ent = None
                if distogram is not None:
                    try:
                        ent = _distogram_entropy(distogram[i, :n, :n])
                    except Exception:
                        ent = None  # a summary must never fail a serve
                self._resolve_entry(e, FoldResponse(
                    request_id=e.request.request_id, status="ok",
                    # copy: a view would pin the whole padded batch in
                    # the caller's hands for the lifetime of the response
                    coords=coords[i, :n].copy(),
                    confidence=confidence[i, :n].copy(),
                    bucket_len=bucket_len, latency_s=latency,
                    attempts=e.attempts, distogram_entropy=ent))
        except Exception as exc:
            # resolution machinery failed mid-batch (e.g. MemoryError on
            # a response copy): entries already left the queue, so
            # anything still unresolved must be error-resolved HERE or
            # its caller blocks forever — then keep serving
            for e in entries:
                if not e.ticket.done():
                    self.metrics.record_error()
                    try:
                        self._resolve_entry(e, FoldResponse(
                            request_id=e.request.request_id,
                            status="error", bucket_len=bucket_len,
                            error=f"post-fold resolution failed: "
                                  f"{exc!r}"))
                    except Exception:
                        e.resolve(FoldResponse(
                            request_id=e.request.request_id,
                            status="error", bucket_len=bucket_len,
                            error=f"post-fold resolution failed: "
                                  f"{exc!r}"))
            return
        if lease is not None:
            self._c_mesh_folds.inc(mesh=lease.label)
        self._record_kernel_batch(bucket_len, kspec, len(entries))
        with self._cond:
            if lease is not None:
                self._mesh_batches[lease.label] = \
                    self._mesh_batches.get(lease.label, 0) + 1
                self._mesh_served[lease.label] = \
                    self._mesh_served.get(lease.label, 0) + len(entries)
            depth = self._depth
        try:
            self.metrics.record_batch(
                bucket_len, cfg.max_batch_size, len(entries), real_tokens,
                waste, now - t0, depth,
                cache_store=(None if self.cache is None
                             else self.cache.snapshot()))
        except Exception:
            # last-resort worker protection (sink I/O failures are
            # already absorbed inside ServeMetrics.record_batch; this
            # additionally survives a misbehaving metrics subclass —
            # observability must never take down serving)
            pass

    # -- step-mode recycle loop (ISSUE 9) --------------------------------

    def _execute_recycle(self, bucket_len: int, entries: List[_Entry],
                         lease: Optional[SliceLease] = None):
        """Run one formed batch with the SCHEDULER owning the recycle
        loop: embed+first-pass executable, then one single-recycle step
        executable per iteration. Between steps: converged elements
        retire early (their tickets resolve NOW; on single-device
        carries the survivor batch is re-packed to a dense row prefix,
        on multi-chip leases rows retire in place via the position->row
        map; a fully-converged batch skips its remaining recycles),
        tighter-deadline pending work preempts the gap, and progressive
        results stream to tickets.
        With converge_tol=0 every element runs all `num_recycles` steps
        and — because the step program IS the scan body — the served
        numerics are identical to the opaque `lax.scan` path.

        CONTINUOUS BATCHING (`RecyclePolicy(continuous=True)`,
        ISSUE 11): each position carries its own recycle index (`ages`),
        retirement is always in place (the position->row map frees
        physical rows instead of re-packing), and between steps freed
        rows are REFILLED with pending same-bucket requests via the
        row-masked init program (`_admit_rows` ->
        `FoldExecutor.run_init_rows`): survivors keep stepping from
        their own depth while admitted rows restart at iteration 0, so
        a saturated bucket's slice never idles a row. Convergence,
        min_recycles, full-depth retirement, progressive streaming and
        `FoldResponse.recycles` are all evaluated against each row's
        OWN age — an admitted row is never compared against a
        pre-admission prev-state (the post-admission fetch refreshes
        the prev snapshot for exactly this reason)."""
        cfg = self.config
        policy = self.recycle_policy
        continuous = self._use_continuous()
        t0 = time.monotonic()
        if self.tracer.enabled:
            for e in entries:
                e.trace.end("queue", bucket_len=bucket_len)
                e.trace.end("retry")   # closes a retry-wait span; no-op
        for e in entries:              # on a first execution
            e.attempts += 1
        devices = lease.devices if lease is not None else None
        mesh_shape = lease.shape if lease is not None else None
        num_recycles = cfg.num_recycles
        active = list(entries)         # still folding, position-ordered
        all_members = list(entries)    # + row admissions (ISSUE 11):
        #   the exception handler and batch accounting must cover every
        #   entry that ever rode this loop, not just the founders
        rows = list(range(len(entries)))   # position -> batch row
        ages = [0] * len(entries)          # position -> OWN recycle idx
        # physical repacking gathers the carried state on the batch
        # axis; on a MULTI-chip lease that is an eager op over a
        # mesh-sharded O(L^2) carry outside the step executable's
        # sharding discipline — retire rows logically there instead
        # (the rows map above) and compact only where the carry lives
        # on a single device. The continuous batcher never repacks:
        # freed physical rows are exactly where admissions land.
        can_repack = (devices is None or len(devices) == 1) \
            and not continuous
        any_nonfinite = False
        r = 0                          # loop-level step count
        # step-loop fault domains (ISSUE 14): carry checkpointing +
        # per-row poison isolation, both off unless the RetryPolicy
        # asked — with the knobs off every local below is inert and
        # the loop is byte-for-byte the PR-13 behavior
        retry = self.retry
        ckpt_every = 0 if retry is None \
            else int(getattr(retry, "checkpoint_every", 0) or 0)
        row_isolate = retry is not None \
            and getattr(retry, "row_isolation", False)
        ckpt = None                    # last _StepCheckpoint
        resumes = 0                    # checkpoint resumes this loop
        resume_probe = False           # next successful step is the
        #                                breaker's half-open probe
        t_attempt = t0                 # start of the executor call a
        #                                watchdog span would cover
        # entries already left the queue: any unresolved exception here
        # would orphan tickets — same guard discipline as _execute
        try:
            batch_trace = (MultiTrace([e.trace for e in active])
                           if self.tracer.enabled else NULL_TRACE)
            with batch_trace.span("batch_form", bucket_len=bucket_len,
                                  n_real=len(entries)):
                batch, waste = self.buckets.assemble(
                    [e.request for e in entries], bucket_len,
                    cfg.max_batch_size, msa_depth=cfg.msa_depth)
            # kernel routing (ISSUE 12): the init pass always runs the
            # bucket's STATIC first-pass spec (warmup pre-compiled it);
            # step_kernel is what the remaining recycles run — the
            # contact-prior flow below may re-plan it per target
            kspec = self._kernel_spec_for(bucket_len)
            init_kw = {} if kspec is None else {"kernel": kspec}
            step_kernel = kspec
            contact_planned = False
            state = None
            while active:
                try:
                    t_attempt = time.monotonic()
                    state = self._run_step_guarded(
                        lambda: self.executor.run_init(
                            batch, trace=batch_trace, devices=devices,
                            mesh_shape=mesh_shape, **init_kw))
                    break
                except Exception as exc:
                    # per-row poison isolation at the FIRST pass: a
                    # row-attributed deterministic failure retires only
                    # the offending founders; the scrubbed batch
                    # re-inits the innocents (bisection stays the
                    # fallback for unattributed failures)
                    scrubbed = self._isolate_poison_rows(
                        exc, batch, active, rows, ages)
                    if scrubbed is None:
                        raise
                    batch = scrubbed
            if state is None:
                # every founder was isolated poison: nothing to fold
                self._finish_step_batch(bucket_len, entries,
                                        all_members, lease, kspec,
                                        contact_planned, any_nonfinite,
                                        waste, t0)
                return
            # durable resume (ISSUE 18): a founder whose fold died
            # with a spilled checkpoint (this process's previous life,
            # or a dead peer reached through the store's backend/peer
            # tiers) restarts at its checkpointed age — its row's
            # just-initialized carry is overwritten with the spilled
            # one, which is exactly PR 14's restore path per row
            if self._ckpt_store is not None and active:
                state = self._resume_from_spill(
                    state, active, rows, ages, range(len(active)))

            def _plan_contact(st, members):
                """Re-plan the step mask from the batch's OWN pair
                activations (the recycle-1 distogram st carries): the
                remaining recycles run a re-lowered step executable
                under the planned pattern — or DENSE when the plan
                degenerates to nearly-all-live. Planning trouble keeps
                the static mask (an observability loss, never a
                serving one). Per-row REAL lengths ride along (via the
                live position->row map) so dead rows — and the padding
                region of a shorter admitted fold (ISSUE 13) — plan as
                dead blocks, never as garbage-live ones."""
                try:
                    row_lengths = [0] * cfg.max_batch_size
                    for pos in range(len(active)):
                        row_lengths[rows[pos]] = \
                            active[pos].request.length
                    planned = self.kernel_policy.contact_spec_for(
                        bucket_len, np.asarray(st.distogram),
                        lengths=row_lengths)
                except Exception:
                    return kspec, False
                self._c_kernel_replans.inc()
                for e in members:
                    e.trace.event(
                        "kernel_contact_replan",
                        kernel=("dense" if planned is None
                                else planned.label),
                        live_frac=(1.0 if planned is None
                                   else round(planned.live_fraction,
                                              4)))
                return planned, True

            if self.kernel_policy is not None \
                    and self.kernel_policy.contact_priors \
                    and kspec is not None:
                step_kernel, contact_planned = _plan_contact(state,
                                                             active)
            # the per-step device-to-host fetch exists for convergence
            # deltas and streaming (and the per-step non-finite scan of
            # row isolation); a preemption-only policy needs none of
            # them, so it pays one fetch at the end like the opaque
            # path instead of copying the padded batch every recycle
            fetch_steps = policy.converge_tol > 0 or policy.stream \
                or row_isolate
            coords_np = conf_np = None
            if fetch_steps:
                coords_np = np.asarray(state.coords)
                conf_np = np.asarray(state.confidence)
                if row_isolate and self._scan_nonfinite_rows(
                        active, rows, ages, coords_np, conf_np):
                    any_nonfinite = True
                self._stream_progress(active, rows, coords_np, conf_np,
                                      ages)
            if ckpt_every and active:
                # checkpoint 0: a failure at the very first step already
                # resumes at the init state instead of requeueing
                ckpt = self._checkpoint_loop(state, batch, active, rows,
                                             ages, 0, step_kernel)
            # every surviving row has age < num_recycles (full-depth
            # rows retire inside the loop), so the condition only
            # gates entry: num_recycles == 0 skips straight to the
            # final retirement below, exactly like the opaque path.
            # The loop runs inside a FAULT ENVELOPE (ISSUE 14): a
            # row-attributed deterministic failure retires ONLY the
            # offending rows and retries the step; a transient
            # failure or watchdog fire resumes the survivors from the
            # last checkpoint at their checkpointed ages; anything
            # else falls through to the classic outer handler
            # (requeue-to-zero / bisection / error)
            step_done = True       # no step attempt pending yet: a
            #                        failure now lost no step progress
            while True:
                try:
                    while active and min(ages) < num_recycles:
                        if policy.preempt:
                            lease = self._maybe_preempt(active, lease,
                                                        r, bucket_len)
                        r += 1
                        step_done = False
                        prev_coords, prev_conf = coords_np, conf_np
                        step_trace = (
                            MultiTrace([e.trace for e in active])
                            if self.tracer.enabled else NULL_TRACE)
                        step_kw = dict(trace=step_trace,
                                       devices=devices,
                                       mesh_shape=mesh_shape)
                        if continuous:
                            # per-step occupancy rides the recycle span
                            # so the obs_report occupancy line can read
                            # it back (the kwarg only exists on
                            # row-admission-capable executors, which
                            # _use_continuous vetted)
                            step_kw["span_attrs"] = {
                                "rows_live": len(active),
                                "rows_total": cfg.max_batch_size}
                        if step_kernel is not None:
                            step_kw["kernel"] = step_kernel
                        t_step = time.monotonic()
                        t_attempt = t_step
                        state = self._run_step_guarded(
                            lambda st=state, rr=r, kw=step_kw:
                            self.executor.run_step(batch, st, rr, **kw))
                        step_done = True   # a failure from here on
                        #   (admission, planning) lost no step: the
                        #   recycles_lost ledger must count r, not r-1
                        if resume_probe:
                            # the resumed loop's first successful step
                            # IS the breaker's half-open probe: the
                            # device just proved it can execute again
                            resume_probe = False
                            if self._breaker is not None:
                                self._breaker.record_success()
                        # per-bucket step-seconds EWMA: what the
                        # cross-bucket AdmissionPricer converts loop
                        # extension into wall time with (and the
                        # native-delay projection's loop-drain term)
                        dt_step = time.monotonic() - t_step
                        prev_s = self._step_ewma.get(bucket_len)
                        self._step_ewma[bucket_len] = \
                            dt_step if prev_s is None \
                            else 0.5 * prev_s + 0.5 * dt_step
                        ages = [a + 1 for a in ages]
                        self._n_recycles_exec += 1
                        self._c_recycles.inc()
                        # row-occupancy ledger, sampled per executed
                        # step: a step costs the same whether a row is
                        # live or dead, which is exactly the waste
                        # continuous admission exists to eliminate
                        live = len(active)
                        self._row_steps_live += live
                        self._row_steps_total += cfg.max_batch_size
                        dead = cfg.max_batch_size - live
                        if dead > 0:
                            self._n_rows_dead_steps += dead
                            self._c_rows_dead_steps.inc(dead)
                        self._g_rows_occupied.set(
                            live / cfg.max_batch_size)
                        # occupancy-weighted TOKEN accounting
                        # (ISSUE 13): the formation-time padding_waste
                        # only prices the founders' grid; this prices
                        # what each executed step actually carried —
                        # live rows' real residues over the full (B, L)
                        # grid — so admitted rows (and the padding a
                        # cross-bucket admit accepts) are observable
                        self.metrics.record_step_occupancy(
                            sum(e.request.length for e in active),
                            cfg.max_batch_size * bucket_len)
                        if fetch_steps:
                            coords_np = np.asarray(state.coords)
                            conf_np = np.asarray(state.confidence)
                            if row_isolate and \
                                    self._scan_nonfinite_rows(
                                        active, rows, ages, coords_np,
                                        conf_np):
                                # per-step non-finite scan (ISSUE 14):
                                # a poisoned row retires the moment its
                                # output goes non-finite — its batch
                                # mates keep stepping and its freed row
                                # is admissible like any early exit
                                any_nonfinite = True
                                if not active:
                                    break
                            self._stream_progress(active, rows,
                                                  coords_np, conf_np,
                                                  ages)
                        else:
                            # fetchless policy: a snapshot fetched for
                            # an earlier retirement is one step stale
                            # NOW — the ripe pass below must re-fetch,
                            # never serve a surviving row its previous
                            # iteration's state
                            coords_np = conf_np = None
                        # retirement against each row's OWN age:
                        # full-depth rows are final (their state IS the
                        # fold result); converged rows past their
                        # min_recycles floor retire early. A full-depth
                        # row never counts as an early retirement even
                        # if its last delta also converged.
                        ripe = {i for i in range(len(active))
                                if ages[i] >= num_recycles}
                        conv: List[int] = []
                        if policy.converge_tol > 0 \
                                and prev_coords is not None:
                            elig = [i for i in range(len(active))
                                    if i not in ripe
                                    and ages[i] >= policy.min_recycles]
                            if elig:
                                deltas = element_deltas(
                                    prev_coords, prev_conf, coords_np,
                                    conf_np,
                                    [active[i].request.length
                                     for i in elig],
                                    rows=[rows[i] for i in elig])
                                for i, d in zip(elig, deltas):
                                    if d <= policy.converge_tol:
                                        conv.append(i)
                                        active[i].trace.event(
                                            "recycle_converged",
                                            recycle=ages[i], delta=d)
                        retired = sorted(ripe | set(conv))
                        if retired:
                            if coords_np is None:
                                # fetchless policy retiring full-depth
                                # rows: one fetch, exactly like the
                                # opaque path's end
                                coords_np = np.asarray(state.coords)
                                conf_np = np.asarray(state.confidence)
                            now = time.monotonic()
                            for i in retired:
                                e = active[i]
                                if i not in ripe:
                                    self._n_retired_early += 1
                                if not self._retire_entry(
                                        e, bucket_len,
                                        coords_np[rows[i]],
                                        conf_np[rows[i]],
                                        ages[i], now):
                                    any_nonfinite = True
                            gone = set(retired)
                            keep = [i for i in range(len(active))
                                    if i not in gone]
                            active = [active[i] for i in keep]
                            rows = [rows[i] for i in keep]
                            ages = [ages[i] for i in keep]
                            if not active:
                                if r < num_recycles:
                                    # fully-converged batch: remaining
                                    # steps are skipped outright
                                    skipped = steps_saved(num_recycles,
                                                          r)
                                    self._n_recycles_skipped += skipped
                                    self._c_recycles_skipped.inc(
                                        skipped)
                                break
                            if can_repack:
                                # re-pack the survivor batch: survivors
                                # become a dense row prefix of both the
                                # carried state and the batch tensors
                                # (and the executor's placement cache
                                # is dropped with the old batch dict)
                                state, idx_list = repack_rows(
                                    state, rows, cfg.max_batch_size)
                                batch = repack_batch(batch, idx_list)
                                sel = np.asarray(rows)
                                coords_np, conf_np = coords_np[sel], \
                                    conf_np[sel]
                                rows = list(range(len(active)))
                            # (not can_repack: rows retire in place —
                            # the position -> row map already shrank
                            # above)
                        # preemption reclaim (ISSUE 20): when the
                        # announced grace window cannot fit this
                        # loop's remaining recycles, spilling NOW
                        # beats finishing never — checkpoint every
                        # row, resolve "preempted" (the checkpoints
                        # survive _resolve_entry for adoption), and
                        # leave the loop
                        if active and self._reclaiming \
                                and not self._reclaim_fits(
                                    bucket_len, ages, num_recycles):
                            self._preempt_spill_loop(
                                bucket_len, state, active, rows,
                                ages, all_members)
                            if not active:
                                break
                        # bulk yield (ISSUE 18): under online burn,
                        # bulk rows checkpoint-and-yield at this gap —
                        # spill to the durable store, requeue as
                        # resumable, free the row for the online
                        # admission right below
                        if self._bulk_queue is not None and active \
                                and self._ckpt_store is not None \
                                and self._bulk_gated():
                            self._yield_bulk_rows(state, active, rows,
                                                  ages, all_members)
                        admitted = []
                        if continuous and active:
                            if lease is None:
                                # inline path: this IS the worker
                                # thread, and a continuously refilled
                                # loop would keep it here indefinitely
                                # — drain fresh submissions and run the
                                # worker's shed sweep from the gap so
                                # expired tickets (which admission
                                # skips by design) never hang behind a
                                # long-lived loop
                                with self._cond:
                                    while self._incoming:
                                        e_in = self._incoming.popleft()
                                        self._pending.setdefault(
                                            e_in.bucket_len,
                                            []).append(e_in)
                                self._shed_expired()
                            batch, state, admitted = self._admit_rows(
                                bucket_len, batch, state, active, rows,
                                ages, all_members, devices, mesh_shape,
                                inline=lease is None, gap=r,
                                kernel=kspec)
                            if admitted and contact_planned:
                                # admitted rows' first pass just landed
                                # in the distogram: re-plan so the mask
                                # covers THEIR contacts too, not just
                                # the founders'. A FAILED re-plan keeps
                                # the current contact spec (still valid
                                # for survivor rows) rather than
                                # silently widening back to the static
                                # mask while the batch stays accounted
                                # as contact-planned.
                                new_kernel, ok = _plan_contact(state,
                                                               admitted)
                                if ok:
                                    step_kernel = new_kernel
                            if admitted and fetch_steps:
                                # refresh the prev snapshot NOW: an
                                # admitted row's first delta must
                                # compare its own post-init state,
                                # never the pre-admission occupant of
                                # the same physical row
                                coords_np = np.asarray(state.coords)
                                conf_np = np.asarray(state.confidence)
                                self._stream_progress(
                                    admitted, rows[-len(admitted):],
                                    coords_np, conf_np,
                                    [0] * len(admitted))
                        if ckpt_every and active and \
                                (admitted or self._draining
                                 or r % ckpt_every == 0):
                            # cadence checkpoints, plus one at every
                            # admission gap: a resume must never
                            # restore a pre-admission carry out from
                            # under rows that now hold admitted work
                            # (a failed checkpoint keeps the previous
                            # one — resume then requeues the admitted
                            # entries as orphans, losing progress but
                            # never tickets). While DRAINING, every
                            # gap checkpoints: with a spill store on,
                            # drain() leaves the freshest possible
                            # resume point for whoever inherits the
                            # fold (ISSUE 18)

                            ckpt = self._checkpoint_loop(
                                state, batch, active, rows, ages, r,
                                step_kernel) or ckpt
                    break     # loop drained clean: leave the envelope
                except Exception as exc:
                    scrubbed = self._isolate_poison_rows(
                        exc, batch, active, rows, ages)
                    if scrubbed is not None:
                        # the failed attempt never executed: undo its
                        # step count (unless the step had completed and
                        # a post-step site raised) and retry with the
                        # offending rows retired + scrubbed from the
                        # batch tensors. The checkpoint must follow the
                        # scrub, or a later resume would restore the
                        # poison and re-raise forever.
                        batch = scrubbed
                        r = max(0, r - (0 if step_done else 1))
                        step_done = True
                        if ckpt_every and active:
                            ckpt = self._checkpoint_loop(
                                state, batch, active, rows, ages, r,
                                step_kernel) or ckpt
                        continue
                    outcome = self._resume_or_requeue(
                        exc, ckpt, all_members, bucket_len, resumes,
                        r - (0 if step_done else 1), t_attempt)
                    if outcome is None:
                        raise     # classic handler (outer except)
                    kind, payload = outcome
                    if kind == "requeued":
                        return    # survivors re-enter via the queue
                    resumes += 1
                    resume_probe = self._breaker is not None
                    (state, batch, active, rows, ages,
                     step_kernel) = payload
                    r = ckpt.step
                    step_done = True
                    coords_np = conf_np = None
                    if fetch_steps:
                        coords_np = np.asarray(state.coords)
                        conf_np = np.asarray(state.confidence)
            if active:
                # only reachable at num_recycles == 0: the init state
                # is the final state for every founder row
                if coords_np is None:
                    coords_np = np.asarray(state.coords)
                    conf_np = np.asarray(state.confidence)
                now = time.monotonic()
                for i, e in enumerate(active):
                    if not self._retire_entry(e, bucket_len,
                                              coords_np[rows[i]],
                                              conf_np[rows[i]],
                                              ages[i], now):
                        any_nonfinite = True
        except Exception as exc:  # resolve/retry, never kill the caller
            survivors = [e for e in all_members if not e.ticket.done()]
            if not survivors:
                return            # everyone already retired
            if self._handle_batch_failure(bucket_len, survivors, exc,
                                          t0):
                return            # retried, bisected, or quarantined
            self.metrics.record_error(len(survivors))
            for e in survivors:
                self._resolve_entry(e, FoldResponse(
                    request_id=e.request.request_id, status="error",
                    bucket_len=e.bucket_len, error=repr(exc),
                    attempts=e.attempts))
            return
        self._finish_step_batch(bucket_len, entries, all_members, lease,
                                kspec, contact_planned, any_nonfinite,
                                waste, t0)

    def _finish_step_batch(self, bucket_len: int, entries: List[_Entry],
                           all_members: List[_Entry],
                           lease: Optional[SliceLease], kspec,
                           contact_planned: bool, any_nonfinite: bool,
                           waste: float, t0: float):
        """Success-path accounting for one completed step loop (breaker
        health, mesh/kernel counters, the batch JSONL record) — shared
        by the normal drain and the all-founders-isolated early exit."""
        cfg = self.config
        if self._breaker is not None:
            # same device-health semantics as the opaque path: a batch
            # with non-finite rows is suspect, a clean one is proof
            (self._breaker.record_failure if any_nonfinite
             else self._breaker.record_success)()
        if lease is not None:
            self._c_mesh_folds.inc(mesh=lease.label)
        self._record_kernel_batch(bucket_len, kspec, len(all_members),
                                  contact=contact_planned)
        with self._cond:
            if lease is not None:
                self._mesh_batches[lease.label] = \
                    self._mesh_batches.get(lease.label, 0) + 1
                self._mesh_served[lease.label] = \
                    self._mesh_served.get(lease.label, 0) \
                    + len(all_members)
            depth = self._depth
        try:
            # founders only: padding_waste is a batch-FORMATION metric
            # (real tokens vs the padded grid minted at assemble time);
            # row admissions reuse that grid over time and are
            # accounted by the rows-occupied ledger instead — counting
            # their tokens here would drive waste negative
            self.metrics.record_batch(
                bucket_len, cfg.max_batch_size, len(entries),
                sum(e.request.length for e in entries), waste,
                time.monotonic() - t0, depth,
                cache_store=(None if self.cache is None
                             else self.cache.snapshot()))
        except Exception:
            pass              # observability never takes down serving

    # -- continuous batching: mid-recycle row admission (ISSUE 11) ------

    def _take_admission_candidate(self, bucket_len: int,
                                  batch_msa_depth: int
                                  ) -> Optional[_Entry]:
        """Thread-safe pop of the best same-bucket admission candidate
        from the pending queue, in deadline/priority order (tightest
        live deadline first — urgent folds claim freed rows without
        needing a preemption gap — then priority, then FIFO). Runs on
        dispatch-pool threads, which is why every `_pending` touch in
        this scheduler now holds `_cond`. Excluded: bisection isolation
        groups (cohort discipline wins), backoff-gated retries, expired
        deadlines (the worker's sweep must shed them — admission must
        never ride a dead request to an after-deadline "ok"), and —
        under an unpinned msa_depth config — requests whose own MSA is
        deeper than the running batch's compiled depth (truncating it
        here would serve different content than its own batch would
        have)."""
        now = time.monotonic()
        with self._cond:
            if not self._running and not self._drain:
                return None    # stop(drain=False) cancels the queue;
                #                admission must not race entries away
            while self._incoming:
                entry = self._incoming.popleft()
                self._pending.setdefault(entry.bucket_len,
                                         []).append(entry)
            pend = self._pending.get(bucket_len)
            if not pend:
                return None
            best = None
            for e in pend:
                if e.group is not None or e.not_before > now:
                    continue
                if e.deadline is not None and e.deadline <= now:
                    continue
                if self.config.msa_depth is None \
                        and e.request.msa is not None \
                        and int(e.request.msa.shape[0]) \
                        > batch_msa_depth:
                    continue
                k = (e.deadline is None, e.deadline or 0.0,
                     -e.request.priority, e.enqueued_at)
                if best is None or k < best[0]:
                    best = (k, e)
            if best is None:
                return None
            entry = best[1]
            pend.remove(entry)
        self._resolve_removed([entry])
        return entry

    def _readmit_pending(self, bucket_len: int, entry: _Entry):
        """Return a taken-but-not-admitted candidate to the pending
        queue (HBM refusal): deadline clock untouched, normal batch
        formation serves it."""
        with self._cond:
            self._pending.setdefault(bucket_len, []).append(entry)
            self._depth += 1
            self._cond.notify_all()

    def _native_delay_s(self, native_bucket: int, now: float,
                        inline: bool, remaining_host_steps: int,
                        host_step_s: float) -> float:
        """Caller holds `_cond`. Projected seconds until
        `native_bucket`'s pending work folds through normal batch
        formation — the latency a cross-bucket admission buys back,
        and the number the AdmissionPricer weighs padded compute
        against. Three terms, max-combined:

        - the batch-formation window: time left until the bucket's
          oldest entry ages past max_wait (zero when the bucket
          already holds a full batch, or under eager formation);
        - inline loops: only this worker forms batches and IT is held
          by the running loop, so the loop's remaining steps gate
          everything (this term is why inline cross-bucket admission
          prices favorably exactly when the native alternative would
          wait out the whole drain anyway);
        - leased loops: when no slice of the native shape is free, the
          soonest capacity we can PROVE will free is this loop's own
          slice at drain — the same remaining-steps bound (other
          leases may free sooner, but a lower bound here only makes
          the pricer conservative about stealing from a bucket that
          could form right now).
        """
        pend = self._pending.get(native_bucket) or []
        wait_left = 0.0
        if pend and len(pend) < self.config.max_batch_size \
                and not self._eager_form_on():
            oldest = min(e.enqueued_at for e in pend)
            wait_left = max(0.0, self.config.max_wait_ms / 1000.0
                            - (now - oldest))
        if inline:
            return max(wait_left, remaining_host_steps * host_step_s)
        if self._allocator is not None \
                and not self._allocator.can_allocate(
                    self.mesh_policy.shape_for(native_bucket)):
            return max(wait_left, remaining_host_steps * host_step_s)
        return wait_left

    def _cross_admissible(self, e: _Entry, host_bucket: int,
                          batch_msa_depth: int, now: float) -> bool:
        """THE cross-bucket admissibility predicate — ONE copy shared
        by the inline yield gate and `_take_cross_candidate`'s scan so
        they can never drift: an entry the take would skip (bisection
        group, backoff-gated retry, pad-frac guard, MSA deeper than
        the batch, already pricer-refused) must make the gate YIELD
        the worker, or it would starve behind a loop that keeps
        refilling past it. `cross_refused` is one-shot on purpose: a
        refusal commits the entry to the drain + native-formation
        fallback (and bounds the refusal counter at one per entry)
        rather than re-pricing it every gap."""
        return (e.group is None and e.not_before <= now
                and not e.cross_refused
                and 1.0 - e.request.length / float(host_bucket)
                <= self.recycle_policy.cross_bucket_max_pad_frac
                and not (self.config.msa_depth is None
                         and e.request.msa is not None
                         and int(e.request.msa.shape[0])
                         > batch_msa_depth))

    def _take_cross_candidate(self, host_bucket: int,
                              batch_msa_depth: int,
                              ages: List[int],
                              admitted_this_round: bool,
                              inline: bool):
        """Cross-bucket admission take (ISSUE 13): pop the best PRICED
        candidate from the SHORTER buckets' pending queues, or None.
        Candidates are considered in the same deadline/priority/FIFO
        order (and under the same eligibility rules) as the same-bucket
        take, across every bucket below the host's; each is priced by
        the AdmissionPricer against its own native-bucket delay
        projection, and refusals stay pending (normal formation — or a
        later, cheaper gap — serves them). Returns (entry, decision).
        """
        pricer = self._admission_pricer
        cfg = self.config
        now = time.monotonic()
        host_step_s = self._step_ewma.get(host_bucket, 0.0)
        num_recycles = cfg.num_recycles
        # steps the host loop still runs regardless of this admission:
        # a row admitted earlier this round restarts at age 0, so the
        # loop already owes the full depth and the candidate rides it
        # for free
        remaining = num_recycles if admitted_this_round else \
            max(0, num_recycles - (min(ages) if ages else 0))
        taken = None
        with self._cond:
            if not self._running and not self._drain:
                return None
            while self._incoming:
                entry = self._incoming.popleft()
                self._pending.setdefault(entry.bucket_len,
                                         []).append(entry)
            cands = []
            for native, pend in self._pending.items():
                if native >= host_bucket:
                    continue
                for e in pend:
                    # shared predicate with the inline yield gate
                    # (group/backoff/pad/MSA/one-shot refusal); the
                    # expired-deadline skip stays take-only — the
                    # worker's shed sweep owns those
                    if not self._cross_admissible(e, host_bucket,
                                                  batch_msa_depth, now):
                        continue
                    if e.deadline is not None and e.deadline <= now:
                        continue
                    k = (e.deadline is None, e.deadline or 0.0,
                         -e.request.priority, e.enqueued_at)
                    cands.append((k, e, native))
            cands.sort(key=lambda t: t[0])
            for _, e, native in cands:
                delay = self._native_delay_s(native, now, inline,
                                             remaining, host_step_s)
                slack = None if e.deadline is None \
                    else e.deadline - now
                decision = pricer.price(
                    native_len=native, host_len=host_bucket,
                    length=e.request.length,
                    batch_size=cfg.max_batch_size,
                    msa_depth=(cfg.msa_depth
                               if cfg.msa_depth is not None
                               else batch_msa_depth),
                    candidate_steps=num_recycles,
                    remaining_host_steps=remaining,
                    native_delay_s=delay, deadline_slack_s=slack,
                    host_step_s=host_step_s)
                if decision.admit:
                    self._pending[native].remove(e)
                    taken = (e, decision)
                    break
                e.cross_refused = True
                self._n_cross_refusals += 1
                e.trace.event("cross_bucket_refused",
                              host_bucket=host_bucket,
                              reason=decision.reason,
                              pad_frac=round(decision.pad_frac, 4))
        if taken is None:
            return None
        self._resolve_removed([taken[0]])
        return taken

    def _admitted_batch(self, batch: dict, bucket_len: int,
                        placements: List[Tuple[int, _Entry]]) -> dict:
        """Fresh batch dict with each admitted request written into its
        freed physical row — the same per-row padding/truncation
        semantics as bucketing.assemble (zero-pad, mask real residues,
        keep the first `depth` MSA rows). A fresh dict holding only the
        canonical input keys (+ the host mirror) on purpose: the
        executor's cached device placement is row-stale the moment a
        row's content changes (same discipline as repack_batch).

        The "_host" key carries the numpy mirror of the batch tensors
        across admission rounds: the FIRST admission of a loop pays one
        device->host fetch, every later one only rewrites the admitted
        rows and re-uploads — no per-gap device sync inside the hot
        step loop. Device arrays are built with `jnp.array` (copy
        semantics), so mutating the mirror next round can never alias
        an array the executor still holds."""
        host = self._host_mirror(batch)
        seq, mask = host["seq"], host["mask"]
        msa, msa_mask = host["msa"], host["msa_mask"]
        for row, e in placements:
            req = e.request
            n = req.length
            seq[row] = 0
            seq[row, :n] = req.seq
            mask[row] = False
            mask[row, :n] = True
            if msa is not None:
                msa[row] = 0
                msa_mask[row] = False
                if req.msa is not None:
                    m = min(req.msa.shape[0], msa.shape[1])
                    msa[row, :m, :n] = req.msa[:m]
                    msa_mask[row, :m, :n] = True
        return self._batch_from_host(host)

    @staticmethod
    def _host_mirror(batch: dict) -> dict:
        """The numpy mirror of one assembled batch's canonical input
        keys: the cached "_host" copy when an earlier admission/scrub/
        checkpoint already paid the device fetch, else one fresh fetch
        cached onto the batch dict — cadence checkpoints of a loop
        whose batch never changes pay ONE fetch per loop, not one per
        checkpoint. Safe to cache: the device tensors are immutable
        between loop iterations (admission/scrub/repack all mint a
        FRESH batch dict), and checkpoint snapshots copy the mirror
        before storing it."""
        host = batch.get("_host")
        if host is None:
            host = {k: (None if batch[k] is None else np.array(batch[k]))
                    for k in ("seq", "mask", "msa", "msa_mask")}
            batch["_host"] = host
        return host

    @staticmethod
    def _batch_from_host(host: dict) -> dict:
        """Fresh device batch dict from a host mirror — only the
        canonical input keys plus the mirror itself, so the executor's
        cached per-slice placement is dropped (same discipline as
        repack_batch). `jnp.array` copies, so later mirror mutation
        never aliases device arrays the executor still holds."""
        import jax.numpy as jnp

        return {"seq": jnp.array(host["seq"]),
                "mask": jnp.array(host["mask"]),
                "msa": (None if host["msa"] is None
                        else jnp.array(host["msa"])),
                "msa_mask": (None if host["msa_mask"] is None
                             else jnp.array(host["msa_mask"])),
                "_host": host}

    def _scrub_batch_rows(self, batch: dict, scrub_rows) -> dict:
        """Zero out the named physical rows (seq 0, mask False, MSA
        cleared) and rebuild the batch dict: a content-addressed
        deterministic failure (poison) cannot re-fire off a row whose
        content is gone, and a dead row is exactly what continuous
        admission refills (ISSUE 14 row isolation)."""
        host = self._host_mirror(batch)
        for row in scrub_rows:
            host["seq"][row] = 0
            host["mask"][row] = False
            if host["msa"] is not None:
                host["msa"][row] = 0
                host["msa_mask"][row] = False
        return self._batch_from_host(host)

    def _admit_rows(self, bucket_len: int, batch: dict, state,
                    active: List[_Entry], rows: List[int],
                    ages: List[int], all_members: List[_Entry],
                    devices, mesh_shape, inline: bool, gap: int,
                    kernel=None):
        """Refill free batch rows mid-recycle (continuous batching,
        ISSUE 11). Candidates come off the pending queue in deadline/
        priority order and pass the same front submit() runs: a result-
        store hit resolves immediately (source "cache") WITHOUT burning
        a row, an in-flight duplicate parks as a coalescing follower
        (never double-folds — its leader's fold populates the store
        under the policy's own `key_extras` keying and settles it), and
        the HBM admission guard prices the request before it may join
        the resident batch. Surviving candidates are written into freed
        physical rows (the position->row map — no physical repack, so
        the same code path serves single-chip and mesh-sharded
        carries) and initialized by the row-masked `init_rows`
        executable under an `admit` span while survivor rows pass
        through untouched.

        `inline` marks the classic no-lease path, where this loop runs
        ON the scheduler worker thread: sustained same-bucket traffic
        could then refill the loop forever while every other bucket
        starves behind it, so inline admission additionally yields —
        stops admitting, letting the loop drain within num_recycles
        steps — as soon as any OTHER bucket holds work past its
        max_wait window that admission itself cannot serve (under a
        cross-bucket policy a shorter bucket's overdue entry that the
        cross take will reach this gap no longer forces the yield —
        see the gate comment below). Mesh-leased loops run on pool
        threads and leave the worker free, so they never need the
        gate.

        With a CROSS-BUCKET policy (ISSUE 13), a round whose host
        queue is dry falls through to `_take_cross_candidate`:
        pending requests from SHORTER buckets may ride the freed rows
        at the host shape, priced per admit.

        Mutates active/rows/ages/all_members in place for the admitted
        entries; returns (batch, state, admitted)."""
        cfg = self.config
        if self._reclaiming:
            # reclaim mode (ISSUE 20): stop admitting rows — a row
            # admitted now restarts at recycle 0 inside a process that
            # is about to die; the pending entry is worth more resolved
            # "preempted" so its caller re-folds on a survivor
            return batch, state, []
        occupied = set(rows)
        free = [k for k in range(cfg.max_batch_size)
                if k not in occupied]
        if not free:
            return batch, state, []
        # an open circuit breaker pauses batch formation; admission
        # must honor the same pause (mirrors _maybe_preempt)
        if self._breaker is not None \
                and not self._breaker.allow_execute():
            return batch, state, []
        depth = 0 if batch.get("msa") is None \
            else int(batch["msa"].shape[1])
        if inline:
            now = time.monotonic()
            cross = self._use_cross_bucket()
            with self._cond:
                # cross-bucket admission (ISSUE 13) can serve a SHORTER
                # bucket's overdue entry right here in the loop, so it
                # no longer forces the yield — but ONLY when the cross
                # take will actually reach it this gap: the host
                # bucket's own queue must be dry (same-bucket
                # candidates fill rows first — with host pending the
                # gate bails exactly like PR 11, so sustained
                # same-bucket traffic can never starve other buckets)
                # and the entry must pass THE SAME `_cross_admissible`
                # predicate the take's scan applies (bisection group,
                # backoff gate, pad-frac guard, MSA depth, one-shot
                # pricer refusal) — an entry the take would skip must
                # force the yield, or it starves behind a loop that
                # keeps refilling past it. (A take-eligible entry
                # outranked gap after gap by tighter-deadline cross
                # candidates follows the system-wide deadline-first
                # discipline, same as everywhere else work queues.)
                host_pending = bool(self._pending.get(bucket_len))
                for other, pend in self._pending.items():
                    if other == bucket_len:
                        continue
                    for e in pend:
                        if (now - e.enqueued_at) * 1000.0 \
                                < cfg.max_wait_ms:
                            continue
                        servable = (cross and not host_pending
                                    and other < bucket_len
                                    and self._cross_admissible(
                                        e, bucket_len, depth, now))
                        if not servable:
                            # only this worker can serve it: stop
                            # refilling so the loop ends and the worker
                            # gets back to _form_batch
                            return batch, state, []
        placements: List[Tuple[int, _Entry]] = []
        cross_admits: List[_Entry] = []
        while free:
            decision = None
            e = self._take_admission_candidate(bucket_len, depth)
            if e is None and self._use_cross_bucket():
                # this bucket's own queue is dry but rows are still
                # free: a pending request from a SHORTER bucket may
                # ride them at the host shape — if the pricer says the
                # padding beats its native-bucket queue delay
                # (ISSUE 13)
                taken = self._take_cross_candidate(
                    bucket_len, depth, ages, bool(placements), inline)
                if taken is not None:
                    e, decision = taken
            if e is None and self._bulk_queue is not None:
                # bulk work-stealing (ISSUE 18): every online take —
                # same-bucket and cross-bucket — came up empty, so a
                # freed row may carry the lowest QoS class (gated by
                # online burn rate inside the take)
                e = self._take_bulk_candidate(bucket_len, depth)
            if e is None:
                break
            # HBM guard, mirroring submit() but RE-PRICED AT THE HOST
            # SHAPE (a cross-bucket candidate joins the host batch's
            # footprint, not its native bucket's): an unpinned
            # msa_depth prices the request's own depth. The policy (or
            # its budget) may have tightened since this entry passed
            # the door — a refused candidate goes back to its NATIVE
            # pending queue (normal formation serves it) and the round
            # stops (its siblings would refuse identically).
            if self.mesh_policy is not None:
                guard_msa = cfg.msa_depth
                if guard_msa is None:
                    guard_msa = 0 if e.request.msa is None \
                        else int(e.request.msa.shape[0])
                if not self.mesh_policy.admits(
                        bucket_len, cfg.max_batch_size, guard_msa,
                        carry_recyclables=True, continuous=True):
                    e.trace.event("row_admission_refused_hbm",
                                  gap=gap, host_bucket=bucket_len)
                    self._readmit_pending(e.bucket_len, e)
                    break
            key = None
            if self.cache is not None:
                key = self._entry_key(e)
            if key is not None:
                try:
                    cached = self.cache.get(key, trace=e.trace)
                except Exception:
                    cached = None
                if cached is not None:
                    # a store hit never burns a row: another batch (or
                    # a peer) finished this key since submit
                    self.metrics.record_cache_hit()
                    e.trace.end("queue")
                    resp = FoldResponse(
                        request_id=e.request.request_id, status="ok",
                        coords=cached.coords.copy(),
                        confidence=cached.confidence.copy(),
                        # the entry's NATIVE bucket (== the loop's for
                        # same-bucket candidates; a cross-bucket one
                        # must not report the host's)
                        bucket_len=e.bucket_len,
                        latency_s=time.monotonic() - e.enqueued_at,
                        source="cache")
                    e.resolve(resp)
                    self._settle_followers(e, resp)
                    continue
                self.metrics.record_cache_miss()
                if e.cache_key is None:
                    # not a coalescing leader (the saturated block-mode
                    # fall-through, or a cache attached after submit):
                    # an in-flight duplicate must park behind its
                    # leader, never double-fold in an admitted row
                    def _trace_parked(leader, e=e):
                        if leader is not None:
                            e.trace.link(leader.trace.trace_id)
                        e.trace.event("coalesced")
                        e.trace.end("queue")
                        e.trace.begin("parked")

                    if self._inflight.attach_follower(
                            key, e, on_follower=_trace_parked):
                        self.metrics.record_coalesced()
                        continue
            placements.append((free.pop(0), e))
            if decision is not None:
                cross_admits.append(e)
                e.trace.event("cross_bucket_admitted",
                              native_bucket=e.bucket_len,
                              host_bucket=bucket_len,
                              reason=decision.reason,
                              pad_frac=round(decision.pad_frac, 4))
        if not placements:
            return batch, state, []
        admitted = [e for _, e in placements]
        if self.tracer.enabled:
            for e in admitted:
                e.trace.end("queue", bucket_len=bucket_len)
                e.trace.end("retry")   # no-op on a first execution
        for e in admitted:
            e.attempts += 1
        # bookkeeping BEFORE the executor call: if init_rows fails, the
        # batch-failure handler must already own these tickets
        for row, e in placements:
            active.append(e)
            rows.append(row)
            ages.append(0)
            e.trace.event("row_admitted", gap=gap, row=row,
                          native_bucket=e.bucket_len)
            # per-admit pad fraction at the host edge: the padding an
            # admission accepted in exchange for a live row (ISSUE 13;
            # same-bucket admits land in the low bins, cross-bucket
            # ones are the distribution's whole point)
            self.metrics.record_admit(
                1.0 - e.request.length / float(bucket_len))
        all_members.extend(admitted)
        self._n_row_admissions += len(admitted)
        self._c_row_admissions.inc(len(admitted))
        if cross_admits:
            self._n_cross_admissions += len(cross_admits)
            for e in cross_admits:
                self._c_cross_admissions.inc(
                    host_bucket=str(bucket_len),
                    native_bucket=str(e.bucket_len))
        new_batch = self._admitted_batch(batch, bucket_len, placements)
        row_mask = np.zeros((cfg.max_batch_size,), bool)
        for row, _ in placements:
            row_mask[row] = True
        admit_trace = (MultiTrace([e.trace for e in admitted])
                       if self.tracer.enabled else NULL_TRACE)
        # admission runs the bucket's STATIC first-pass spec (the one
        # warmup pre-compiled) — a contact-planned step spec describes
        # the founders' contacts, not a newly admitted target's
        admit_kw = {} if kernel is None else {"kernel": kernel}
        if self._use_cross_bucket():
            # admit spans tagged with the admitted rows' native buckets
            # (ISSUE 13 obs): only under a cross-bucket policy, where
            # the executor is known to speak the kwarg (custom stubs
            # without it keep working under plain continuous)
            admit_kw["span_attrs"] = {
                "host_bucket": bucket_len,
                "native_bucket": ",".join(
                    str(b) for b in sorted({e.bucket_len
                                            for e in admitted}))}
        while True:
            try:
                state = self._run_step_guarded(
                    lambda: self.executor.run_init_rows(
                        new_batch, state, row_mask, trace=admit_trace,
                        devices=devices, mesh_shape=mesh_shape,
                        **admit_kw))
                break
            except Exception as exc:
                # per-row poison isolation at the ADMISSION pass
                # (ISSUE 14): a poison request admitted mid-loop fails
                # the row-masked init deterministically with its row
                # attributed — quarantine and retire exactly that row,
                # scrub it, and re-run the init for the remaining
                # admitted rows (survivor rows pass through untouched
                # either way; innocent admitted rows re-init from the
                # same deterministic first pass). Anything else
                # propagates to the loop's fault envelope.
                scrubbed = self._isolate_poison_rows(
                    exc, new_batch, active, rows, ages)
                if scrubbed is None:
                    raise
                new_batch = scrubbed
                placements = [(row, e) for row, e in placements
                              if not e.ticket.done()]
                admitted = [e for _, e in placements]
                if not placements:
                    # every admitted row was poison: the carried state
                    # is untouched — the loop continues with survivors
                    return new_batch, state, []
                row_mask = np.zeros((cfg.max_batch_size,), bool)
                for row, _ in placements:
                    row_mask[row] = True
        # durable resume (ISSUE 18): an admitted entry may be a fold
        # some dead replica (or this one's previous life, or a yielded
        # bulk loop) already carried to age N — consult the spill
        # store and continue it there instead of from the init state
        if self._ckpt_store is not None and admitted:
            adm = {id(e) for e in admitted}
            state = self._resume_from_spill(
                state, active, rows, ages,
                [i for i, e in enumerate(active) if id(e) in adm])
        return new_batch, state, admitted

    def _retire_entry(self, e: _Entry, bucket_len: int, coords_row,
                      conf_row, recycles: int, now: float) -> bool:
        """Terminal "ok" resolution for one step-loop element at
        `recycles` executed iterations (early-converged or final).
        Returns False when the output failed non-finite validation
        (the entry then went through _resolve_nonfinite instead).
        Metrics and the response report the entry's own NATIVE bucket
        (`e.bucket_len`) — identical to the loop's `bucket_len` for
        every founder and same-bucket admit, but a CROSS-bucket
        admitted fold (ISSUE 13) must land in its native bucket's
        latency histogram, or the short-fold p99 the feature exists to
        improve would be invisible (filed under the host bucket)."""
        n = e.request.length
        if self.retry is not None and not (
                np.isfinite(coords_row[:n]).all()
                and np.isfinite(conf_row[:n]).all()):
            self._resolve_nonfinite(e, e.bucket_len)
            return False
        coords = coords_row[:n].copy()
        confidence = conf_row[:n].copy()
        if self.recycle_policy.stream:
            # the update that retired the element: same arrays its
            # terminal response carries, flagged converged
            try:
                e.ticket._publish_progress(FoldProgress(
                    e.request.request_id, recycles, coords.copy(),
                    confidence.copy(), converged=True))
            except Exception:
                pass
        latency = now - e.enqueued_at
        self.metrics.record_served(e.bucket_len, latency)
        self._resolve_entry(e, FoldResponse(
            request_id=e.request.request_id, status="ok",
            coords=coords, confidence=confidence,
            bucket_len=e.bucket_len, latency_s=latency,
            attempts=e.attempts, recycles=recycles))
        return True

    def _stream_progress(self, active: List[_Entry],
                         rows: List[int], coords_np, conf_np,
                         recycles):
        """Publish one per-recycle progressive update to every active
        element's ticket (RecyclePolicy(stream=True) only). `rows`
        maps each active position to its batch row; `recycles` is the
        per-position OWN recycle index list (ages — an admitted row
        streams from 0 while its batch mates stream their own depth),
        or one shared int for legacy callers."""
        if not self.recycle_policy.stream:
            return
        validate = self.retry is not None
        per_row = isinstance(recycles, (list, tuple))
        for i, e in enumerate(active):
            n = e.request.length
            try:
                coords = coords_np[rows[i], :n]
                conf = conf_np[rows[i], :n]
                if validate and not (np.isfinite(coords).all()
                                     and np.isfinite(conf).all()):
                    # the terminal path refuses to serve non-finite
                    # output as "ok"; a progressive update must not
                    # leak the same garbage to a streaming client
                    continue
                e.ticket._publish_progress(FoldProgress(
                    e.request.request_id,
                    recycles[i] if per_row else recycles,
                    coords.copy(), conf.copy()))
            except Exception:
                pass          # a broken observer never stalls the loop

    def _run_step_guarded(self, call):
        """One init/step executor call under the optional per-batch
        watchdog — each recycle step is its own watchdog window, which
        is exactly the granularity the step loop buys."""
        watchdog_s = None if self.retry is None else self.retry.watchdog_s
        if watchdog_s is None:
            return call()
        return run_with_watchdog(call, watchdog_s)

    # -- step-loop fault domains (ISSUE 14) ------------------------------

    def _checkpoint_loop(self, state, batch, active, rows, ages,
                         step: int, kernel) -> Optional[_StepCheckpoint]:
        """Snapshot the running loop to host memory: the carry (with
        shardings, so a mesh-sharded state re-uploads onto its slice),
        a COPY of the batch host mirror (later admission rounds mutate
        the live one in place), and the membership/row/age triple.
        Snapshot trouble returns None — checkpointing is a recovery
        optimization and must never fail a healthy loop; the caller
        keeps the previous checkpoint."""
        from alphafold2_tpu.predict import snapshot_step_state

        try:
            host = self._host_mirror(batch)
            snap_host = {k: (None if v is None else np.array(v))
                         for k, v in host.items()}
            snap_state = snapshot_step_state(state)
        except Exception:
            return None
        self._n_checkpoints += 1
        if self._ckpt_store is not None:
            self._spill_rows(snap_state, active, rows, ages)
        return _StepCheckpoint(snap_state, snap_host, list(rows),
                               list(ages), list(active), int(step),
                               kernel)

    def _spill_rows(self, snap_state, active: List[_Entry],
                    rows: List[int], ages: List[int]):
        """Durable spill (ISSUE 18): every in-memory checkpoint also
        writes each row's slice of the snapshot to the CheckpointStore
        keyed by (fold_key, model_tag, age) — one npz per row, so a
        single fold migrates without its batch mates. Rides the
        snapshot `_checkpoint_loop` already paid for; per-row trouble
        (unkeyable request, unsliceable carry, disk errors) skips that
        row, never the loop — the store counts it."""
        store = self._ckpt_store
        from alphafold2_tpu.cache.checkpoints import row_checkpoint
        for i, e in enumerate(active):
            key = self._entry_key(e)
            if key is None:
                continue
            try:
                ck = row_checkpoint(
                    snap_state, rows[i], fold_key=key,
                    model_tag=self.model_tag, age=ages[i],
                    seq=e.request.seq, msa=e.request.msa)
            except ValueError:
                store.stats.bump("spill_errors")
                continue
            if store.put_row(ck) is not None:
                e.trace.event("checkpoint_spilled", recycle=ages[i])

    def _resume_from_spill(self, state, active: List[_Entry],
                           rows: List[int], ages: List[int],
                           positions):
        """Durable resume (ISSUE 18): consult the CheckpointStore for
        each just-initialized position; on a validated hit, overwrite
        that row's slice of every carry leaf with the spilled one and
        set its age — `.at[row].set` of the stored values does no
        arithmetic, so the continued loop is byte-equal to the
        uninterrupted one. ANY validation trouble (leaf count, shape,
        dtype, reference drift, a different sequence under a colliding
        key) discards the checkpoint and keeps age 0: refold-from-zero
        is always the safe fallback. Mutates ages in place; returns
        the (possibly updated) state."""
        store = self._ckpt_store
        import jax
        import jax.numpy as jnp
        leaves = treedef = None
        for i in positions:
            e = active[i]
            key = self._entry_key(e)
            if key is None:
                continue
            ckpt = store.latest(key, trace=e.trace)
            if ckpt is None:
                continue
            if not (ckpt.seq.shape == e.request.seq.shape
                    and bool(np.array_equal(ckpt.seq, e.request.seq))
                    and 0 < ckpt.age < self.config.num_recycles):
                store.discard(key)
                continue
            try:
                restored = ckpt.restore_leaves()
                if leaves is None:
                    leaves, treedef = jax.tree_util.tree_flatten(state)
                if len(restored) != len(leaves):
                    raise ValueError("carry leaf count drifted")
                row = rows[i]
                new_leaves = list(leaves)
                for j, new in enumerate(restored):
                    cur = leaves[j]
                    if isinstance(cur, jax.Array):
                        arr = jnp.asarray(new)
                        if arr.shape[1:] != cur.shape[1:] \
                                or arr.dtype != cur.dtype:
                            raise ValueError(
                                f"carry leaf {j} shape/dtype drifted")
                        new_leaves[j] = cur.at[row].set(arr[0])
                    elif new != cur:
                        raise ValueError(
                            f"reference leaf {j} drifted")
                leaves = new_leaves
            except Exception:
                store.discard(key)
                continue
            ages[i] = int(ckpt.age)
            self._n_spill_resumes += 1
            self._c_spill_resumes.inc()
            e.trace.event("spill_resume", recycle=ckpt.age)
        if leaves is not None:
            state = jax.tree_util.tree_unflatten(treedef, leaves)
        return state

    def _scan_nonfinite_rows(self, active: List[_Entry],
                             rows: List[int], ages: List[int],
                             coords_np, conf_np) -> int:
        """Per-step non-finite scan (RetryPolicy(row_isolation)): any
        active row whose real residues carry non-finite coords or
        confidence is retired NOW through the existing poison-strike
        machinery (`_resolve_nonfinite` — quarantine at the policy
        threshold) while its batch mates keep stepping. Mutates
        active/rows/ages in place; returns the number of rows
        isolated. Without the knob this never runs and detection stays
        at retirement time, exactly the PR-5/11 behavior."""
        bad = []
        for i in range(len(active)):
            n = active[i].request.length
            if not (np.isfinite(coords_np[rows[i], :n]).all()
                    and np.isfinite(conf_np[rows[i], :n]).all()):
                bad.append(i)
        if not bad:
            return 0
        for i in bad:
            e = active[i]
            self._n_row_isolations += 1
            self._c_row_isolations.inc()
            e.trace.event("row_poison_isolated", kind="nonfinite",
                          row=rows[i], recycle=ages[i])
            self._resolve_nonfinite(e, e.bucket_len)
        gone = set(bad)
        keep = [i for i in range(len(active)) if i not in gone]
        active[:] = [active[i] for i in keep]
        rows[:] = [rows[i] for i in keep]
        ages[:] = [ages[i] for i in keep]
        return len(bad)

    def _isolate_poison_rows(self, exc: Exception, batch: dict,
                             active: List[_Entry], rows: List[int],
                             ages: List[int]) -> Optional[dict]:
        """Per-row poison isolation for a row-attributed DETERMINISTIC
        failure (ISSUE 14): when the exception names the batch rows it
        came from (`exc.rows` — content-addressed chaos does; real XLA
        payloads go through the `serve.xla_errors` attribution parser,
        ISSUE 20, and fall back to bisection only when the message
        names no row), quarantine exactly
        those entries (a deterministic single-row attribution IS the
        proof — same standard as the batch-of-1 bisection terminal),
        resolve them "poisoned", scrub their rows from the batch
        tensors, and return the scrubbed batch for the caller to retry
        the step with — the survivors never leave the loop. Returns
        None when not applicable (knob off, transient, unattributed,
        or the rows don't map to live entries)."""
        retry = self.retry
        if retry is None or not getattr(retry, "row_isolation", False):
            return None
        bad_rows = getattr(exc, "rows", None)
        if not bad_rows and getattr(retry, "xla_classify", False):
            # real XLA payloads carry no .rows — fall back to parsing
            # the message for a named batch position (ISSUE 20); ()
            # keeps the legacy bisection path
            from alphafold2_tpu.serve.xla_errors import attributed_rows
            bad_rows = attributed_rows(repr(exc)) or None
        if not bad_rows or retry.is_transient(exc):
            return None
        bad = {int(x) for x in bad_rows}
        positions = [i for i in range(len(active)) if rows[i] in bad]
        if not positions:
            return None
        now = time.monotonic()
        for i in positions:
            e = active[i]
            key = self._entry_key(e)
            if key is not None:
                self._quarantine.add(key, reason="poison_input")
            self._n_row_isolations += 1
            self._c_row_isolations.inc()
            self.metrics.record_poisoned()
            e.trace.event("row_poison_isolated", kind="raise",
                          row=rows[i], recycle=ages[i])
            self._resolve_entry(e, FoldResponse(
                request_id=e.request.request_id, status="poisoned",
                bucket_len=e.bucket_len, attempts=e.attempts,
                latency_s=now - e.enqueued_at,
                error=f"poison_input: row-attributed deterministic "
                      f"failure isolated to batch row {rows[i]}, key "
                      f"quarantined: {exc!r}"))
        scrub = [rows[i] for i in positions]
        gone = set(positions)
        keep = [i for i in range(len(active)) if i not in gone]
        active[:] = [active[i] for i in keep]
        rows[:] = [rows[i] for i in keep]
        ages[:] = [ages[i] for i in keep]
        if self._breaker is not None:
            # deterministic failure: the device RAN the batch — proof
            # of health, same semantics as the bisection path
            self._breaker.record_success()
        return self._scrub_batch_rows(batch, scrub)

    def _note_watchdog(self, entries: List[_Entry], t_run: float,
                       now: float):
        """Watchdog-fire bookkeeping shared by the classic batch
        handler and the checkpoint-resume path: count it, span it,
        rebuild the executor (a hung device call's compiled state is
        not trustworthy)."""
        self._n_watchdog_fires += 1
        self._c_watchdog.inc()
        if self.tracer.enabled:
            for e in entries:
                e.trace.add_span("watchdog", t_run, now,
                                 timeout_s=self.retry.watchdog_s)
                e.trace.event("watchdog_fired")
        self._rebuild_executor()

    def _resume_or_requeue(self, exc: Exception,
                           ckpt: Optional[_StepCheckpoint],
                           all_members: List[_Entry], bucket_len: int,
                           resumes: int, completed: int,
                           t_attempt: float):
        """Recovery decision for one TRANSIENT step-loop failure under
        carry checkpointing (ISSUE 14). Three outcomes:

        - None: not applicable (knob off, no checkpoint yet,
          deterministic failure, resume budget spent, stopping, or the
          breaker is already open) — the caller re-raises into the
          classic handler, byte-for-byte the PR-5 recovery;
        - ("resumed", (state, batch, active, rows, ages, kernel)): the
          checkpoint re-uploaded; survivors continue at their
          checkpointed ages (bounded progress loss — the steps between
          checkpoint and failure, counted in
          `serve_recycles_lost_total`). Entries that joined the loop
          AFTER the checkpoint (admission raced the failure) re-enter
          via the queue so no ticket is ever lost. On a watchdog fire
          the executor was rebuilt first.
        - ("requeued", None): the checkpoint could not be restored (or
          the rebuilt executor lost step mode) — survivors took the
          classic requeue-to-zero path right here; the caller just
          returns.
        """
        retry = self.retry
        if retry is None or ckpt is None \
                or not getattr(retry, "checkpoint_every", 0):
            return None
        if not retry.is_transient(exc):
            return None
        if resumes + 1 >= retry.max_attempts:
            return None          # budget spent: classic handler
        with self._cond:
            if not self._running:
                return None      # stopping: every ticket resolves now
        if self._breaker is not None \
                and not self._breaker.allow_execute():
            return None          # open breaker: honor the pause via
        #                          the requeue path's formation gate
        keep = [i for i in range(len(ckpt.active))
                if not ckpt.active[i].ticket.done()]
        if not keep:
            return None
        survivors = [ckpt.active[i] for i in keep]
        now = time.monotonic()
        fired = isinstance(exc, WatchdogTimeout)
        # `completed` = step iterations that finished before the
        # failure (the caller subtracts the in-flight attempt when the
        # step itself raised); everything past the checkpoint re-runs
        lost = max(0, int(completed) - ckpt.step)
        if fired:
            # a hung device call's compiled state is not trustworthy —
            # rebuild BEFORE the restore below touches the device:
            # uploading the checkpoint through the wedged client would
            # re-create the very hang the watchdog just recovered from,
            # this time outside its guard (restore_step_state's
            # default-device fallback expects the post-rebuild world)
            self._note_watchdog(survivors, t_attempt, now)
            if not self._step_capable:
                # the rebuilt executor lost step mode (custom factory):
                # requeue-to-zero over EVERY unresolved member — with
                # the classic path's exhaustion split, since
                # _handle_batch_failure can't run (it would rebuild and
                # count this watchdog a second time)
                if self._breaker is not None:
                    self._breaker.record_failure()
                self._requeue_or_exhaust(
                    bucket_len,
                    [e for e in all_members if not e.ticket.done()],
                    exc, now)
                return ("requeued", None)
        from alphafold2_tpu.predict import restore_step_state
        try:
            res_trace = (MultiTrace([e.trace for e in survivors])
                         if self.tracer.enabled else NULL_TRACE)
            with res_trace.span("resume", recycle=ckpt.step, lost=lost,
                                attempt=resumes + 1):
                state = restore_step_state(ckpt.state)
                host = {k: (None if v is None else np.array(v))
                        for k, v in ckpt.host.items()}
                batch = self._batch_from_host(host)
        except Exception:
            if fired:
                # the watchdog is already counted and the executor
                # rebuilt: the classic handler would do both a second
                # time, so the requeue-to-zero fallback runs here
                if self._breaker is not None:
                    self._breaker.record_failure()
                self._requeue_or_exhaust(
                    bucket_len,
                    [e for e in all_members if not e.ticket.done()],
                    exc, now)
                return ("requeued", None)
            # restore trouble with nothing counted yet: hand the
            # UNTOUCHED exception to the classic handler — it owns the
            # breaker/exhaustion bookkeeping of the requeue-to-zero
            # path, and nothing double-counts
            return None
        # committed: the classic handler will never see this failure,
        # so the breaker must learn about it HERE — a resume recovers
        # progress, it must not blind degraded-mode detection (same
        # transient-indicts / deterministic-never semantics as
        # _handle_batch_failure; the resumed loop's first successful
        # step records the offsetting success)
        if self._breaker is not None:
            self._breaker.record_failure()
        # entries that joined after the checkpoint (a raced admission)
        # are not in the restored membership: requeue them — progress
        # lost, tickets never — and DROP them from the loop membership,
        # so a later failure of this same loop can never requeue them a
        # second time (a double queue reference would double-serve one
        # ticket)
        ids = {id(e) for e in survivors}
        orphans = [e for e in all_members
                   if not e.ticket.done() and id(e) not in ids]
        if orphans:
            gone = {id(e) for e in orphans}
            for e in orphans:
                e.trace.event("resume_orphan_requeued")
            self._requeue(orphans, bucket_len, now)
            all_members[:] = [e for e in all_members
                              if id(e) not in gone]
        for e in survivors:
            e.attempts += 1
            e.trace.event("checkpoint_resume", recycle=ckpt.step,
                          lost=lost, error=repr(exc))
        self._n_ckpt_resumes += 1
        self._c_ckpt_resumes.inc()
        if lost:
            self._n_recycles_lost += lost
            self._c_recycles_lost.inc(lost)
        self.metrics.record_retried(len(survivors))
        if self._breaker is not None:
            self._breaker.begin_probe()   # no-op unless half-open: the
        #                                   resumed loop IS the probe
        delay = retry.delay_s(resumes + 1, rng=self._retry_rng)
        if delay > 0:
            # known trade: on a leased slice this backoff idles the
            # held chips for up to backoff_max_s — still strictly
            # cheaper than the classic path's full restart-from-zero,
            # and bounded by the per-loop resume budget
            time.sleep(delay)
        return ("resumed", (state, batch, survivors,
                            [ckpt.rows[i] for i in keep],
                            [ckpt.ages[i] for i in keep], ckpt.kernel))

    def _maybe_preempt(self, active: List[_Entry],
                       lease: Optional[SliceLease], gap: int,
                       bucket_len: Optional[int] = None):
        """Between-recycles preemption window. Inline (no lease): this
        IS the worker thread, so it forms and executes tighter-deadline
        pending batches directly — the deadline fold lands between the
        long batch's recycles instead of behind its last one. On a
        leased slice (dispatch-pool thread): when tighter-deadline work
        is pending and the device pool is saturated, release the slice
        for one gap so the worker can place the urgent batch, then
        blocking-re-acquire the SAME span (the carried state and the
        compiled executables are bound to those exact devices).
        A preemptor never preempts (per-thread guard) and each gap
        admits AT MOST ONE urgent batch, so preemption is bounded in
        both depth and breadth — sustained deadline traffic interleaves
        gap by gap instead of starving the running batch. Returns the
        (possibly re-acquired) lease.

        The yield frees SCHEDULING capacity, not device memory — the
        suspended loop's carried state stays HBM-resident, so an
        urgent batch on the freed chips is a concurrent per-device
        peak. `_preempt_hbm_admits` (memory-aware preemption
        admission, ISSUE 10) prices urgent footprint + suspended carry
        against the budget and REFUSES the yield when they cannot
        co-reside (`serve_preempt_hbm_refusals_total`) — near-limit
        flagship configs keep their headroom automatically. Known
        limit: a leased yield for an urgent entry still inside its
        max_wait window can go unplaced for that window (bounded by
        max_wait_ms — the worker's batch formation does not jump the
        window the way the inline take does)."""
        if getattr(self._preempting, "flag", False):
            return lease
        # an open circuit breaker pauses batch formation; a preemption
        # gap must honor the same pause, not hammer the suspect
        # executor with urgent batches during its recovery window
        if self._breaker is not None and not self._breaker.allow_execute():
            return lease
        deadlines = [e.deadline for e in active if e.deadline is not None]
        tighter_than = min(deadlines) if deadlines else None
        if lease is None:
            # ONE urgent batch per gap (same bound as the leased path's
            # one-gap yield): each recycle step opens another gap, so a
            # burst of deadline traffic interleaves with the running
            # batch instead of starving it outright — sustained urgent
            # arrivals must not pin a half-executed batch at one gap
            # while its callers' result timeouts expire
            cand = self._take_urgent(tighter_than)
            if cand is None:
                return lease
            bucket2, take2 = cand
            self._n_preemptions += 1
            self._c_preemptions.inc()
            for e in active:
                e.trace.event("preempted", gap=gap,
                              by_bucket=bucket2)
            for e in take2:
                e.trace.event("preempting", gap=gap)
            self._preempting.flag = True
            try:
                self._execute(bucket2, take2)
            finally:
                self._preempting.flag = False
            return lease
        with self._cond:
            urgent = self._pending_tightest
            needed = self._pending_tightest_chips
            urgent_bucket = self._pending_tightest_bucket
            urgent_msa = self._pending_tightest_msa
        if urgent is None or (tighter_than is not None
                              and urgent >= tighter_than):
            return lease
        if self._allocator.can_allocate((1, 1)):
            return lease      # free chips exist; nothing is starved
        if needed is not None:
            free = (self._allocator.total_devices
                    - self._allocator.busy_devices)
            if free + chips_of(lease.shape) < needed:
                # yielding our slice still cannot place the urgent
                # batch (it needs a wider slice than would free):
                # don't pay the yield latency or count a preemption
                # that admits nothing
                return lease
        if not self._preempt_hbm_admits(bucket_len, urgent_bucket,
                                        urgent_msa):
            # memory-aware preemption admission (ISSUE 10, closing the
            # PR-9 known limit): the yield frees SCHEDULING capacity,
            # not HBM — this loop's carried Recyclables stay resident
            # on these exact devices while the urgent batch runs, so
            # the pair is a concurrent per-device peak. When urgent
            # footprint + suspended carry exceeds the budget, refuse
            # the yield: the urgent batch waits out the remaining
            # recycles instead of OOMing both workloads.
            self._n_preempt_hbm_refusals += 1
            self._c_preempt_hbm_refusals.inc()
            for e in active:
                e.trace.event("preempt_hbm_refused", gap=gap)
            return lease
        self._n_preemptions += 1
        self._c_preemptions.inc()
        for e in active:
            e.trace.event("preempted", gap=gap)
        self._release_lease(lease)
        # one gap's window for the worker to place the urgent batch
        time.sleep(max(self.config.poll_ms / 1000.0 * 2, 0.01))
        lease = self._allocator.acquire_span(lease)
        self._set_busy_gauge()
        return lease

    def _preempt_hbm_admits(self, running_bucket: Optional[int],
                            urgent_bucket: Optional[int],
                            urgent_msa: Optional[int] = None) -> bool:
        """Memory-aware preemption admission: may the urgent bucket's
        batch run on devices still holding this suspended loop's
        carried state? Prices the urgent batch's full analytic
        footprint (step-mode, since the preempting batch runs under the
        same policy) PLUS the suspended carry's per-device bytes
        (`FoldMemoryModel.carry_bytes`) against the per-device budget.
        Conservative: assumes the urgent slice overlaps this lease's
        devices (the freed chips are exactly where the worker will
        place it under saturation — the only condition a yield fires
        in). True when no memory model is configured (the guard is
        opt-in, like the too_large guard it extends)."""
        mp = self.mesh_policy
        if mp is None or mp.memory is None or urgent_bucket is None \
                or running_bucket is None:
            return True
        cfg = self.config
        # MSA pricing mirrors the submit-time guard: a pinned
        # config.msa_depth wins; unpinned (None) prices the urgent
        # entry's OWN depth (advertised by the worker alongside its
        # bucket) — pricing zero there would lowball deep-MSA traffic
        # into exactly the concurrent-peak OOM this guard prevents
        guard_msa = cfg.msa_depth
        if guard_msa is None:
            guard_msa = urgent_msa or 0
        urgent_bytes = mp.memory.fold_bytes(
            urgent_bucket, cfg.max_batch_size, guard_msa,
            shape=mp.shape_for(urgent_bucket),
            carry_recyclables=self._use_step_loop(),
            continuous=self._use_continuous())
        carry = mp.memory.carry_bytes(
            running_bucket, cfg.max_batch_size,
            chips=mp.chips_for(running_bucket))
        return urgent_bytes + carry <= mp.memory.hbm_bytes_per_device

    @staticmethod
    def _urgent_eligible(e: _Entry, now: float) -> bool:
        """THE preemption-eligibility predicate: carries a live
        (unexpired) deadline, is not backoff-gated, and is not part of
        a bisection isolation group (cohort discipline wins). One copy,
        shared by the urgent take and the worker's tightest-deadline
        advertisement so they can never drift."""
        return (e.deadline is not None and e.deadline > now
                and e.group is None and e.not_before <= now)

    def _take_urgent(self, tighter_than: Optional[float]):
        """Worker-thread only (the inline preemption path): pick the
        pending bucket holding the tightest not-yet-expired deadline
        beating `tighter_than` (any deadline qualifies when the running
        batch has none) and take up to max_batch_size of its entries,
        tightest deadlines first. Bisection isolation groups never ride
        a preemption batch — their cohort discipline wins."""
        now = time.monotonic()
        # one _cond hold end to end: continuous row admission (pool
        # threads) takes from _pending too, so scan + removal must be
        # atomic against it
        with self._cond:
            while self._incoming:
                entry = self._incoming.popleft()
                self._pending.setdefault(entry.bucket_len,
                                         []).append(entry)
            best = None
            for bucket_len, pend in self._pending.items():
                for e in pend:
                    if not self._urgent_eligible(e, now):
                        continue
                    if tighter_than is not None \
                            and e.deadline >= tighter_than:
                        continue
                    if best is None or e.deadline < best[0]:
                        best = (e.deadline, bucket_len)
            if best is None:
                return None
            _, bucket_len = best
            # batch fill excludes expired deadlines too: a dead request
            # must resolve "shed" via the worker's sweep, never ride a
            # preemption batch to an after-deadline "ok" (deadline-free
            # fill entries are fine — they just serve sooner)
            pend = [e for e in self._pending[bucket_len]
                    if e.group is None and e.not_before <= now
                    and not (e.deadline is not None and e.deadline <= now)]
            take = sorted(pend, key=lambda e: (e.deadline is None,
                                               e.deadline or 0.0,
                                               -e.request.priority,
                                               e.enqueued_at))
            take = take[:self.config.max_batch_size]
            taken = {id(e) for e in take}
            self._pending[bucket_len] = [
                e for e in self._pending[bucket_len]
                if id(e) not in taken]
        self._resolve_removed(take)
        return bucket_len, take

    # -- resilience: worker side -----------------------------------------

    def _run_executor(self, batch: dict, batch_trace,
                      lease: Optional[SliceLease] = None, kernel=None):
        """executor.run with the optional per-batch watchdog deadline.
        The trace/devices/kernel kwargs are only passed when in use, so
        alternate executors (tests) needn't know about obs, meshes, or
        kernel policies; `self.executor` is read inside the closure so
        a rebuild between batches takes effect immediately."""
        kw = {}
        if batch_trace is not NULL_TRACE:
            kw["trace"] = batch_trace
        if lease is not None:
            kw["devices"] = lease.devices
            kw["mesh_shape"] = lease.shape
        if kernel is not None:
            kw["kernel"] = kernel
        if kw:
            call = lambda: self.executor.run(  # noqa: E731
                batch, self.config.num_recycles, **kw)
        else:
            call = lambda: self.executor.run(  # noqa: E731
                batch, self.config.num_recycles)
        watchdog_s = None if self.retry is None else self.retry.watchdog_s
        if watchdog_s is None:
            return call()
        return run_with_watchdog(call, watchdog_s)

    def _requeue_or_exhaust(self, bucket_len: int,
                            entries: List[_Entry], exc: Exception,
                            now: float):
        """The transient requeue-to-zero tail shared by the classic
        handler and the checkpoint-resume fallback: entries past their
        retry budget error-resolve with `retry_exhausted`, the rest
        re-enqueue with backoff and the usual retry bookkeeping."""
        retry = self.retry
        survivors = [e for e in entries
                     if e.attempts < retry.max_attempts]
        for e in entries:
            if e.attempts >= retry.max_attempts:
                self.metrics.record_error()
                self._resolve_entry(e, FoldResponse(
                    request_id=e.request.request_id, status="error",
                    bucket_len=bucket_len, attempts=e.attempts,
                    error=f"retry_exhausted after {e.attempts} "
                          f"attempts: {exc!r}"))
        if survivors:
            delay = retry.delay_s(max(e.attempts for e in survivors),
                                  rng=self._retry_rng)
            self._n_retries += len(survivors)
            self._c_retries.inc(len(survivors))
            self.metrics.record_retried(len(survivors))
            for e in survivors:
                e.trace.event("retry_scheduled", delay_s=delay,
                              attempts=e.attempts, error=repr(exc))
            self._requeue(survivors, bucket_len, now + delay)

    def _handle_batch_failure(self, bucket_len: int,
                              entries: List[_Entry], exc: Exception,
                              t_run: float) -> bool:
        """Failure-domain triage for one failed batch execution. True =
        handled (entries retried, bisected, or quarantined); False =
        the caller error-resolves everyone, exactly the pre-resilience
        path. Never called with entries still in the queue."""
        retry = self.retry
        if retry is None:
            return False
        now = time.monotonic()
        fired = isinstance(exc, WatchdogTimeout)
        if fired:
            self._note_watchdog(entries, t_run, now)
        transient = retry.is_transient(exc)
        if self._breaker is not None:
            # a deterministic failure proves the device RAN the batch:
            # only transient/watchdog failures indict the executor
            (self._breaker.record_failure if transient
             else self._breaker.record_success)()
        with self._cond:
            if not self._running:
                return False     # stopping: every ticket resolves NOW
        if transient:
            exhausted = [e for e in entries
                         if e.attempts >= retry.max_attempts]
            if exhausted and retry.bisect and len(entries) > 1:
                # a batch that keeps failing "transiently" is
                # indistinguishable from poison — corner it, but KEEP
                # the backoff: if the device really is struggling,
                # bisection must not turn into a zero-delay hammer
                delay = retry.delay_s(max(e.attempts for e in entries),
                                      rng=self._retry_rng)
                self._bisect(bucket_len, entries,
                             not_before=now + delay)
                return True
            self._requeue_or_exhaust(bucket_len, entries, exc, now)
            return True
        # deterministic failure: isolate the poison
        if not retry.bisect:
            return False
        if len(entries) == 1:
            e = entries[0]
            key = self._entry_key(e)
            if key is None:
                return False     # unkeyable: plain terminal error
            self._quarantine.add(key, reason="poison_input")
            self.metrics.record_poisoned()
            e.trace.event("poison_quarantined")
            self._resolve_entry(e, FoldResponse(
                request_id=e.request.request_id, status="poisoned",
                bucket_len=bucket_len, attempts=e.attempts,
                latency_s=now - e.enqueued_at,
                error=f"poison_input: failed deterministically as a "
                      f"batch of 1, key quarantined: {exc!r}"))
            return True
        self._bisect(bucket_len, entries)
        return True

    def _bisect(self, bucket_len: int, entries: List[_Entry],
                not_before: Optional[float] = None):
        """Split a failing batch into two isolation groups and re-run
        each alone: the innocent half succeeds immediately, the poison
        half keeps splitting — a single poison request is cornered and
        quarantined in <= log2(batch) extra executions. Default no
        backoff (a deterministic failure is not load); the transient-
        exhausted path passes `not_before` to keep its backoff."""
        self._n_bisections += 1
        self._c_bisections.inc()
        if not_before is None:
            not_before = time.monotonic()
        mid = len(entries) // 2
        for half in (entries[:mid], entries[mid:]):
            if not half:
                continue
            gid = next(self._group_counter)
            for e in half:
                e.group = gid
                e.trace.event("bisect", group=gid, size=len(half))
            self._requeue(half, bucket_len, not_before)

    def _requeue(self, entries: List[_Entry], bucket_len: int,
                 not_before: float):
        """Put failed entries back in pending for another execution.
        Deadlines and enqueued_at are NOT reset — the caller's clock
        kept running through the failure, and an entry whose deadline
        expires mid-backoff is shed like any other."""
        tracing = self.tracer.enabled
        for e in entries:
            e.not_before = not_before
            if tracing:
                e.trace.begin("retry")
        # through _incoming, NOT _pending: with a mesh policy this runs
        # on a dispatch-pool thread while the worker owns _pending; the
        # worker moves incoming entries into their bucket under _cond,
        # so the requeue is race-free on both paths
        with self._cond:
            self._incoming.extend(entries)
            self._depth += len(entries)
            self._cond.notify_all()

    def _rebuild_executor(self):
        """Watchdog fired: swap the executor for a fresh one. The hung
        call's thread still references the old instance, so its late
        result (if the device ever answers) lands in garbage, never in
        the serving path."""
        try:
            if self.executor_factory is not None:
                self.executor = self.executor_factory()
                if hasattr(self.executor, "model_tag"):
                    self.executor.model_tag = self._model_tag
            elif hasattr(self.executor, "rebuild"):
                self.executor = self.executor.rebuild()
            else:
                return           # nothing to rebuild with: keep serving
        except Exception:
            return               # a failed rebuild keeps the old one —
        #                          better a suspect executor than none
        # a swapped-in executor may not speak step mode (custom
        # executor_factory): recompute so the recycle loop degrades to
        # the opaque path instead of AttributeError-ing mid-batch
        self._step_capable = hasattr(self.executor, "run_init") \
            and hasattr(self.executor, "run_step")
        self._n_rebuilds += 1
        self._c_rebuilds.inc()

    def _resolve_nonfinite(self, e: _Entry, bucket_len: int):
        """A fold came back with non-finite coords/confidence: never
        serve it as "ok". The entry's key takes a poison strike; at the
        policy threshold it is quarantined (status "poisoned"),
        otherwise it error-resolves with `nonfinite_output`."""
        self._n_nonfinite += 1
        self._c_nonfinite.inc()
        e.trace.event("nonfinite_output")
        key = self._entry_key(e)
        quarantined = key is not None and self._quarantine.strike(
            key, self.retry.nan_poison_threshold)
        now = time.monotonic()
        if quarantined:
            self.metrics.record_poisoned()
            self._resolve_entry(e, FoldResponse(
                request_id=e.request.request_id, status="poisoned",
                bucket_len=bucket_len, attempts=e.attempts,
                latency_s=now - e.enqueued_at,
                error="nonfinite_output: fold produced non-finite "
                      "coords/confidence; key quarantined"))
        else:
            self.metrics.record_error()
            self._resolve_entry(e, FoldResponse(
                request_id=e.request.request_id, status="error",
                bucket_len=bucket_len, attempts=e.attempts,
                latency_s=now - e.enqueued_at,
                error="nonfinite_output: fold produced non-finite "
                      "coords/confidence"))

    def _drain_all_entries(self) -> List[_Entry]:
        with self._cond:
            leftovers = list(self._incoming)
            self._incoming.clear()
            for entries in self._pending.values():
                leftovers.extend(entries)
            self._pending.clear()
            self._depth -= len(leftovers)
            self._cond.notify_all()
        # bulk entries live outside _depth: drain them AFTER the depth
        # adjustment so the online accounting stays exact
        if self._bulk_queue is not None:
            leftovers.extend(self._bulk_queue.drain())
        return leftovers

    def _cancel_remaining(self):
        leftovers = self._drain_all_entries()
        if self._reclaiming:
            # reclaim stop (ISSUE 20): queued work that never founded
            # resolves "preempted" — a RETRIABLE terminal the fleet
            # client fails over on immediately, and whose spilled
            # checkpoint (for requeued mid-loop yields) survives for
            # the adopting replica to resume
            self.metrics.record_preempted(len(leftovers))
            for e in leftovers:
                self._resolve_entry(e, FoldResponse(
                    request_id=e.request.request_id, status="preempted",
                    bucket_len=e.bucket_len, attempts=e.attempts or 1,
                    error="replica preempted before folding"))
            return
        self.metrics.record_cancelled(len(leftovers))
        for e in leftovers:
            self._resolve_entry(e, FoldResponse(
                request_id=e.request.request_id, status="cancelled",
                bucket_len=e.bucket_len, attempts=e.attempts or 1,
                error="scheduler stopped without draining"))

    def _fail_outstanding(self, error: str):
        """Worker crashed outside executor.run (e.g. the metrics sink):
        stop accepting work and resolve every outstanding ticket as an
        error instead of leaving callers blocked forever."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        leftovers = self._drain_all_entries()
        self.metrics.record_error(len(leftovers))
        for e in leftovers:
            self._resolve_entry(e, FoldResponse(
                request_id=e.request.request_id, status="error",
                bucket_len=e.bucket_len, attempts=e.attempts or 1,
                error=f"scheduler worker crashed: {error}"))
